"""Client drivers — the reference's L6 layer (SURVEY §1): standalone
programs that exercise the service over gRPC.

  load_client   — doorder.go:18-60's randomized order blaster
  cancel_client — delorder.go:14-38's single cancel

Run as modules:  python -m gome_tpu.clients.doorder [host:port]
                 python -m gome_tpu.clients.delorder [host:port]
"""

from .doorder import load_client
from .delorder import cancel_client

__all__ = ["load_client", "cancel_client"]

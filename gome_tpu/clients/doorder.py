"""Load-test client — behavioral port of gomengine/doorder.go:18-60.

Fires n-1 randomized limit orders (the reference's loop is
`for i := 1; i < 2000` → 1,999 orders, doorder.go:37) at one symbol over
gRPC: random BUY/SALE, price and volume uniform in (0,1] rounded to 2
decimals (doorder.go:38-47's rand.Float64 + FloatRound(…, 2)), fixed
uuid="2", oid = loop index. Reports throughput the reference never measured
(SURVEY §6: baseline must be measured, not quoted).
"""

from __future__ import annotations

import collections
import random
import re
import time

import grpc

from ..api import order_pb2 as pb
from ..api.service import OrderStub
from ..utils.resilience import BackoffPolicy, backoff_delays

#: gateway retryable status (service.gateway.CODE_RETRYABLE): the
#: remainder was NOT accepted and a later retry should succeed.
CODE_RETRYABLE = 14

#: retry-after hint embedded in retryable reject messages by the
#: admission controller (service.admission.RETRY_AFTER_FMT).
RETRY_AFTER_RE = re.compile(r"retry-after=([0-9.]+)s")


def send_batch_retrying(
    send,
    orders: list,
    cancel: list | None = None,
    policy: BackoffPolicy | None = None,
    rng: random.Random | None = None,
    sleep=time.sleep,
) -> dict:
    """Submit one logical batch through `send(orders, cancel) -> resp`,
    retrying the unconsumed remainder whenever the gateway answers the
    retryable status (code 14: overloaded / degraded) instead of failing
    the batch outright.

    The consumed prefix of an aborted batch is exactly
    `resp.accepted + len(resp.reject_index)` (every entry before the
    abort point was either accepted or per-entry rejected — the
    gateway's remainder contract), so a retry resubmits only the tail:
    at-most-once per entry, no duplicates. Waits combine the server's
    parsed retry-after hint with decorrelated jitter from
    utils.resilience (`max(hint, jitter)` — the hint is a floor, the
    jitter de-synchronizes the retrying herd). A non-retryable code or
    an exhausted retry budget leaves the tail in `aborted`.

    Returns {ok, rejected, aborted, retries}."""
    policy = policy or BackoffPolicy()
    delays = backoff_delays(policy, rng or random.Random())
    ok = rejected = retries = aborted = 0
    while orders:
        resp = send(orders, cancel)
        consumed = resp.accepted + len(resp.reject_index)
        ok += resp.accepted
        rejected += len(resp.reject_index)
        if resp.code != CODE_RETRYABLE:
            # 0 = fully applied (consumed == len); 3 = permanent abort,
            # the tail is counted, never silently resubmitted.
            aborted += len(orders) - consumed
            break
        orders = orders[consumed:]
        if cancel:
            cancel = cancel[consumed:]
        if not orders:
            break
        m = RETRY_AFTER_RE.search(resp.message or "")
        hint = float(m.group(1)) if m else 0.0
        try:
            delay = next(delays)
        except StopIteration:  # retry budget exhausted — fail loudly
            aborted += len(orders)
            break
        retries += 1
        sleep(max(delay, hint))
    return {
        "ok": ok, "rejected": rejected, "aborted": aborted,
        "retries": retries,
    }


def load_client(
    target: str,
    n: int = 2000,
    symbol: str = "eth2usdt",
    uuid: str = "2",
    seed: int | None = None,
    kind: int = 0,
    concurrency: int = 1,
    symbols: list[str] | None = None,
    price_lo: float = 0.01,
    price_hi: float = 1.0,
    decimals: int = 2,
    batch_n: int = 0,
) -> dict:
    """Send n-1 orders (the reference's serial loop at concurrency=1; higher
    values pipeline that many in-flight requests over one HTTP/2 channel —
    the serial client measures round-trip latency, not server capacity).
    Defaults reproduce doorder.go:38-47 exactly; `symbols` (random pick per
    order) and the price band exist for sustained benches, where the
    reference's full-range prices would pile depth without crossing.
    batch_n > 0 switches to the amortized DoOrderBatch RPC with batch_n
    orders per request (still `concurrency` requests in flight) — the
    fast front door; the per-REQUEST grpc tax spreads over batch_n orders.
    Returns {sent, ok, rejected, elapsed_s, orders_per_s}."""
    rng = random.Random(seed)
    pick = symbols or [symbol]

    def requests():  # lazy: O(window) client memory at any n
        for i in range(1, n):  # doorder.go:37 loop bounds
            yield pb.OrderRequest(
                uuid=uuid,
                oid=str(i),
                symbol=pick[rng.randrange(len(pick))] if symbols else symbol,
                transaction=rng.randrange(2),  # doorder.go:39-44
                price=round(rng.uniform(price_lo, price_hi), decimals),
                volume=round(rng.uniform(0.01, 1.0), 2),
                kind=kind,
            )

    sent = ok = rejected = aborted = retried = 0
    window = max(1, concurrency)
    with grpc.insecure_channel(target) as channel:
        stub = OrderStub(channel)
        t0 = time.perf_counter()
        pending = collections.deque()
        if batch_n > 0:
            import itertools

            retry_rng = random.Random(seed)

            def send(orders, cancel):
                return stub.DoOrderBatch(pb.OrderBatchRequest(orders=orders))

            def settle(f, chunk):
                nonlocal ok, rejected, aborted, retried
                resp = f.result()
                ok += resp.accepted
                rejected += len(resp.reject_index)
                consumed = resp.accepted + len(resp.reject_index)
                if resp.code == CODE_RETRYABLE and consumed < len(chunk):
                    # Overloaded / degraded gateway: honor the retryable
                    # status — resubmit the unconsumed tail under
                    # decorrelated-jitter backoff (synchronously; the
                    # stall IS the backpressure reaching this client).
                    r = send_batch_retrying(
                        send, chunk[consumed:], rng=retry_rng
                    )
                    ok += r["ok"]
                    rejected += r["rejected"]
                    aborted += r["aborted"]
                    retried += r["retries"]
                    return
                # A code-3 mid-batch abort (batcher closed, bus down)
                # leaves a tail that was neither accepted nor
                # per-order-rejected; count it so sent == ok + rejected
                # + aborted always holds and failures surface HERE, not
                # as an opaque downstream count mismatch.
                aborted += len(chunk) - consumed

            reqs = requests()
            while True:
                chunk = list(itertools.islice(reqs, batch_n))
                if not chunk:
                    break
                if len(pending) >= window:
                    settle(*pending.popleft())
                pending.append(
                    (
                        stub.DoOrderBatch.future(
                            pb.OrderBatchRequest(orders=chunk)
                        ),
                        chunk,
                    )
                )
                sent += len(chunk)
            for f, chunk in pending:
                settle(f, chunk)
        else:
            # One loop for both unary modes: a window of 1 sends
            # request-after-response, exactly the reference's serial
            # client.
            def settle(f):
                nonlocal ok, rejected
                resp = f.result()
                ok += resp.code == 0
                rejected += resp.code != 0

            for req in requests():
                if len(pending) >= window:
                    settle(pending.popleft())
                pending.append(stub.DoOrder.future(req))
                sent += 1
            for f in pending:
                settle(f)
        elapsed = time.perf_counter() - t0
    return {
        "sent": sent,
        "ok": ok,
        "rejected": rejected,
        "aborted": aborted,  # batch entries lost to a mid-batch abort
        "retried": retried,  # code-14 retry rounds (backpressure honored)
        "elapsed_s": elapsed,
        "orders_per_s": sent / elapsed if elapsed > 0 else 0.0,
    }


def main(argv=None):
    import json
    import sys

    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else "127.0.0.1:8088"
    n = int(argv[1]) if len(argv) > 1 else 2000
    concurrency = int(argv[2]) if len(argv) > 2 else 1
    n_symbols = int(argv[3]) if len(argv) > 3 else 0
    kwargs = {}
    if n_symbols:
        kwargs["symbols"] = [f"sym{i}" for i in range(n_symbols)]
    if len(argv) > 4:  # crossing price band for sustained benches
        if len(argv) < 7:
            sys.exit(
                "usage: doorder TARGET [N [CONCURRENCY [N_SYMBOLS "
                "[PRICE_LO PRICE_HI DECIMALS [SEED]]]]]"
            )
        kwargs["price_lo"] = float(argv[4])
        kwargs["price_hi"] = float(argv[5])
        kwargs["decimals"] = int(argv[6])
    if len(argv) > 7:
        kwargs["seed"] = int(argv[7])
    if len(argv) > 8:  # orders per DoOrderBatch request (0 = unary)
        kwargs["batch_n"] = int(argv[8])
    if len(argv) > 9 and n_symbols:  # symbol-namespace prefix (scaling
        kwargs["symbols"] = [  # benches give each gateway its own)
            f"{argv[9]}sym{i}" for i in range(n_symbols)
        ]
    stats = load_client(target, n=n, concurrency=concurrency, **kwargs)
    print(json.dumps(stats))


if __name__ == "__main__":
    main()

"""Load-test client — behavioral port of gomengine/doorder.go:18-60.

Fires n-1 randomized limit orders (the reference's loop is
`for i := 1; i < 2000` → 1,999 orders, doorder.go:37) at one symbol over
gRPC: random BUY/SALE, price and volume uniform in (0,1] rounded to 2
decimals (doorder.go:38-47's rand.Float64 + FloatRound(…, 2)), fixed
uuid="2", oid = loop index. Reports throughput the reference never measured
(SURVEY §6: baseline must be measured, not quoted).
"""

from __future__ import annotations

import collections
import random
import time

import grpc

from ..api import order_pb2 as pb
from ..api.service import OrderStub


def load_client(
    target: str,
    n: int = 2000,
    symbol: str = "eth2usdt",
    uuid: str = "2",
    seed: int | None = None,
    kind: int = 0,
    concurrency: int = 1,
) -> dict:
    """Send n-1 orders (the reference's serial loop at concurrency=1; higher
    values pipeline that many in-flight requests over one HTTP/2 channel —
    the serial client measures round-trip latency, not server capacity).
    Returns {sent, ok, rejected, elapsed_s, orders_per_s}."""
    rng = random.Random(seed)

    def requests():  # lazy: O(window) client memory at any n
        for i in range(1, n):  # doorder.go:37 loop bounds
            yield pb.OrderRequest(
                uuid=uuid,
                oid=str(i),
                symbol=symbol,
                transaction=rng.randrange(2),  # doorder.go:39-44
                price=round(rng.uniform(0.01, 1.0), 2),
                volume=round(rng.uniform(0.01, 1.0), 2),
                kind=kind,
            )

    sent = ok = rejected = 0
    window = max(1, concurrency)
    with grpc.insecure_channel(target) as channel:
        stub = OrderStub(channel)
        t0 = time.perf_counter()
        # One loop for both modes: a window of 1 sends request-after-response,
        # exactly the reference's serial client.
        pending = collections.deque()

        def settle(f):
            nonlocal ok, rejected
            resp = f.result()
            ok += resp.code == 0
            rejected += resp.code != 0

        for req in requests():
            if len(pending) >= window:
                settle(pending.popleft())
            pending.append(stub.DoOrder.future(req))
            sent += 1
        for f in pending:
            settle(f)
        elapsed = time.perf_counter() - t0
    return {
        "sent": sent,
        "ok": ok,
        "rejected": rejected,
        "elapsed_s": elapsed,
        "orders_per_s": sent / elapsed if elapsed > 0 else 0.0,
    }


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else "127.0.0.1:8088"
    n = int(argv[1]) if len(argv) > 1 else 2000
    concurrency = int(argv[2]) if len(argv) > 2 else 1
    stats = load_client(target, n=n, concurrency=concurrency)
    print(
        f"sent={stats['sent']} ok={stats['ok']} rejected={stats['rejected']} "
        f"elapsed={stats['elapsed_s']:.2f}s rate={stats['orders_per_s']:.0f}/s"
    )


if __name__ == "__main__":
    main()

"""Cancel client — behavioral port of gomengine/delorder.go:14-38: one
DeleteOrder for a hardcoded order (uuid="2", oid="11", price=0.5,
delorder.go:30-36). The cancel contract requires the exact resting price
(SURVEY §2.3.2)."""

from __future__ import annotations

import grpc

from ..api import order_pb2 as pb
from ..api.service import OrderStub


def cancel_client(
    target: str,
    uuid: str = "2",
    oid: str = "11",
    symbol: str = "eth2usdt",
    transaction: int = 0,
    price: float = 0.5,
    volume: float = 1.0,
) -> pb.OrderResponse:
    with grpc.insecure_channel(target) as channel:
        stub = OrderStub(channel)
        return stub.DeleteOrder(
            pb.OrderRequest(
                uuid=uuid,
                oid=oid,
                symbol=symbol,
                transaction=transaction,
                price=price,
                volume=volume,
            )
        )


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else "127.0.0.1:8088"
    resp = cancel_client(target)
    print(f"code={resp.code} message={resp.message}")


if __name__ == "__main__":
    main()

"""Cancel client — behavioral port of gomengine/delorder.go:14-38: one
DeleteOrder for a hardcoded order (uuid="2", oid="11", price=0.5,
delorder.go:30-36). The cancel contract requires the exact resting price
(SURVEY §2.3.2). Retryable (code 14) responses — overloaded or degraded
gateway — are retried under decorrelated-jitter backoff like the load
client, honoring the server's retry-after hint."""

from __future__ import annotations

import random
import time

import grpc

from ..api import order_pb2 as pb
from ..api.service import OrderStub
from ..utils.resilience import BackoffPolicy, backoff_delays
from .doorder import CODE_RETRYABLE, RETRY_AFTER_RE


def cancel_client(
    target: str,
    uuid: str = "2",
    oid: str = "11",
    symbol: str = "eth2usdt",
    transaction: int = 0,
    price: float = 0.5,
    volume: float = 1.0,
    policy: BackoffPolicy | None = None,
    sleep=time.sleep,
) -> pb.OrderResponse:
    delays = backoff_delays(policy or BackoffPolicy(), random.Random())
    with grpc.insecure_channel(target) as channel:
        stub = OrderStub(channel)
        while True:
            resp = stub.DeleteOrder(
                pb.OrderRequest(
                    uuid=uuid,
                    oid=oid,
                    symbol=symbol,
                    transaction=transaction,
                    price=price,
                    volume=volume,
                )
            )
            if resp.code != CODE_RETRYABLE:
                return resp
            m = RETRY_AFTER_RE.search(resp.message or "")
            hint = float(m.group(1)) if m else 0.0
            try:
                delay = next(delays)
            except StopIteration:  # budget exhausted: surface the 14
                return resp
            sleep(max(delay, hint))


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else "127.0.0.1:8088"
    resp = cancel_client(target)
    print(f"code={resp.code} message={resp.message}")


if __name__ == "__main__":
    main()

"""Pallas TPU kernel for the batched match step (SURVEY §7 step 4).

What it buys over the XLA `scan x vmap` baseline (engine/batch.py): the scan
materializes the full book state — and every one of the ~60 elementwise
passes over it — to HBM on each of the T time steps. This kernel blocks the
symbol axis, loads one block's books into VMEM ONCE, applies all T ops with
the books resident on-chip, and writes the final state back once:
intermediate HBM traffic disappears and the T-step dependency chain runs
entirely out of VMEM.

Semantics are not re-implemented: the kernel body calls the SAME
`step_rows_impl` core the scan path's step_impl wraps, so the oracle-parity
tests that pin the step pin this kernel too. The kernel is pure data
movement + orchestration; matching math lives in exactly one place
(engine/step.py).

TPU layout discipline (Mosaic tiles the minor two dims as (8, 128) and only
allows unaligned dynamic offsets on the major dim):

  * book arrays ship as per-side [S, cap] rows (10 arrays) — the public
    [S, 2, cap] BookState is sliced/restacked OUTSIDE the kernel. A [2, cap]
    side axis inside would waste 4x on the size-2 sublane dim and need an
    offset-concat restack every step, which Mosaic cannot lower.
  * the 7 op fields ship packed in ONE [T, 8, S] int32 array (row 7 spare);
    each step reads the [8, B] slab at its (major-dim, unaligned-ok) time
    index and peels rows.
  * the 7 per-op scalar outputs come back the same way: one [T, 8, S] pack.
  * the 5 non-derivable per-op fill-record arrays come back time-leading as
    [T, K, S]; the step's [B, K] records are transposed in-VMEM so the lane
    dim stays the (dense) symbol block (fill_qty / taker_after are
    reconstructed outside the kernel — see _REC_FIELDS).
The host repacks to the public [S, T, ...] StepOutput shapes outside the
kernel — pure XLA transposes, off the hot dependency chain.

The compiled kernel is int32-only (Mosaic has no 64-bit lowering);
BookConfig dtype=int64 callers use the scan path. On TPU
`pallas_available()` gates the choice; everywhere else
`pallas_batch_step(..., interpret=True)` executes the same code path in
interpreter mode (used by the CPU test suite for parity).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..engine.book import BookConfig, BookState, DeviceOp, StepOutput
from ..engine.step import _Side, step_rows_impl

# Only the 5 non-derivable record fields cross the kernel boundary:
# fill_qty == maker_prefill - maker_remaining and taker_after ==
# taker volume - cumsum(fill_qty) are reconstructed outside (less VMEM,
# fewer in-kernel transposes, less HBM).
_REC_FIELDS = (
    "fill_price", "maker_oid", "maker_uid",
    "maker_prefill", "maker_remaining",
)
_SCALAR_FIELDS = (
    "n_fills", "fill_overflow", "taker_remaining", "rested",
    "book_overflow", "cancel_found", "cancel_volume",
)
_OP_FIELDS = ("action", "side", "is_market", "price", "volume", "oid", "uid")


def pallas_available(dtype=jnp.int32) -> bool:
    """True when the default backend can run the compiled kernel. Mosaic has
    no 64-bit vector lowering, so int64 books always take the scan path."""
    return jax.default_backend() == "tpu" and jnp.dtype(dtype).itemsize <= 4


def interpret_block_s(s: int) -> int:
    """Interpret-mode lane blocking (no Mosaic constraints): any divisor
    works; prefer the sublane width so CPU tests tile like the compiled
    kernel. The ONE policy for every interpret-mode caller."""
    return next(b for b in (8, 4, 2, 1) if s % b == 0)


def default_block_s(s: int, cap: int = 256) -> int | None:
    """The compiled kernel's lane-blocking policy, in ONE place: 128-lane
    blocks when the lane count divides, else one sublane-aligned whole-axis
    block (VMEM-bounded, so only for modest s; s % 8 != 0 hits unsupported
    Mosaic relayouts). Deep books shrink the block: the resident per-block
    book tiles are ~10 x block x 2*cap x 4 B, and Mosaic's scoped-VMEM
    stack is 16 MB — cap=1024 at block 128 is a compile-time VMEM OOM.
    None means no valid blocking — callers fall back to the scan path."""
    # Valid blockings are 128-multiples or the whole axis (Mosaic lane-dim
    # rule enforced in pallas_batch_step); within that, the book tile must
    # fit the scoped-VMEM stack (~16 MB total; the in/out aliased tiles
    # cost ~2x the nominal size, so budget the tile at 6 MB).
    tile = lambda b: 10 * b * 2 * cap * 4
    limit = 6 << 20
    if s % 128 == 0 and tile(128) <= limit:
        return 128
    if s <= 256 and s % 8 == 0 and tile(s) <= limit:
        return s
    return None


def _kernel(config: BookConfig, t_block: int, *refs):
    """refs: 12 book-in (5 buy rows, 5 sale rows, count, next_seq) +
    1 op-pack-in + 12 book-out + 5 record-out + 1 scalar-pack-out.
    See module docstring for layouts.

    The grid is (lane blocks, time blocks): the book blocks' index maps
    ignore the time-block index, so each lane block's books stay RESIDENT
    in VMEM across the whole time sweep (Pallas revisited-block semantics;
    time is the innermost grid dim), while op/record/scalar blocks page
    through t_block-deep windows — VMEM cost is O(t_block), not O(T), so
    a hot symbol can run thousands of ops deep in one kernel launch. At
    time block 0 the input books seed the output refs; afterwards the
    carry lives in the output refs."""
    (bb_p, bb_l, bb_s, bb_o, bb_u, sb_p, sb_l, sb_s, sb_o, sb_u,
     cnt, nsq, ops,
     ob_p, ob_l, ob_s, ob_o, ob_u, os_p, os_l, os_s, os_o, os_u,
     ocnt, onsq,
     fp, mo, mu, mp, mr, scal) = refs
    rec_refs = (fp, mo, mu, mp, mr)

    @pl.when(pl.program_id(1) == 0)
    def _seed():
        for dst, src in (
            (ob_p, bb_p), (ob_l, bb_l), (ob_s, bb_s), (ob_o, bb_o),
            (ob_u, bb_u), (os_p, sb_p), (os_l, sb_l), (os_s, sb_s),
            (os_o, sb_o), (os_u, sb_u), (ocnt, cnt), (onsq, nsq),
        ):
            dst[...] = src[...]

    buy = _Side(ob_p[...], ob_l[...], ob_s[...], ob_o[...], ob_u[...])
    sale = _Side(os_p[...], os_l[...], os_s[...], os_o[...], os_u[...])
    counts = ocnt[...]  # [B, 2]
    # Loop carries stay rank-2: Mosaic's layout inference crashes on rank-1
    # vectors carried through fori_loop (layout.h implicit-dim check); the
    # [B, 1] squeeze/unsqueeze inside the body is free.
    carry = (buy, sale, counts[:, 0:1], counts[:, 1:2], onsq[...])

    step = jax.vmap(
        lambda b, a, nb, ns, nq, o: step_rows_impl(config, b, a, nb, ns, nq, o)
    )

    def body(t, carry):
        buy, sale, nb, ns, nq = carry
        slab = ops[pl.ds(t, 1)][0]  # [8, B] in config.dtype
        # The pack rides in config.dtype (lossless for the value fields; the
        # three code fields are small ints) — casting the codes back to i32
        # keeps step semantics identical across dtypes.
        op = DeviceOp(
            **{
                f: (
                    slab[i].astype(jnp.int32)
                    if f in ("action", "side", "is_market")
                    else slab[i]
                )
                for i, f in enumerate(_OP_FIELDS)
            }
        )
        buy, sale, nb, ns, nq, out = step(
            buy, sale, nb[:, 0], ns[:, 0], nq[:, 0], op
        )
        # fill records: [B, K] -> transpose -> slot t of [T, K, B]
        for ref, f in zip(rec_refs, _REC_FIELDS):
            ref[pl.ds(t, 1)] = jnp.transpose(getattr(out, f))[None]
        # per-op scalars: one [8, B] slab (row 7 zero) in config.dtype, so
        # int64 taker_remaining/cancel_volume survive the pack intact
        dt = config.dtype
        s = jnp.stack(
            [getattr(out, f).astype(dt) for f in _SCALAR_FIELDS]
            + [jnp.zeros_like(out.n_fills).astype(dt)]
        )
        scal[pl.ds(t, 1)] = s[None]
        return buy, sale, nb[:, None], ns[:, None], nq[:, None]

    buy, sale, nb, ns, nq = jax.lax.fori_loop(0, t_block, body, carry)
    for ref, v in zip((ob_p, ob_l, ob_s, ob_o, ob_u), buy):
        ref[...] = v
    for ref, v in zip((os_p, os_l, os_s, os_o, os_u), sale):
        ref[...] = v
    # Two static slice-stores, not a concat: Mosaic's vector concat rejects
    # tiny lane extents (offset mismatch at block_s == 1).
    ocnt[:, 0:1] = nb
    ocnt[:, 1:2] = ns
    onsq[...] = nq


@functools.partial(
    jax.jit,
    static_argnums=(0,),
    static_argnames=("block_s", "interpret", "block_t"),
)
def pallas_batch_step(
    config: BookConfig,
    books: BookState,
    ops: DeviceOp,
    block_s: int = 128,
    interpret: bool = False,
    block_t: int | None = None,
) -> tuple[BookState, StepOutput]:
    """Drop-in replacement for engine.batch.batch_step with identical
    semantics (books [S, ...], ops [S, T] -> books', outs [S, T, ...]).
    S must be a multiple of block_s (callers pad lanes; NOP rows are free),
    and the compiled path needs block_s to be a multiple of 128 (the packed
    op/record/scalar blocks put the symbol axis on the lane dim).

    block_t: time-block depth (must divide T; default min(T, 64)). Books
    stay VMEM-resident across the time sweep while op/record windows page
    in t_block-deep blocks, so VMEM cost is O(block_t) and deep time axes
    (hot-symbol dense grids, engine/batch.py) fit at any T.
    """
    s, t_len = ops.action.shape
    if block_t is None:
        # Largest divisor of T that fits the paged-block VMEM budget:
        # per time step the kernel pages op (8 rows) + 5 record (K rows
        # each) + scalar (8 rows) blocks of block_s lanes, double-buffered
        # by the pipeline. Mosaic's scoped-VMEM stack is 16 MB and the
        # resident book tiles take ~10*block_s*2*cap*4 (in+out), so give
        # the paged blocks ~5 MB. (Found the hard way: cap=256 K=16
        # block_s=128 at block_t=64 allocates 17.5 MB and fails to
        # compile.)
        per_t = (
            block_s
            * (8 + 5 * config.max_fills + 8)
            * jnp.dtype(config.dtype).itemsize
            * 2
        )
        budget_t = max(int((5 << 20) // per_t), 1)
        block_t = min(t_len, 64, budget_t)
        while t_len % block_t:
            block_t -= 1
    if s % block_s != 0:
        raise ValueError(f"S={s} not a multiple of block_s={block_s}")
    if t_len % block_t != 0:
        raise ValueError(f"T={t_len} not a multiple of block_t={block_t}")
    if not interpret and not (
        block_s % 128 == 0 or (block_s == s and block_s % 8 == 0)
    ):
        # Packed op/record/scalar blocks put the symbol axis on the lane
        # dim; Mosaic requires lane-dim blocks to be 128-multiples unless
        # the block spans the full axis — and sub-sublane blocks (B % 8
        # != 0) hit unsupported pad/concat relayouts in the book rows.
        raise ValueError(
            f"compiled kernel needs block_s % 128 == 0, or block_s == S "
            f"with S % 8 == 0 (got block_s={block_s}, S={s})"
        )
    cap = config.cap
    k = config.max_fills
    dt = jnp.dtype(config.dtype)
    sq = jnp.dtype(config.seq_dtype)
    if not interpret and (dt.itemsize > 4 or sq.itemsize > 4):
        raise ValueError(
            "compiled pallas kernel is int32-only (no Mosaic 64-bit "
            "lowering); use the scan path (or interpret=True) for int64"
        )
    grid = (s // block_s, t_len // block_t)

    def bspec(*shape):
        # Symbol-major blocks: block i covers rows [i*block_s, ...) and the
        # full extent of every trailing axis. The time-block index j is
        # IGNORED — time is the innermost grid dim, so the block is
        # revisited and stays VMEM-resident across the whole time sweep.
        nd = len(shape)
        return pl.BlockSpec(
            (block_s,) + shape, lambda i, j, _nd=nd: (i,) + (0,) * _nd
        )

    def tspec(mid):
        # Time-paged blocks [block_t, mid, block_s] at (time block j, lane
        # block i): dynamic per-step access lands on the major dim; the
        # symbol block rides the lane dim; only a block_t-deep window is
        # resident at a time.
        return pl.BlockSpec(
            (block_t, mid, block_s), lambda i, j: (j, 0, i)
        )

    row = lambda dtype: jax.ShapeDtypeStruct((s, cap), dtype)
    book_specs = [bspec(cap)] * 10 + [bspec(2), bspec(1)]
    book_shape = (
        [row(dt), row(dt), row(sq), row(dt), row(dt)] * 2
        + [
            jax.ShapeDtypeStruct((s, 2), jnp.int32),
            jax.ShapeDtypeStruct((s, 1), sq),
        ]
    )
    in_specs = book_specs + [tspec(8)]
    out_specs = book_specs + [tspec(k)] * 5 + [tspec(8)]
    out_shape = (
        book_shape
        + [jax.ShapeDtypeStruct((t_len, k, s), dt)] * 5
        + [jax.ShapeDtypeStruct((t_len, 8, s), dt)]  # scalar pack
    )
    aliases = {i: i for i in range(12)}

    op_pack = jnp.stack(
        [jnp.transpose(getattr(ops, f).astype(dt)) for f in _OP_FIELDS]
        + [jnp.zeros((t_len, s), dt)],
        axis=1,
    )  # [T, 8, S] in config.dtype (lossless for every field)

    rows_in = [
        getattr(books, f)[:, side]
        for side in (0, 1)
        for f in ("price", "lots", "seq", "oid", "uid")
    ]

    call = pl.pallas_call(
        functools.partial(_kernel, config, block_t),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )
    call_args = (*rows_in, books.count, books.next_seq[:, None], op_pack)
    if interpret:
        outs = call(*call_args)
    else:
        # Trace the compiled kernel with x64 promotion off regardless of the
        # global flag: every input is concretely 32-bit, but with x64 on,
        # Python-int literals inside the kernel promote to int64 and send
        # Mosaic's convert_element_type lowering into infinite recursion.
        with jax.enable_x64(False):
            outs = call(*call_args)
    (ob_p, ob_l, ob_s, ob_o, ob_u, os_p, os_l, os_s, os_o, os_u,
     ocnt, onsq, fp, mo, mu, mp, mr, scal) = outs

    pair = lambda b, a: jnp.stack([b, a], axis=1)  # [S, cap] x2 -> [S, 2, cap]
    new_books = BookState(
        price=pair(ob_p, os_p),
        lots=pair(ob_l, os_l),
        seq=pair(ob_s, os_s),
        oid=pair(ob_o, os_o),
        uid=pair(ob_u, os_u),
        count=ocnt,
        next_seq=onsq[:, 0],
    )
    sca = jnp.transpose(scal, (2, 0, 1))  # [T, 8, S] -> [S, T, 8]
    fields = {
        f: jnp.transpose(r, (2, 0, 1))  # [T, K, S] -> [S, T, K]
        for f, r in zip(_REC_FIELDS, (fp, mo, mu, mp, mr))
    }
    for i, f in enumerate(_SCALAR_FIELDS):
        want = dt if f in ("taker_remaining", "cancel_volume") else jnp.int32
        fields[f] = sca[..., i].astype(want)
    # Derived record fields (post-kernel XLA; see _REC_FIELDS note). Both
    # are exactly the step's definitions: qty = maker lots consumed;
    # taker_after = taker volume minus the inclusive fill prefix, reported
    # only on slots that filled.
    qty = fields["maker_prefill"] - fields["maker_remaining"]  # [S, T, K]
    fields["fill_qty"] = qty
    cum = jnp.cumsum(qty, axis=-1)
    vol = ops.volume.astype(dt)[:, :, None]
    fields["taker_after"] = jnp.where(qty > 0, vol - cum, 0)
    out = StepOutput(**fields)
    return new_books, out

"""Pallas TPU kernel for the batched match step (SURVEY §7 step 4).

What it buys over the XLA `scan x vmap` baseline (engine/batch.py): the scan
materializes the full [S, 2, cap] book state to HBM on every one of the T
time steps — ~2 x T x 5 arrays of HBM traffic per grid. This kernel blocks
the symbol axis, loads one block's books into VMEM ONCE, applies all T ops
with the books resident on-chip, and writes the final state back once:
HBM traffic drops by ~T, and the T-step dependency chain runs entirely out
of VMEM.

Semantics are not re-implemented: the kernel body calls the SAME
`step_impl` the scan path uses (vmap'd over the block's symbols), so the
oracle-parity tests that pin step_impl pin this kernel too. The kernel is
pure data movement + orchestration; matching math lives in exactly one
place (engine/step.py).

The kernel runs on TPU; everywhere else `pallas_batch_step(...,
interpret=True)` executes the same code path in interpreter mode (used by
the CPU test suite for parity).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..engine.book import BookConfig, BookState, DeviceOp, StepOutput
from ..engine.step import step_impl


def pallas_available() -> bool:
    """True when the default backend can run the compiled kernel."""
    return jax.default_backend() == "tpu"


def _kernel(config: BookConfig, t_len: int, *refs):
    """refs: 7 book-in + 7 op + 7 book-out + 14 StepOutput-out refs.

    Layout per block (B = symbol block size):
      book arrays   [B, 2, cap]  (count [B, 2], next_seq [B, 1])
      op arrays     [B, T]
      fill records  [B, T, K]
      op scalars    [B, T]
    """
    (bp, bl, bs, bo, bu, bc, bn,
     action, side, ismkt, oprice, ovol, ooid, ouid,
     op_, ol_, os_, oo_, ou_, oc_, on_,
     fp, fq, mo, mu, mp, mr, ta, nf, fo, tr, rs, bov, cf, cv) = refs

    books = BookState(
        price=bp[...],
        lots=bl[...],
        seq=bs[...],
        oid=bo[...],
        uid=bu[...],
        count=bc[...],
        next_seq=bn[...][:, 0],
    )
    step = jax.vmap(lambda b, o: step_impl(config, b, o))

    def body(t, books):
        op = DeviceOp(
            action=action[:, t],
            side=side[:, t],
            is_market=ismkt[:, t],
            price=oprice[:, t],
            volume=ovol[:, t],
            oid=ooid[:, t],
            uid=ouid[:, t],
        )
        books, out = step(books, op)
        # fill records [B, K] -> slot t of [B, T, K]
        for ref, v in (
            (fp, out.fill_price), (fq, out.fill_qty), (mo, out.maker_oid),
            (mu, out.maker_uid), (mp, out.maker_prefill),
            (mr, out.maker_remaining), (ta, out.taker_after),
        ):
            ref[:, pl.ds(t, 1), :] = v[:, None, :]
        # per-op scalars [B] -> slot t of [B, T]
        for ref, v in (
            (nf, out.n_fills), (fo, out.fill_overflow),
            (tr, out.taker_remaining), (rs, out.rested),
            (bov, out.book_overflow), (cf, out.cancel_found),
            (cv, out.cancel_volume),
        ):
            ref[:, pl.ds(t, 1)] = v[:, None]
        return books

    books = jax.lax.fori_loop(0, t_len, body, books)
    op_[...] = books.price
    ol_[...] = books.lots
    os_[...] = books.seq
    oo_[...] = books.oid
    ou_[...] = books.uid
    oc_[...] = books.count
    on_[...] = books.next_seq[:, None]


@functools.partial(
    jax.jit, static_argnums=(0,), static_argnames=("block_s", "interpret")
)
def pallas_batch_step(
    config: BookConfig,
    books: BookState,
    ops: DeviceOp,
    block_s: int = 8,
    interpret: bool = False,
) -> tuple[BookState, StepOutput]:
    """Drop-in replacement for engine.batch.batch_step with identical
    semantics (books [S, ...], ops [S, T] -> books', outs [S, T, ...]).
    S must be a multiple of block_s (callers pad lanes; NOP rows are free).
    """
    s, t_len = ops.action.shape
    if s % block_s != 0:
        raise ValueError(f"S={s} not a multiple of block_s={block_s}")
    cap = config.cap
    k = config.max_fills
    dt = config.dtype
    sq = config.seq_dtype
    grid = (s // block_s,)

    def bspec(*shape):
        # index_map: block i covers rows [i*block_s, (i+1)*block_s) and the
        # full extent of every trailing axis.
        nd = len(shape)
        return pl.BlockSpec(
            (block_s,) + shape, lambda i, _nd=nd: (i,) + (0,) * _nd
        )

    book_specs = [
        bspec(2, cap), bspec(2, cap), bspec(2, cap), bspec(2, cap),
        bspec(2, cap), bspec(2), bspec(1),
    ]
    op_specs = [bspec(t_len)] * 7
    out_specs = (
        book_specs
        + [bspec(t_len, k)] * 7
        + [bspec(t_len)] * 7
    )
    out_shape = (
        [
            jax.ShapeDtypeStruct((s, 2, cap), dt),  # price
            jax.ShapeDtypeStruct((s, 2, cap), dt),  # lots
            jax.ShapeDtypeStruct((s, 2, cap), sq),  # seq
            jax.ShapeDtypeStruct((s, 2, cap), dt),  # oid
            jax.ShapeDtypeStruct((s, 2, cap), dt),  # uid
            jax.ShapeDtypeStruct((s, 2), jnp.int32),  # count
            jax.ShapeDtypeStruct((s, 1), sq),  # next_seq
        ]
        + [jax.ShapeDtypeStruct((s, t_len, k), dt)] * 7  # fill records
        + [
            jax.ShapeDtypeStruct((s, t_len), jnp.int32),  # n_fills
            jax.ShapeDtypeStruct((s, t_len), jnp.int32),  # fill_overflow
            jax.ShapeDtypeStruct((s, t_len), dt),  # taker_remaining
            jax.ShapeDtypeStruct((s, t_len), jnp.int32),  # rested
            jax.ShapeDtypeStruct((s, t_len), jnp.int32),  # book_overflow
            jax.ShapeDtypeStruct((s, t_len), jnp.int32),  # cancel_found
            jax.ShapeDtypeStruct((s, t_len), dt),  # cancel_volume
        ]
    )

    # Alias book inputs to book outputs: the kernel fully overwrites them,
    # and aliasing lets the runtime reuse the (donated) buffers.
    aliases = {i: i for i in range(7)}

    outs = pl.pallas_call(
        functools.partial(_kernel, config, t_len),
        grid=grid,
        in_specs=book_specs + op_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(
        books.price, books.lots, books.seq, books.oid, books.uid,
        books.count, books.next_seq[:, None],
        ops.action, ops.side, ops.is_market, ops.price, ops.volume,
        ops.oid, ops.uid,
    )
    (op_, ol_, os_, oo_, ou_, oc_, on_,
     fp, fq, mo, mu, mp, mr, ta, nf, fo, tr, rs, bov, cf, cv) = outs
    new_books = BookState(
        price=op_, lots=ol_, seq=os_, oid=oo_, uid=ou_,
        count=oc_, next_seq=on_[:, 0],
    )
    out = StepOutput(
        fill_price=fp, fill_qty=fq, maker_oid=mo, maker_uid=mu,
        maker_prefill=mp, maker_remaining=mr, taker_after=ta,
        n_fills=nf, fill_overflow=fo, taker_remaining=tr, rested=rs,
        book_overflow=bov, cancel_found=cf, cancel_volume=cv,
    )
    return new_books, out

"""Custom TPU kernels (Pallas) for the matching hot path."""

from .pallas_match import pallas_batch_step, pallas_available

__all__ = ["pallas_batch_step", "pallas_available"]

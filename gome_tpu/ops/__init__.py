"""Custom TPU kernels (Pallas) for the matching hot path."""

from .pallas_match import (
    default_block_s,
    interpret_block_s,
    pallas_available,
    pallas_batch_step,
)

__all__ = [
    "default_block_s",
    "interpret_block_s",
    "pallas_available",
    "pallas_batch_step",
]

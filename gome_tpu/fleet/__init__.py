"""Fleet fault-tolerance tier: partition routing, member health gating,
and exactly-once failover (round 12).

`parallel/` answers "which in-process shard owns this symbol"; this
package answers the *deployment* question — which fleet **member**
(engine service process) owns which bus partition right now, given that
members die. The split is deliberate: routing math (`PartitionMap`)
is pure and testable, health classification (`HealthGate`) folds in the
existing `/healthz`/`/durability` polls, and `FailoverController` is the
only piece that mutates ownership — and only after a standby has
recovered the dead member's durable state (`Persister.restore_latest()`
+ `match_seq`), so a handoff can never double-consume.
"""

from .router import (
    FailoverController,
    HealthGate,
    PartitionMap,
    PartitionRouter,
    RouteUnavailable,
    partition_of,
)

__all__ = [
    "FailoverController",
    "HealthGate",
    "PartitionMap",
    "PartitionRouter",
    "RouteUnavailable",
    "partition_of",
]

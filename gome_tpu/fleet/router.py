"""Partition routing, health gating, and exactly-once failover.

Three layers, strictly ordered by what they are allowed to know:

  partition_of / PartitionMap   pure math + explicit ownership table.
                                Consistent hashing (fnv1a, the same
                                stable hash parallel/router.py uses for
                                in-process shards) maps a symbol to a
                                *partition*; the map — not the hash —
                                maps a partition to a *member*, so
                                reassignment is a table edit with an
                                epoch bump, never a rehash that moves
                                unrelated symbols (CoinTossX keeps its
                                failover unit the replicated partition
                                for the same reason).

  HealthGate                    classifies members UP/SUSPECT/DOWN from
                                consecutive poll failures — fed by the
                                obs/fleet aggregator's `/healthz` poll
                                results, or directly by a drill parent
                                that watched the process die.

  PartitionRouter               the read path: symbol -> live member.
                                Routing to a DOWN member whose
                                partitions have not been failed over
                                raises RouteUnavailable — callers shed
                                (retryable) rather than enqueue into a
                                stalled partition.

  FailoverController            the only writer of the map. A standby
                                must (1) *claim* the dead member under
                                the lock — exactly one claimant wins per
                                (member, epoch) — then (2) recover the
                                dead member's durable state off-lock
                                (`Persister.restore_latest()` + WAL
                                replay seeds `match_seq`, the PR 10
                                exactly-once cursor), and only then
                                (3) commit the reassignment. A crash
                                between claim and commit leaves the map
                                untouched; `release()` re-opens the
                                claim. No double-consume is possible
                                because ownership changes only after
                                recovery proves where the committed
                                offset stands.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from ..parallel.router import fnv1a

__all__ = [
    "FailoverController",
    "HealthGate",
    "PartitionMap",
    "PartitionRouter",
    "RouteUnavailable",
    "partition_of",
]

# Member health states (HealthGate) ------------------------------------
UP = "up"
SUSPECT = "suspect"
DOWN = "down"

# Failover claim states (FailoverController) ---------------------------
CLAIMED = "claimed"
RECOVERED = "recovered"


def partition_of(symbol: str, n_partitions: int) -> int:
    """Stable symbol -> partition id. fnv1a, not crc32: one hash family
    for every routing tier in the tree (parallel/router.py chose it
    because Python's hash() is salted per process)."""
    if n_partitions <= 0:
        raise ValueError("n_partitions must be positive")
    return fnv1a(symbol) % n_partitions


class RouteUnavailable(ConnectionError):
    """Owner of the target partition is DOWN and not yet failed over.

    Subclasses ConnectionError so every existing degraded-path handler
    (gateway code 14, batcher spill, client retry) treats it as
    retryable without new plumbing."""

    def __init__(self, symbol: str, partition: int, member: str):
        super().__init__(
            f"partition {partition} ({symbol!r}) owner {member!r} is down"
        )
        self.symbol = symbol
        self.partition = partition
        self.member = member


class PartitionMap:
    """Explicit partition -> member ownership table with an epoch.

    The epoch is bumped on every reassignment; a failover claim is keyed
    to the epoch it observed, so a claim raced against a concurrent
    reassignment is void rather than silently applied to a newer map.
    """

    def __init__(self, n_partitions: int, assignments: dict[int, str]):
        if n_partitions <= 0:
            raise ValueError("n_partitions must be positive")
        missing = set(range(n_partitions)) - set(assignments)
        if missing:
            raise ValueError(f"unassigned partitions: {sorted(missing)}")
        extra = set(assignments) - set(range(n_partitions))
        if extra:
            raise ValueError(f"assignments out of range: {sorted(extra)}")
        for p, m in assignments.items():
            if not m:
                raise ValueError(f"partition {p}: empty member name")
        self.n_partitions = n_partitions
        self._lock = threading.Lock()
        self._assignments = dict(assignments)  # guarded by self._lock
        self._epoch = 0  # guarded by self._lock

    @classmethod
    def even(cls, n_partitions: int, members: Iterable[str]) -> "PartitionMap":
        """Round-robin bootstrap map: partition i -> members[i % len]."""
        ms = list(members)
        if not ms:
            raise ValueError("need at least one member")
        return cls(
            n_partitions,
            {p: ms[p % len(ms)] for p in range(n_partitions)},
        )

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def owner(self, partition: int) -> str:
        with self._lock:
            return self._assignments[partition]

    def owner_of_symbol(self, symbol: str) -> tuple[int, str]:
        p = partition_of(symbol, self.n_partitions)
        with self._lock:
            return p, self._assignments[p]

    def partitions_of(self, member: str) -> list[int]:
        with self._lock:
            return sorted(
                p for p, m in self._assignments.items() if m == member
            )

    def members(self) -> list[str]:
        with self._lock:
            return sorted(set(self._assignments.values()))

    def reassign(self, partitions: Iterable[int], member: str) -> int:
        """Move `partitions` to `member`; returns the new epoch.

        Only FailoverController should call this on a live fleet — it is
        public for bootstrap/rebalance tooling, and atomic: all moves
        land under one epoch bump."""
        ps = list(partitions)
        if not member:
            raise ValueError("empty member name")
        with self._lock:
            for p in ps:
                if p not in self._assignments:
                    raise KeyError(f"unknown partition {p}")
            for p in ps:
                self._assignments[p] = member
            self._epoch += 1
            return self._epoch

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "n_partitions": self.n_partitions,
                "assignments": {
                    str(p): m for p, m in sorted(self._assignments.items())
                },
            }


@dataclass
class _MemberHealth:
    state: str = UP
    consecutive_failures: int = 0
    polls: int = 0


class HealthGate:
    """Consecutive-failure debounce over member health polls.

    One failed `/healthz` scrape is noise (GC pause, port hiccup);
    `suspect_after` consecutive failures marks SUSPECT, `down_after`
    marks DOWN. Any success snaps back to UP. A parent that *watched*
    the process exit skips the debounce via `mark_down()` — it has
    ground truth, not a sample."""

    def __init__(self, suspect_after: int = 2, down_after: int = 4):
        if not (0 < suspect_after <= down_after):
            raise ValueError("need 0 < suspect_after <= down_after")
        self.suspect_after = suspect_after
        self.down_after = down_after
        self._lock = threading.Lock()
        self._members: dict[str, _MemberHealth] = {}  # guarded by self._lock

    def record(self, member: str, healthy: bool) -> str:
        """Fold one poll result; returns the member's new state."""
        with self._lock:
            h = self._members.setdefault(member, _MemberHealth())
            h.polls += 1
            if healthy:
                h.consecutive_failures = 0
                h.state = UP
            else:
                h.consecutive_failures += 1
                if h.consecutive_failures >= self.down_after:
                    h.state = DOWN
                elif h.consecutive_failures >= self.suspect_after:
                    h.state = SUSPECT
            return h.state

    def mark_down(self, member: str) -> None:
        """Ground-truth death (observed process exit): skip the debounce."""
        with self._lock:
            h = self._members.setdefault(member, _MemberHealth())
            h.consecutive_failures = max(
                h.consecutive_failures, self.down_after
            )
            h.state = DOWN

    def state(self, member: str) -> str:
        with self._lock:
            h = self._members.get(member)
            return h.state if h is not None else UP

    def is_down(self, member: str) -> bool:
        return self.state(member) == DOWN

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {
                m: {
                    "state": h.state,
                    "consecutive_failures": h.consecutive_failures,
                    "polls": h.polls,
                }
                for m, h in sorted(self._members.items())
            }


class PartitionRouter:
    """Health-gated read path: symbol -> live owning member.

    Pure reader — holds no state of its own beyond the map + gate it was
    built over, so a drill parent, a gateway, and a test can share one
    map and see reassignments the instant the controller commits them.
    """

    def __init__(self, pmap: PartitionMap, gate: HealthGate | None = None):
        self.pmap = pmap
        self.gate = gate or HealthGate()

    def partition(self, symbol: str) -> int:
        return partition_of(symbol, self.pmap.n_partitions)

    # gomelint: hotpath — every order resolves its target member here
    def route(self, symbol: str) -> str:
        """Owner of `symbol`'s partition; RouteUnavailable if DOWN."""
        p, member = self.pmap.owner_of_symbol(symbol)
        if self.gate.is_down(member):
            raise RouteUnavailable(symbol, p, member)
        return member

    # gomelint: hotpath — batch dispatch routes whole partitions here
    def route_partition(self, partition: int) -> str:
        member = self.pmap.owner(partition)
        if self.gate.is_down(member):
            raise RouteUnavailable("", partition, member)
        return member


@dataclass
class _Claim:
    standby: str
    epoch: int  # map epoch the claim observed
    state: str = CLAIMED  # CLAIMED -> RECOVERED (then removed on commit)
    partitions: tuple[int, ...] = field(default_factory=tuple)


class FailoverController:
    """Exactly-once partition handoff: claim -> recover -> commit.

    `failover(dead, standby, recover_fn)` is the whole protocol:

      claim    under `lock`, reject if `dead` is not DOWN, if it is
               already claimed, or if the map epoch moved since the
               caller looked — exactly one standby wins.
      recover  off-lock, run `recover_fn(dead, partitions)`: the standby
               restores the dead member's durable state
               (`Persister.restore_latest()` + WAL replay) and seeds its
               consumer's `match_seq` cursor from it, so replay after
               the handoff emits identical seqs and the committed bus
               offset is honored — no double-consume.
      commit   back under the map, `reassign()` bumps the epoch; the
               claim is retired. If `recover_fn` raises, the claim is
               released and another standby may try.

    The lock is injectable (`lock=`) so the PR 11 deterministic
    interleaver can drive the claim race with a SteppingLock across
    seeded schedules.
    """

    def __init__(
        self,
        pmap: PartitionMap,
        gate: HealthGate,
        lock: Any | None = None,
    ):
        self.pmap = pmap
        self.gate = gate
        self._lock = lock if lock is not None else threading.Lock()
        self._claims: dict[str, _Claim] = {}  # guarded by self._lock
        self._history: list[dict[str, Any]] = []  # guarded by self._lock

    def claim(self, dead: str, standby: str) -> _Claim | None:
        """Phase 1: atomically claim `dead` for `standby`. None = lost."""
        if not self.gate.is_down(dead):
            return None
        with self._lock:
            if dead in self._claims:
                return None  # another standby already holds the claim
            parts = tuple(self.pmap.partitions_of(dead))
            if not parts:
                return None  # nothing to take over
            c = _Claim(standby=standby, epoch=self.pmap.epoch,
                       partitions=parts)
            self._claims[dead] = c
            return c

    def release(self, dead: str, standby: str) -> None:
        """Abort a claim (recovery failed / claimant died mid-handoff)."""
        with self._lock:
            c = self._claims.get(dead)
            if c is not None and c.standby == standby:
                del self._claims[dead]

    def commit(self, dead: str, standby: str) -> int | None:
        """Phase 3: reassign the claimed partitions; returns new epoch.

        Voids the claim (returns None) if the map epoch moved since the
        claim was taken — someone rebalanced underneath us, so applying
        the stale reassignment could clobber newer ownership."""
        with self._lock:
            c = self._claims.get(dead)
            if c is None or c.standby != standby:
                return None
            if self.pmap.epoch != c.epoch:
                del self._claims[dead]
                return None
            epoch = self.pmap.reassign(c.partitions, standby)
            del self._claims[dead]
            self._history.append({
                "dead": dead,
                "standby": standby,
                "partitions": list(c.partitions),
                "epoch": epoch,
            })
            return epoch

    def failover(
        self,
        dead: str,
        standby: str,
        recover_fn: Callable[[str, tuple[int, ...]], Any],
    ) -> int | None:
        """Full claim -> recover -> commit protocol; returns the new map
        epoch, or None if this standby lost the claim race (or the
        member was not DOWN / had no partitions)."""
        c = self.claim(dead, standby)
        if c is None:
            return None
        try:
            recover_fn(dead, c.partitions)
        except BaseException:
            self.release(dead, standby)
            raise
        c.state = RECOVERED
        epoch = self.commit(dead, standby)
        if epoch is None:
            # Epoch moved under the claim; treat like a lost race.
            return None
        return epoch

    def history(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(h) for h in self._history]

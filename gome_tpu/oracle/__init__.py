from .book import OracleEngine, SymbolBook, RestingOrder

__all__ = ["OracleEngine", "SymbolBook", "RestingOrder"]

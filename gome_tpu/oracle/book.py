"""Pure-Python executable model of the reference's matching semantics.

This is the parity oracle (SURVEY §7 step 1): it re-implements the observable
*behavior* of the reference's SetOrder / DeleteOrder / Match / MatchOrder
(gomengine/engine/engine.go:56-198) and the pre-pool protocol
(gomengine/engine/nodepool.go:14-28, gomengine/main.go:44-45) on plain Python
data structures, emitting the MatchResult event stream (engine.go:24-28) that
defines parity for the TPU engine.

Deliberate behavioral choices (SURVEY §2.3):
  * price-time priority: price via sorted level scan (nodepool.go:86-115),
    time via per-level FIFO (nodelink.go) — replicated with a dict of deques.
  * taker remainder rests at its own limit price (engine.go:69-83).
  * cancel requires the exact resting price and does NOT check ownership
    (engine.go:92-98); a miss emits nothing.
  * cancel-before-consume race: a DEL clears the pre-pool marker, so the
    queued ADD is dropped at consume time (engine.go:58-62,88-90).
  * no self-trade prevention (engine.go:138-198 never compares uuids).
  * event field semantics per types.MatchResult docstring.
  * the middle-delete hash leak (nodelink.go:151-164, SURVEY §2.3.1) is
    unobservable in the event stream and is not replicated.

Extensions beyond the reference (flagged explicitly):
  * MARKET orders (BASELINE.json config 5): cross the book ignoring price;
    any remainder is dropped (never rests) and emits no event.

Out-of-contract inputs (deliberate divergences on degenerate streams):
  * volume <= 0 ADDs: the reference emits a MatchVolume=0 pseudo-event when
    crossing (engine.go:176-194 diff<0 branch with matchVolume=0) and rests
    a zero-volume node when not crossing (engine.go:69-83), polluting the
    book with zero-depth levels. We match nothing and rest nothing; the
    ingestion bridge rejects volume<=0 before it reaches any engine.
  * duplicate oids on one symbol: the reference corrupts its linked list
    (NodeName collision in S:link:P, ordernode.go:110-112); we keep both
    orders and cancel FIFO-first. Callers must not reuse oids.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass

from ..types import (
    Action,
    MatchResult,
    Order,
    OrderSnapshot,
    OrderType,
    Side,
    StepStats,
    snapshot_of,
)


@dataclass
class RestingOrder:
    """One node in a price level's FIFO queue (reference: the JSON-encoded
    OrderNode stored in the S:link:P hash, ordernode.go:9-36)."""

    uuid: str
    oid: str
    side: Side
    price: int
    volume: int  # remaining lots
    seq: int  # arrival order (time priority; implicit in the reference's list)


class SymbolBook:
    """One symbol's order book: price level -> FIFO deque of resting orders.

    Re-expresses the reference's Redis schema (SURVEY §2.1): the S:BUY/S:SALE
    zsets become the sorted key views of `self.levels[side]`; the S:depth hash
    becomes `level_volume()`; the S:link:P hash-encoded linked lists become
    deques.
    """

    def __init__(self, symbol: str):
        self.symbol = symbol
        self.levels: dict[Side, dict[int, collections.deque[RestingOrder]]] = {
            Side.BUY: {},
            Side.SALE: {},
        }
        self._seq = 0

    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    # -- views ------------------------------------------------------------
    def crossing_levels(self, taker_side: Side, price: int | None) -> list[int]:
        """Occupied opposing price levels the taker crosses, best first.

        BUY taker: asks with price <= limit, ascending (nodepool.go:101-103).
        SALE taker: bids with price >= limit, descending (nodepool.go:90-92).
        price=None (MARKET extension) crosses every occupied level.
        """
        opp = self.levels[taker_side.opposite]
        if taker_side is Side.BUY:
            prices = sorted(p for p in opp if price is None or p <= price)
        else:
            prices = sorted(
                (p for p in opp if price is None or p >= price), reverse=True
            )
        return prices

    def level_volume(self, side: Side, price: int) -> int:
        q = self.levels[side].get(price)
        return sum(o.volume for o in q) if q else 0

    def depth(self, side: Side, max_levels: int | None = None) -> list[tuple[int, int]]:
        """[(price, aggregate volume)] best-first — the reference's depth view
        (S:BUY/S:SALE zset + S:depth hash)."""
        prices = sorted(self.levels[side], reverse=(side is Side.BUY))
        if max_levels is not None:
            prices = prices[:max_levels]
        return [(p, self.level_volume(side, p)) for p in prices]

    def orders(self, side: Side) -> list[RestingOrder]:
        """All resting orders on a side in priority order (best price first,
        FIFO within level)."""
        out: list[RestingOrder] = []
        for p in sorted(self.levels[side], reverse=(side is Side.BUY)):
            out.extend(self.levels[side][p])
        return out

    # -- mutations ---------------------------------------------------------
    def rest(self, order: Order, volume: int) -> RestingOrder:
        """Append to the FIFO at the order's own limit price
        (engine.go:80-82, nodepool.go:31-46)."""
        node = RestingOrder(
            uuid=order.uuid,
            oid=order.oid,
            side=order.side,
            price=order.price,
            volume=volume,
            seq=self.next_seq(),
        )
        self.levels[order.side].setdefault(order.price, collections.deque())
        self.levels[order.side][order.price].append(node)
        return node

    def remove_empty_level(self, side: Side, price: int) -> None:
        q = self.levels[side].get(price)
        if q is not None and not q:
            del self.levels[side][price]

    def find(self, side: Side, price: int, oid: str) -> RestingOrder | None:
        """Lookup by (price, oid) — the reference's S:link:P + S:node:O lookup
        (engine.go:92-93); oid alone is insufficient (SURVEY §2.3.2)."""
        for node in self.levels[side].get(price, ()):
            if node.oid == oid:
                return node
        return None

    def unlink(self, node: RestingOrder) -> None:
        q = self.levels[node.side].get(node.price)
        if q is not None:
            try:
                q.remove(node)
            except ValueError:
                pass
            self.remove_empty_level(node.side, node.price)


class OracleEngine:
    """The full reference pipeline in one process: gRPC gateway semantics
    (enqueue + pre-pool mark, main.go:39-64) + the sequential consumer loop
    (rabbitmq.go:116-125 -> engine.DoOrder, engine.go:46-54).

    Events accumulate in `self.events` in emission order — the parity stream.
    """

    def __init__(self) -> None:
        self.books: dict[str, SymbolBook] = {}
        self.pre_pool: set[tuple[str, str, str]] = set()
        self.queue: collections.deque[Order] = collections.deque()
        self.events: list[MatchResult] = []
        self.stats = StepStats()

    def book(self, symbol: str) -> SymbolBook:
        if symbol not in self.books:
            self.books[symbol] = SymbolBook(symbol)
        return self.books[symbol]

    # -- gateway side (main.go:39-64) --------------------------------------
    def submit(self, order: Order) -> None:
        """gRPC handler semantics: ADD marks the pre-pool (main.go:44-45),
        both actions enqueue; response is always success (main.go:49,61)."""
        if order.action is Action.ADD:
            self.pre_pool.add(self._prekey(order))
        self.queue.append(order)

    # -- consumer side (rabbitmq.go:116-125) -------------------------------
    def drain(self) -> list[MatchResult]:
        """Process everything queued, strictly sequentially. Returns the
        events emitted by this drain."""
        start = len(self.events)
        while self.queue:
            self.do_order(self.queue.popleft())
        return self.events[start:]

    def process(self, order: Order) -> list[MatchResult]:
        """submit + drain in one call. The returned events are this order's
        alone only if the queue was empty beforehand; with prior submit()s
        pending, their events are included too (drain is strictly FIFO)."""
        self.submit(order)
        return self.drain()

    def do_order(self, order: Order) -> None:
        """engine.DoOrder (engine.go:46-54)."""
        if order.action is Action.ADD:
            self.set_order(order)
        elif order.action is Action.DEL:
            self.delete_order(order)

    # -- matching (engine.go:56-85,118-198) --------------------------------
    def set_order(self, order: Order) -> None:
        key = self._prekey(order)
        if key not in self.pre_pool:
            # Cancelled (or never marked) before consumption: drop
            # (engine.go:58-62; SURVEY §2.3.3).
            self.stats.dropped_no_prepool += 1
            return
        self.pre_pool.discard(key)

        book = self.book(order.symbol)
        limit = None if order.order_type is OrderType.MARKET else order.price
        remaining = order.volume
        for level_price in book.crossing_levels(order.side, limit):
            remaining = self._match_level(book, order, level_price, remaining)
            if remaining <= 0:
                break

        if remaining > 0 and order.order_type is OrderType.LIMIT:
            # Remainder rests at its own limit price (engine.go:69-83).
            book.rest(order, remaining)
        # MARKET remainder is dropped (extension; reference has no markets).

    def _match_level(
        self, book: SymbolBook, taker: Order, level_price: int, remaining: int
    ) -> int:
        """MatchOrder's FIFO walk at one price level (engine.go:138-198),
        iterative where the reference recurses (engine.go:161)."""
        queue = book.levels[taker.side.opposite].get(level_price)
        while remaining > 0 and queue:
            maker = queue[0]
            if remaining >= maker.volume:
                # Full maker fill (engine.go:145-175; diff>0 and diff==0
                # branches are identical observably).
                match_volume = maker.volume
                remaining -= match_volume
                queue.popleft()
                self._emit(
                    taker=self._taker_snapshot(taker, remaining),
                    maker=OrderSnapshot(
                        uuid=maker.uuid,
                        oid=maker.oid,
                        symbol=book.symbol,
                        side=maker.side,
                        price=maker.price,
                        volume=match_volume,  # pre-fill volume
                    ),
                    match_volume=match_volume,
                )
            else:
                # Partial maker fill (engine.go:176-194).
                match_volume = remaining
                maker.volume -= match_volume
                remaining = 0
                self._emit(
                    taker=self._taker_snapshot(taker, 0),
                    maker=OrderSnapshot(
                        uuid=maker.uuid,
                        oid=maker.oid,
                        symbol=book.symbol,
                        side=maker.side,
                        price=maker.price,
                        volume=maker.volume,  # post-fill remaining
                    ),
                    match_volume=match_volume,
                )
        book.remove_empty_level(taker.side.opposite, level_price)
        return remaining

    # -- cancellation (engine.go:87-116) -----------------------------------
    def delete_order(self, order: Order) -> None:
        # Clear the pre-pool marker first so a still-queued ADD dies
        # (engine.go:88-90).
        self.pre_pool.discard(self._prekey(order))

        book = self.books.get(order.symbol)
        node = (
            book.find(order.side, order.price, order.oid) if book else None
        )
        if node is None:
            # Already filled / never rested / wrong price: no event
            # (engine.go:96-98).
            self.stats.cancels_missed += 1
            return

        remaining = node.volume  # partial-fill-safe (engine.go:100)
        book.unlink(node)

        # The reference serializes the REQUEST node with volume overwritten
        # to the resting remainder (engine.go:100,109).
        snap = snapshot_of(order, remaining)
        self.events.append(
            MatchResult(node=snap, match_node=snap, match_volume=0)
        )

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _prekey(order: Order) -> tuple[str, str, str]:
        """S:comparison field = S:U:O (ordernode.go:89-92)."""
        return (order.symbol, order.uuid, order.oid)

    @staticmethod
    def _taker_snapshot(taker: Order, remaining: int) -> OrderSnapshot:
        # Taker keeps its original limit price; volume is the post-fill
        # remaining (engine.go:147,164,184).
        return snapshot_of(taker, remaining)

    def _emit(
        self, taker: OrderSnapshot, maker: OrderSnapshot, match_volume: int
    ) -> None:
        self.stats.fills += 1
        self.events.append(
            MatchResult(node=taker, match_node=maker, match_volume=match_volume)
        )

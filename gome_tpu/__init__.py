"""gome_tpu — a TPU-native limit-order-book matching framework.

A ground-up rebuild of the capabilities of lxalano/gome (a Go + gRPC +
RabbitMQ + Redis matching-engine microservice; see SURVEY.md) designed for
TPU hardware: each symbol's order book is a fixed-shape HBM-resident array
structure, price-time-priority matching is a vectorized JAX/Pallas step
function `vmap`'d across thousands of independent symbols and sharded across
chips with `jax.sharding`.

Layout:
  gome_tpu.types    — domain types (Side, Action, Order, MatchResult)
  gome_tpu.fixed    — fixed-point scaling (reference:
                      gomengine/engine/ordernode.go:76-87)
  gome_tpu.oracle   — pure-Python executable model of the reference semantics
  gome_tpu.engine   — JAX book state + match/cancel step functions
  gome_tpu.ops      — Pallas TPU kernels for the hot path
  gome_tpu.parallel — device mesh, shardings, symbol routing
  gome_tpu.bridge   — gRPC/socket front door + micro-batcher
                      (reference: gomengine/main.go)
  gome_tpu.persist  — snapshot/restore + replay recovery (reference:
                      Redis-is-the-book, SURVEY §5.4)
  gome_tpu.utils    — config, logging, metrics
"""

__version__ = "0.1.0"

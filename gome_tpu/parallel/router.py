"""Symbol-hash routing across engine shards — the multi-host dispatch layer.

The reference's parallelism axis is per-symbol independence (every Redis key
is symbol-prefixed; SURVEY §2.1). Scaling beyond one chip/host therefore
needs no collectives at all: partition symbols across engine shards and
route each order to its owner — the EP-style routing of SURVEY §2.1/§5.8.
Cross-shard traffic exists only here, at dispatch (DCN between hosts, PCIe
to chips); matching never communicates.

Topology:
  ShardRouter      — stable symbol -> shard mapping (fnv1a hash; adding
                     hosts is a controlled resharding, never implicit).
  ShardedEngine    — N MatchEngine shards behind the single-engine facade:
                     mark/process split per shard, events merged back into
                     arrival order. In-process stand-in for N per-host
                     engine services; the wire variant routes to N doOrder
                     queues (one per shard service) with the same mapping.
  multihost_mesh   — jax.distributed + a global 1-D symbol mesh for the
                     single-process-per-host deployment where one engine
                     spans hosts via jax.sharding instead of N independent
                     shards (chips linked by ICI/DCN; XLA partitions the
                     batched step with zero collectives, mesh.py).
"""

from __future__ import annotations

from ..engine.book import BookConfig
from ..engine.orchestrator import MatchEngine
from ..types import MatchResult, Order


def fnv1a(s: str) -> int:
    """Stable 64-bit FNV-1a (Python's hash() is salted per process — useless
    for cross-host agreement)."""
    h = 0xCBF29CE484222325
    for b in s.encode():
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class ShardRouter:
    def __init__(self, n_shards: int):
        if n_shards <= 0:
            raise ValueError("n_shards must be positive")
        self.n_shards = n_shards

    def route(self, symbol: str) -> int:
        return fnv1a(symbol) % self.n_shards


class ShardedEngine:
    """N engine shards behind the MatchEngine facade. Correctness argument:
    a symbol maps to exactly one shard, so per-symbol op order is preserved
    by construction; shards share nothing, so processing order across
    shards is free (SURVEY §5.2's serialized-per-symbol invariant)."""

    def __init__(
        self,
        n_shards: int,
        config: BookConfig | None = None,
        n_slots: int = 128,
        max_t: int = 32,
        kernel: str = "scan",
        engine_factory=None,
    ):
        self.router = ShardRouter(n_shards)
        factory = engine_factory or (
            lambda i: MatchEngine(
                config=config, n_slots=n_slots, max_t=max_t, kernel=kernel
            )
        )
        self.shards = [factory(i) for i in range(n_shards)]

    def mark(self, order: Order) -> None:
        self.shards[self.router.route(order.symbol)].mark(order)

    def unmark(self, order: Order) -> None:
        self.shards[self.router.route(order.symbol)].unmark(order)

    def process(self, orders: list[Order]) -> list[MatchResult]:
        """Apply one micro-batch across shards; returns the event stream in
        the EXACT single-FIFO global emission order of the reference
        consumer (rabbitmq.go:116-125): each shard processes its sub-batch
        tagged with global arrival indices (one device call per shard, full
        batching preserved) and the per-order event groups merge back by
        arrival."""
        by_shard: dict[int, list[tuple[int, Order]]] = {}
        for i, order in enumerate(orders):
            by_shard.setdefault(self.router.route(order.symbol), []).append(
                (i, order)
            )
        merged: list[tuple[int, list[MatchResult]]] = []
        for shard_id, items in by_shard.items():
            merged.extend(self.shards[shard_id].process_indexed(items))
        merged.sort(key=lambda kv: kv[0])
        return [ev for _, evs in merged for ev in evs]

    def process_columnar(self, orders: list[Order]):
        """Columnar facade parity with MatchEngine (the consumer publishes
        through the EventBatch surface; the wrapper provides it)."""
        return _ResultsBatch(self.process(orders))

    def process_frame(self, cols: dict):
        """ORDER-frame ingestion on the in-process sharded facade: decodes
        to Orders and runs the exact object path — admission semantics
        included (per-shard columnar splitting with per-shard interner
        tables is not worth the complexity here; sharded DEPLOYMENTS route
        frames to per-shard doOrder queues upstream, so each shard's
        consumer gets whole frames and the native frame pipeline)."""
        from ..engine.frames import orders_from_frame

        return _ResultsBatch(self.process(orders_from_frame(cols)))

    def process_with_arrival_order(
        self, orders: list[Order]
    ) -> list[MatchResult]:
        """Kept for API compatibility: process() itself now emits exact
        global-FIFO order (per-order arrival tags), so this is an alias."""
        return self.process(orders)

    @property
    def stats(self):
        return [s.stats for s in self.shards]


class _ResultsBatch:
    """list[MatchResult] with the minimal EventBatch surface the consumer's
    publish path uses (len, to_results, to_json_lines, seq0)."""

    seq0 = None  # unstamped; the consumer passes seq0 explicitly

    def __init__(self, results):
        self._results = results

    def __len__(self):
        return len(self._results)

    def to_results(self):
        return list(self._results)

    def to_json_lines(self, seq0=None):
        import dataclasses

        from ..bus import encode_match_result

        if seq0 is None:
            return [encode_match_result(r) for r in self._results]
        return [
            encode_match_result(dataclasses.replace(r, seq=seq0 + i))
            for i, r in enumerate(self._results)
        ]


def multihost_mesh(n_local: int | None = None):
    """Global 1-D symbol mesh across all participating hosts' devices.

    Single-host (and test) environments get the local mesh. Multi-host
    requires jax.distributed.initialize() to have run (coordinator env);
    afterwards jax.devices() spans hosts, ICI/DCN routing is XLA's problem,
    and the batched step shards with zero collectives exactly as on one
    chip.
    """
    import jax

    from .mesh import make_mesh

    return make_mesh(n_local if n_local is not None else len(jax.devices()))

from .mesh import (
    make_mesh,
    shard_batch,
    shard_execution_report,
    sharded_batch_step,
    symbol_sharding,
)
from .router import ShardedEngine, ShardRouter, fnv1a, multihost_mesh

__all__ = [
    "make_mesh",
    "shard_batch",
    "shard_execution_report",
    "sharded_batch_step",
    "symbol_sharding",
    "ShardRouter",
    "ShardedEngine",
    "fnv1a",
    "multihost_mesh",
]

from .mesh import (
    make_mesh,
    shard_batch,
    sharded_batch_step,
    symbol_sharding,
)

__all__ = [
    "make_mesh",
    "shard_batch",
    "sharded_batch_step",
    "symbol_sharding",
]

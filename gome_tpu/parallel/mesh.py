"""Multi-chip scaling: symbol-sharded books over a device mesh.

The reference's only parallelism axis is per-symbol independence — every
Redis key is symbol-prefixed and symbols share nothing (SURVEY §2.1). The
TPU equivalent: the [S] symbol-lane axis of the stacked BookState/op grids is
partitioned across a 1-D `jax.sharding.Mesh` ("sym" axis). Matching needs
ZERO collectives — XLA partitions the batched scan x vmap step into S/D
independent lanes per chip; cross-chip traffic exists only at the dispatch
layer (host routes orders to the chip owning the symbol's lane — the
EP-style symbol-hash routing of SURVEY §2.1) and for global metrics
reductions (psum over "sym").

Multi-host: the same mesh spans hosts; lane routing keys on
lane // lanes_per_shard so each host's bridge feeds only its local shard and
order traffic rides DCN at the dispatch layer, never inside the step
(SURVEY §5.8).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.book import BookConfig, BookState, DeviceOp
from ..engine.batch import batch_step

SYM_AXIS = "sym"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the symbol axis. n_devices must divide the lane count
    used with it."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SYM_AXIS,))


def symbol_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for any array whose leading axis is the symbol-lane axis
    (every BookState leaf and every DeviceOp grid leaf)."""
    return NamedSharding(mesh, P(SYM_AXIS))


def shard_batch(mesh: Mesh, tree):
    """Place a [S, ...]-leaved pytree (BookState stack or DeviceOp grid)
    with the leading axis split across the mesh."""
    return jax.device_put(tree, symbol_sharding(mesh))


def sharded_batch_step(config: BookConfig, mesh: Mesh):
    """The batched step with explicit symbol-axis shardings pinned on inputs
    and outputs — the full multi-chip matching step. Compiles to per-chip
    independent lane scans with no communication.
    """
    sharding = symbol_sharding(mesh)

    def stepper(books: BookState, ops: DeviceOp):
        return batch_step(config, books, ops)

    return jax.jit(
        stepper,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
    )


def global_fill_rate(outs) -> jax.Array:
    """Example cross-chip reduction: total fills in a batch (a psum over the
    sharded lane axis, handled by XLA from the jnp.sum)."""
    import jax.numpy as jnp

    return jnp.sum(outs.n_fills)

"""Multi-chip scaling: symbol-sharded books over a device mesh.

The reference's only parallelism axis is per-symbol independence — every
Redis key is symbol-prefixed and symbols share nothing (SURVEY §2.1). The
TPU equivalent: the [S] symbol-lane axis of the stacked BookState/op grids is
partitioned across a 1-D `jax.sharding.Mesh` ("sym" axis). Matching needs
ZERO collectives — XLA partitions the batched scan x vmap step into S/D
independent lanes per chip; cross-chip traffic exists only at the dispatch
layer (host routes orders to the chip owning the symbol's lane — the
EP-style symbol-hash routing of SURVEY §2.1) and for global metrics
reductions (psum over "sym").

Multi-host: the same mesh spans hosts; lane routing keys on
lane // lanes_per_shard so each host's bridge feeds only its local shard and
order traffic rides DCN at the dispatch layer, never inside the step
(SURVEY §5.8).
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.book import BookConfig, BookState, DeviceOp
from ..engine.batch import batch_step

SYM_AXIS = "sym"


def _shard_map_fn(mesh: Mesh):
    """shard_map bound to `mesh` (replication checking off where
    supported — spelled check_vma on new jax, check_rep on older: the
    checker has no rule for pallas_call, whose ShapeDtypeStruct outputs
    carry no varying-mesh-axis annotation, and the bodies here are
    embarrassingly parallel so the check proves nothing)."""
    try:
        from jax import shard_map as _shard_map

        return functools.partial(_shard_map, mesh=mesh, check_vma=False)
    except ImportError:  # older jax
        import inspect

        from jax.experimental.shard_map import shard_map as _shard_map

        kwargs = {"mesh": mesh}
        if "check_rep" in inspect.signature(_shard_map).parameters:
            kwargs["check_rep"] = False
        return functools.partial(_shard_map, **kwargs)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the symbol axis. n_devices must divide the lane count
    used with it. Raises when fewer than n_devices devices exist — a
    silently smaller mesh would pass every downstream divisibility check
    against the WRONG size and ship a topology the operator didn't ask
    for."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"mesh wants {n_devices} devices but only "
                    f"{len(devices)} are available"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SYM_AXIS,))


def symbol_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for any array whose leading axis is the symbol-lane axis
    (every BookState leaf and every DeviceOp grid leaf)."""
    return NamedSharding(mesh, P(SYM_AXIS))


# gomelint: hotpath — per-dispatch mesh placement of the ops grid
def shard_batch(mesh: Mesh, tree):
    """Place a [S, ...]-leaved pytree (BookState stack or DeviceOp grid)
    with the leading axis split across the mesh."""
    return jax.device_put(tree, symbol_sharding(mesh))


def sharded_batch_step(
    config: BookConfig,
    mesh: Mesh,
    kernel: str = "scan",
    pallas_interpret: bool = False,
):
    """The batched step with explicit symbol-axis shardings pinned on inputs
    and outputs — the full multi-chip matching step. Compiles to per-chip
    independent lane work with no communication.

    kernel="scan": XLA scan x vmap, partitioned by GSPMD. kernel="pallas":
    the VMEM-resident kernel runs PER CHIP inside a shard_map over the
    symbol mesh — each chip sees its local [S/D, ...] block and launches
    the same compiled kernel a single-chip engine would, so multi-chip
    keeps the kernel's ~3x win over the scan path. Falls back to the scan
    step when the kernel cannot run (off-TPU without pallas_interpret,
    int64 books, local lane counts with no valid blocking).
    """
    sharding = symbol_sharding(mesh)

    use_pallas = False
    interpret = False
    if kernel == "pallas":
        from ..ops import pallas_available

        interpret = not pallas_available(config.dtype)
        use_pallas = not interpret or pallas_interpret

    if use_pallas:
        shard_map = _shard_map_fn(mesh)
        from ..engine.batch import full_kernel_step
        from ..ops import default_block_s, interpret_block_s

        def stepper(books: BookState, ops: DeviceOp):
            s_local = ops.action.shape[0] // mesh.size
            block = default_block_s(s_local, config.cap)
            if block is None and interpret:
                block = interpret_block_s(s_local)
            if block is None:
                return batch_step(config, books, ops)
            # full_kernel_step carries the cap-class slice/guard/write-back
            # (engine.batch): local book blocks may be stored wider than
            # this grid's cap class.
            per_chip = lambda b, o: full_kernel_step(
                config, b, o, block, interpret
            )
            spec = P(SYM_AXIS)
            return shard_map(
                per_chip,
                in_specs=(spec, spec),
                out_specs=(spec, spec),
            )(books, ops)

    else:

        def stepper(books: BookState, ops: DeviceOp):
            return batch_step(config, books, ops)

    return jax.jit(
        stepper,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
    )


def sharded_dense_step(
    config: BookConfig,
    mesh: Mesh,
    kernel: str = "scan",
    pallas_interpret: bool = False,
):
    """Per-shard dense gather/scatter step under the mesh — the multi-chip
    form of engine.batch.dense_batch_step/dense_kernel_step.

    Skewed (Zipf) flow is exactly where dense packing matters, and per-
    symbol key isolation makes it embarrassingly partitionable
    (ordernode.go:89-117): each shard gathers only its LOCAL live lanes,
    so the whole step needs zero collectives. The packer
    (BatchEngine._grid_geometry) lays the compact row axis out as
    [D * R_s] — shard d's rows occupy the contiguous block
    [d*R_s, (d+1)*R_s) and name only lanes that shard owns — so the
    standard symbol-axis sharding hands every chip its own [R_s] block of
    rows, its own [S/D] block of books, and the step inside shard_map is
    the SAME gather -> scan/kernel -> scatter a single-chip dense grid
    runs.

    Returns a jitted fn(books, local_ids, ops) with shardings pinned;
    local_ids are shard-local lane indices (sentinel >= S/D on padding
    rows — gathered as zero books, dropped by the scatter)."""
    sharding = symbol_sharding(mesh)
    shard_map = _shard_map_fn(mesh)
    from ..engine.batch import (
        _guard_capped,
        _lane_scan_impl,
        _scatter_books_cap,
        _slice_books_cap,
    )

    use_pallas = False
    interpret = False
    if kernel == "pallas":
        from ..ops import pallas_available

        interpret = not pallas_available(config.dtype)
        use_pallas = not interpret or pallas_interpret

    def per_chip(books, ids, ops):
        import jax.numpy as jnp

        # Cap-class slice/guard/scatter, as in engine.batch.dense_*_step:
        # the stored block may be wider than this grid's cap class.
        cap = config.cap
        base = _slice_books_cap(books, cap)
        sub = jax.tree.map(
            lambda a: jnp.take(a, ids, axis=0, mode="fill", fill_value=0),
            base,
        )
        pre_counts = sub.count
        block = None
        if use_pallas:
            from ..ops import default_block_s, interpret_block_s

            block = default_block_s(ids.shape[0], config.cap)
            if block is None and interpret:
                block = interpret_block_s(ids.shape[0])
        if block is not None:
            from ..ops import pallas_batch_step

            sub, outs = pallas_batch_step(
                config, sub, ops, block_s=block, interpret=interpret
            )
        else:
            sub, outs = jax.vmap(
                lambda b, o: _lane_scan_impl(config, b, o)
            )(sub, ops)
        outs = _guard_capped(outs, pre_counts, cap, ops)
        new_books = _scatter_books_cap(books, ids, sub, cap)
        return new_books, outs

    spec = P(SYM_AXIS)

    def stepper(books: BookState, ids, ops: DeviceOp):
        return shard_map(
            per_chip,
            in_specs=(spec, spec, spec),
            out_specs=(spec, spec),
        )(books, ids, ops)

    return jax.jit(
        stepper,
        in_shardings=(sharding, sharding, sharding),
        out_shardings=(sharding, sharding),
    )


def shard_execution_report(
    config: BookConfig,
    mesh: Mesh,
    books: BookState,
    lane_ids,
    ops: DeviceOp,
    repeats: int = 3,
) -> dict:
    """MEASURED per-shard execution time for one dense mesh dispatch
    (ISSUE 9): the skew tax as device seconds, not a host histogram.

    ``shard_map`` executes every shard inside ONE dispatch, so the host
    never sees per-shard time. This probe exploits the dense layout's
    shard-locality (each row block [d*R_s, (d+1)*R_s) names only shard
    d's lanes, zero collectives) to replay each shard's block as an
    INDEPENDENT single-device call — same gather -> scan -> scatter
    graph (engine.batch.dense_batch_step), same shapes, pinned to that
    shard's own device — and times it best-of-``repeats``. Because the
    per-shard row height R_s is the bucketed MAX of the live counts,
    every shard pays the hottest shard's row count; ``exec_ms`` vs
    ``live_lanes`` is that tax, measured.

    Args mirror the dispatch: ``books`` the full [S] stack, ``lane_ids``
    the [D*R_s] GLOBAL ids with sentinel ``S`` on padding rows (exactly
    what ``BatchEngine._grid_geometry`` returns), ``ops`` the [D*R_s, T]
    grid. An offline/ops-surface probe — never the dispatch path.
    """
    import time

    import jax.numpy as jnp

    from ..engine.batch import dense_batch_step

    d = mesh.size
    s = int(books.count.shape[0])
    local = s // d
    r_s = len(lane_ids) // d
    devices = list(np.asarray(mesh.devices).flat)

    ids_np = np.asarray(lane_ids)
    shards = []
    for j in range(d):
        dev = devices[j]
        blk = jax.tree.map(
            lambda a, j=j: jax.device_put(a[j * local:(j + 1) * local], dev),
            books,
        )
        ids_j = ids_np[j * r_s:(j + 1) * r_s]
        # Localize exactly as the dispatch does (engine.batch._step):
        # global lane % local IS the local index; sentinel -> `local`
        # (out of range: gathered as zeros, dropped by the scatter).
        ids_local = jax.device_put(
            jnp.asarray(
                np.where(ids_j >= s, local, ids_j % local), jnp.int32
            ),
            dev,
        )
        ops_j = jax.tree.map(
            lambda a, j=j: jax.device_put(a[j * r_s:(j + 1) * r_s], dev), ops
        )
        jax.block_until_ready(dense_batch_step(config, blk, ids_local, ops_j))
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            jax.block_until_ready(
                dense_batch_step(config, blk, ids_local, ops_j)
            )
            best = min(best, time.perf_counter() - t0)
        live_j = int((ids_j < s).sum())
        shards.append({
            "shard": j,
            "device": str(dev),
            "rows": r_s,
            "live_lanes": live_j,
            "rows_per_live_lane": round(r_s / live_j, 4) if live_j else None,
            "exec_ms": round(best * 1e3, 4),
        })
    times = [sh["exec_ms"] for sh in shards]
    lives = [sh["live_lanes"] for sh in shards]
    total_live = sum(lives) or 1
    return {
        "n_shards": d,
        "rows_per_shard": r_s,
        "dispatched_rows": d * r_s,
        "live_lanes": sum(lives),
        "shards": shards,
        "exec_ms_max": max(times),
        "exec_ms_mean": round(sum(times) / len(times), 4),
        "live_skew": round(max(lives) * d / total_live, 4),
        "rows_per_live_lane": round(d * r_s / total_live, 4),
    }


def global_fill_rate(outs) -> jax.Array:
    """Example cross-chip reduction: total fills in a batch (a psum over the
    sharded lane axis, handled by XLA from the jnp.sum)."""
    import jax.numpy as jnp

    return jnp.sum(outs.n_fills)

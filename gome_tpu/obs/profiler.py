"""Measured roofline: programmatic profiler capture + trace-event
attribution (ISSUE 9).

Everything the obs stack reported before this module is ANALYTIC — what
XLA's cost model says an entry *should* cost (``costmodel``), never what
a run *achieved*. This module closes that loop in three pieces:

  * ``capture()`` — a bounded ``jax.profiler`` window
    (``start_trace``/``stop_trace`` with a Perfetto artifact), plus
    helpers to locate the run directory and load the gzipped Chrome
    trace-event JSON back out of it.
  * ``parse_trace_events()`` — a pure parser. Each measured region is
    wrapped in a ``jax.profiler.TraceAnnotation`` named
    ``gome_profile/<entry>`` (``/`` as separator — the TraceMe pipeline
    STRIPS everything before a ``:``), and device time is attributed as
    the **interval union** of XLA op events clipped to the annotation
    windows. Union, not sum: XLA op events nest (a ``call`` contains the
    ``reduce-window`` it calls, with nearly identical duration) and the
    CPU runtime duplicates ``TfrtCpuExecutable::Execute`` across
    threads, so naive summing double-counts.
  * ``measured_entry_report()`` — drives the cost model's own canonical
    entries (the ``analysis.envelope.traced_entries`` memo) inside a
    capture and joins measured device time against the analytic
    flops / bytes-accessed rows: achieved GFLOP/s, achieved GB/s, and
    efficiency vs the machine's roofline ceiling
    (``min(peak_flops, intensity * peak_bw)``; peaks from
    ``GOME_PEAK_GFLOPS``/``GOME_PEAK_GBPS`` or a one-shot calibration).

``PROFILER`` is the process singleton behind the ops ``/profile``
endpoint and the ``gome_profile_*`` gauges, armed from the
``ops.profile`` / ``ops.profile_keep`` config knobs (service.app). Same
hot-path contract as TRACER/JOURNAL/TIMELINE: disabled (the default) its
``note_shard_dispatch`` hook — called from ``engine.batch._grid_geometry``
on every dense mesh dispatch — costs one attribute check and ZERO
allocations (pinned by ``sys.getallocatedblocks`` in tests).

Import discipline: NO jax at module scope — ``engine.batch`` imports
``PROFILER`` at import time and the pure parser must stay usable (and
testable) without a backend. jax loads lazily inside ``capture`` /
``measured_entry_report`` / ``machine_peaks``.

Measured scope: only the PUBLIC entries (``costmodel.RATCHET_ENTRIES``).
The ``_donating`` twins donate their argument buffers, and the memo
shares ONE argument set across repeats — executing a twin would
invalidate the very arrays the next repeat needs. CPU wall parity with
the public entries was already shown in PR 4; the twins' win is
footprint (``costmodel.donation_report``), not time.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import tempfile
import threading
from collections import deque

from ..utils.metrics import REGISTRY

#: Annotation-window name prefix. ``/`` by necessity: TraceMe treats
#: ``:`` as a metadata separator and strips everything before it, so a
#: ``gome_profile:lane_scan`` window surfaces as bare ``lane_scan``.
ANNOTATION_PREFIX = "gome_profile/"

#: Host-side event-name prefixes that are runtime plumbing, not compute.
#: Anything containing ``::`` (C++ runtime symbols — TfrtCpuExecutable,
#: ThunkExecutor, ThreadpoolListener) is excluded by rule; these cover
#: the bare-named rest.
_HOST_INFRA_PREFIXES = (
    "PjitFunction",
    "ParseArguments",
    "CopyToDevice",
    "TransferTo",
    "BufferFromHost",
    "ExecuteOptions",
    "RunBackend",
)


# ---------------------------------------------------------------------------
# capture window + artifact plumbing


class Capture:
    """Handle yielded by ``capture()``: where the trace landed."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.run_dir: str | None = None
        self.perfetto: str | None = None


@contextlib.contextmanager
def capture(log_dir: str | None = None):
    """Bounded profiler window. Everything executed inside the ``with``
    lands in one trace run under ``log_dir`` (a fresh temp dir when
    None), with a Perfetto artifact (gzipped Chrome trace-event JSON).
    On exit the handle's ``run_dir``/``perfetto`` point at the capture.
    """
    import jax

    cap = Capture(log_dir or tempfile.mkdtemp(prefix="gome-profile-"))
    jax.profiler.start_trace(
        cap.log_dir, create_perfetto_link=False, create_perfetto_trace=True
    )
    try:
        yield cap
    finally:
        jax.profiler.stop_trace()
        cap.run_dir = latest_run_dir(cap.log_dir)
        cap.perfetto = perfetto_path(cap.run_dir)


def latest_run_dir(log_dir: str | None) -> str | None:
    """The newest profiler run directory under ``log_dir``
    (``plugins/profile/<timestamp>/``), or None."""
    if not log_dir:
        return None
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile", "*")))
    return runs[-1] if runs else None


def perfetto_path(run_dir: str | None) -> str | None:
    """The Perfetto trace artifact inside a run dir, or None."""
    if not run_dir:
        return None
    hits = sorted(glob.glob(os.path.join(run_dir, "*perfetto_trace.json.gz")))
    return hits[-1] if hits else None


def load_trace_events(run_dir: str | None) -> list[dict]:
    """Trace-event list out of a run dir's Perfetto artifact ([] when
    the capture produced nothing)."""
    path = perfetto_path(run_dir)
    if path is None:
        return []
    with gzip.open(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", []) or []
    return doc or []


# ---------------------------------------------------------------------------
# pure trace-event parser


def _merge(intervals):
    """Sorted, non-overlapping union of (start, end) intervals."""
    out: list[list[float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1][1] = e
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _clip(intervals, windows):
    """Intersect op intervals with the (merged) annotation windows."""
    clipped = []
    for s, e in intervals:
        for ws, we in windows:
            cs, ce = max(s, ws), min(e, we)
            if ce > cs:
                clipped.append((cs, ce))
    return clipped


def _union_us(intervals) -> float:
    return sum(e - s for s, e in _merge(intervals))


def _is_compute_op(name: str) -> bool:
    """Host-side heuristic: XLA op events (``fusion.3``, ``call``,
    ``reduce-window.2.clone``, …) vs runtime plumbing. Python-originated
    events are ``$``-prefixed; C++ runtime symbols carry ``::``."""
    if not name or name.startswith("$") or "::" in name:
        return False
    return not name.startswith(_HOST_INFRA_PREFIXES)


def parse_trace_events(
    events: list[dict],
    labels,
    prefix: str = ANNOTATION_PREFIX,
) -> dict[str, dict]:
    """Attribute device time to annotation windows.

    For each label, finds its ``prefix + label`` complete events ("X"
    phase; the bare label is also accepted — older TraceMe pipelines
    strip the prefix at a separator) and computes:

      * ``windows``   — number of annotation windows seen
      * ``wall_us``   — summed window duration
      * ``device_us`` — interval-UNION of compute-op events clipped to
        the windows (nesting- and thread-duplication-safe)
      * ``by_device`` — the same union split per device process (on TPU
        each chip is its own pid; on CPU this degenerates to one host
        row), the per-shard attribution surface
      * ``events``    — number of compute-op events that intersected
      * ``top_op``    — the single longest contributing op name

    Events on processes whose name contains ``/device:`` count as
    compute by construction (real accelerator timelines); host events
    pass the ``_is_compute_op`` heuristic.
    """
    procs: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            procs[e.get("pid")] = (e.get("args") or {}).get("name", "")

    want = {}
    for lab in labels:
        want[prefix + lab] = lab
        want.setdefault(lab, lab)

    windows: dict[str, list] = {lab: [] for lab in labels}
    ops: list[tuple[float, float, str, str]] = []  # (start, end, name, proc)
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if name in want:
            windows[want[name]].append((ts, ts + dur))
            continue
        if dur <= 0:
            continue
        pname = procs.get(e.get("pid"), "")
        if "/device:" in pname or _is_compute_op(name):
            ops.append((ts, ts + dur, name, pname or "host"))

    out: dict[str, dict] = {}
    for lab in labels:
        win = _merge(windows[lab])
        if not win:
            out[lab] = {
                "windows": 0, "wall_us": 0.0, "device_us": 0.0,
                "by_device": {}, "events": 0, "top_op": None,
            }
            continue
        hits = []
        by_dev: dict[str, list] = {}
        top_name, top_dur = None, 0.0
        for s, e, name, pname in ops:
            clipped = _clip([(s, e)], win)
            if not clipped:
                continue
            hits.extend(clipped)
            by_dev.setdefault(pname, []).extend(clipped)
            got = sum(ce - cs for cs, ce in clipped)
            if got > top_dur:
                top_name, top_dur = name, got
        out[lab] = {
            "windows": len(windows[lab]),
            "wall_us": round(sum(e - s for s, e in win), 3),
            "device_us": round(_union_us(hits), 3),
            "by_device": {
                d: round(_union_us(iv), 3) for d, iv in sorted(by_dev.items())
            },
            "events": len(hits),
            "top_op": top_name,
        }
    return out


# ---------------------------------------------------------------------------
# machine peaks (roofline ceilings)

_PEAKS_CACHE: dict = {}
_PEAKS_LOCK = threading.Lock()


def machine_peaks(refresh: bool = False) -> dict:
    """Roofline ceilings for THIS machine. ``GOME_PEAK_GFLOPS`` /
    ``GOME_PEAK_GBPS`` override (source ``env``); otherwise a one-shot
    memoized calibration (source ``calibrated``): best-of-N f32 matmul
    for the FLOP/s ceiling, best-of-N saxpy sweep for the bandwidth
    ceiling. Calibrated ceilings are the practically-achievable ones —
    exactly the comparison an efficiency%% against a tiny integer scan
    should use — not datasheet numbers."""
    with _PEAKS_LOCK:
        if _PEAKS_CACHE and not refresh:
            return dict(_PEAKS_CACHE)
        env_f = os.environ.get("GOME_PEAK_GFLOPS")
        env_b = os.environ.get("GOME_PEAK_GBPS")
        if env_f and env_b:
            peaks = {
                "peak_gflops": float(env_f),
                "peak_gbps": float(env_b),
                "source": "env",
            }
        else:
            peaks = _calibrate()
            if env_f:
                peaks["peak_gflops"] = float(env_f)
            if env_b:
                peaks["peak_gbps"] = float(env_b)
            if env_f or env_b:
                peaks["source"] = "env+calibrated"
        _PEAKS_CACHE.clear()
        _PEAKS_CACHE.update(peaks)
        return dict(peaks)


def _calibrate() -> dict:
    import time

    import jax
    import jax.numpy as jnp

    n = 512
    a = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)
    jax.block_until_ready(mm(a, a))
    best = min(_timed(lambda: jax.block_until_ready(mm(a, a)), time)
               for _ in range(5))
    peak_gflops = 2.0 * n**3 / best / 1e9

    m = 1 << 22  # 4M f32 lanes: 16 MB operand, past L2 on anything real
    v = jnp.ones((m,), jnp.float32)
    axpy = jax.jit(lambda x: x * 2.0 + 1.0)
    jax.block_until_ready(axpy(v))
    best = min(_timed(lambda: jax.block_until_ready(axpy(v)), time)
               for _ in range(5))
    peak_gbps = 2.0 * 4 * m / best / 1e9  # one read + one write stream

    return {
        "peak_gflops": round(peak_gflops, 3),
        "peak_gbps": round(peak_gbps, 3),
        "source": "calibrated",
    }


def _timed(fn, time) -> float:
    t0 = time.perf_counter()
    fn()
    return max(time.perf_counter() - t0, 1e-9)


# ---------------------------------------------------------------------------
# the measured roofline report


def measured_entry_report(
    dtype: str = "int32", repeats: int = 8, log_dir: str | None = None
) -> dict:
    """Measure the cost model's canonical entries and join against the
    analytic rows. Compiles (and warms) each public entry OUTSIDE the
    capture window, then runs ``repeats`` block_until_ready'd calls per
    entry inside one ``gome_profile/<entry>`` annotation; the parser's
    per-window device-time union divided by ``repeats`` is the measured
    per-call device time. Achieved GFLOP/s and GB/s use the ANALYTIC
    flops / bytes-accessed (there are no per-op hardware counters on
    CPU, and on TPU the analytic numbers are the roofline's x-axis
    anyway): ``achieved = analytic_work / measured_time``.
    """
    import jax

    from . import costmodel

    analytic = {
        r["entry"]: r for r in costmodel.entry_report(dtype) if "error" not in r
    }
    peaks = machine_peaks()

    from ..analysis.envelope import traced_entries

    # Fresh device copies per CALL, materialized before the capture
    # opens: some entries donate their accumulators (compact_accum), so
    # executing the shared traced_entries memo's args would delete
    # buffers other consumers still hold — and a donated arg can't be
    # passed twice. Copies are tiny (canonical geometry) and keep the
    # capture window free of copy traffic.
    def _fresh(args):
        return jax.tree.map(
            lambda a: jax.numpy.array(a) if isinstance(a, jax.Array) else a,
            args,
        )

    jobs = []
    with costmodel._x64_ctx(dtype):
        for rec in traced_entries(dtype):
            jits = rec.get("jits")
            if not jits or "args" not in rec:
                continue
            for label, fn in jits:
                if label not in costmodel.RATCHET_ENTRIES:
                    continue  # donating twins: see module docstring
                arg_sets = [_fresh(rec["args"]) for _ in range(repeats + 1)]
                try:
                    # compile+warm — per-iteration drain is deliberate
                    # throughout this probe: each call must retire before
                    # the next so the annotation window bounds real
                    # device time, not pipelined overlap.
                    jax.block_until_ready(fn(*arg_sets[0]))  # gomelint: disable=GL504
                except Exception:  # backend-specific gaps mirror costmodel
                    continue
                # (set 0 was donated to the warm call above)
                jax.block_until_ready(arg_sets[1:])  # gomelint: disable=GL504
                jobs.append((label, fn, arg_sets[1:]))
        with capture(log_dir) as cap:
            for label, fn, arg_sets in jobs:
                with jax.profiler.TraceAnnotation(ANNOTATION_PREFIX + label):
                    for args in arg_sets:
                        jax.block_until_ready(fn(*args))  # gomelint: disable=GL504

    parsed = parse_trace_events(
        load_trace_events(cap.run_dir), [j[0] for j in jobs]
    )
    entries = {
        label: _roofline_row(label, parsed.get(label), analytic.get(label, {}),
                             repeats, peaks)
        for label, _, _ in jobs
    }
    return {
        "dtype": dtype,
        "repeats": repeats,
        "platform": jax.default_backend(),
        "peaks": peaks,
        "entries": entries,
        "run_dir": cap.run_dir,
        "perfetto_trace": cap.perfetto,
    }


def _roofline_row(label, parsed, analytic, repeats, peaks) -> dict:
    if not parsed or not parsed["windows"]:
        return {"entry": label, "error": "no trace window captured"}
    wall_per_call = parsed["wall_us"] / repeats
    device_us = parsed["device_us"]
    dev_per_call = (device_us or parsed["wall_us"]) / repeats
    row = {
        "entry": label,
        "calls": repeats,
        "wall_us_per_call": round(wall_per_call, 3),
        "device_us_per_call": round(dev_per_call, 3),
        "device_time_source": "xla_ops" if device_us else "annotation_wall",
        "trace_events": parsed["events"],
        "top_op": parsed.get("top_op"),
        "by_device": parsed.get("by_device", {}),
        "flops": analytic.get("flops"),
        "bytes_accessed": analytic.get("bytes_accessed"),
        "arithmetic_intensity": analytic.get("arithmetic_intensity"),
    }
    flops, nbytes = row["flops"], row["bytes_accessed"]
    if dev_per_call > 0:
        if flops is not None:
            # flops per µs → GFLOP/s is ×1e6 / 1e9
            row["achieved_gflops"] = round(flops / dev_per_call * 1e-3, 6)
        if nbytes is not None:
            row["achieved_gbps"] = round(nbytes / dev_per_call * 1e-3, 6)
    pf, pb = peaks.get("peak_gflops"), peaks.get("peak_gbps")
    ai = row["arithmetic_intensity"]
    if pf and pb and ai is not None:
        ceiling = min(pf, ai * pb)
        row["roofline_ceiling_gflops"] = round(ceiling, 3)
        if row.get("achieved_gflops") is not None and ceiling > 0:
            row["efficiency_pct"] = round(
                100.0 * row["achieved_gflops"] / ceiling, 4
            )
    return row


def bench_measured(dtype: str = "int32", repeats: int = 4) -> dict:
    """The compact measured block bench.py folds next to its analytic
    block: per-entry device time, achieved GFLOP/s / GB/s, efficiency.
    Goes through PROFILER when armed (the report rides the ring and the
    gauges update); falls back to a direct capture otherwise."""
    if PROFILER.enabled:
        rep = PROFILER.capture_report(dtype, repeats=repeats)
    else:
        rep = measured_entry_report(dtype, repeats=repeats)
    fields = ("device_us_per_call", "achieved_gflops", "achieved_gbps",
              "efficiency_pct")
    return {
        "dtype": dtype,
        "platform": rep["platform"],
        "peaks": rep["peaks"],
        "entries": {
            k: {f: v.get(f) for f in fields}
            for k, v in rep["entries"].items()
            if "error" not in v
        },
    }


# ---------------------------------------------------------------------------
# the process singleton


def _median(xs):
    if not xs:
        return None
    xs = sorted(xs)
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


class Profiler:
    """Bounded ring of measured-roofline reports + per-shard dispatch
    telemetry behind the ops ``/profile`` endpoint.

    Disabled by default. ``install()`` (service.app, from the
    ``ops.profile`` knob) arms the ring and registers the
    ``gome_profile_*`` gauges; per-entry labeled children appear after
    the first capture. ``note_shard_dispatch`` is the hot-path hook —
    engine.batch calls it on every dense mesh dispatch with values it
    already computed, so the disabled cost is ONE attribute check and
    zero allocations."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._reports: deque | None = None  # guarded by self._lock (armed ⇔ deque)
        self._shards: deque | None = None  # guarded by self._lock
        self._log_dir: str | None = None  # guarded by self._lock
        self._captures = 0  # guarded by self._lock

    @property
    def enabled(self) -> bool:
        return self._reports is not None  # gomelint: disable=GL402

    def install(
        self,
        keep_n: int = 8,
        log_dir: str | None = None,
        registry=None,
    ) -> "Profiler":
        with self._lock:
            keep = deque(self._reports or (), maxlen=max(1, int(keep_n)))
            self._reports = keep
            if self._shards is None:
                self._shards = deque(maxlen=256)
            self._log_dir = log_dir
        self._export(registry or REGISTRY)
        return self

    def disable(self) -> None:
        with self._lock:
            self._reports = None
            self._shards = None

    # ------------------------------------------------------------------
    # hot path

    def note_shard_dispatch(self, n_shards, rows_per_shard, live_counts):
        """Record one dense mesh dispatch's per-shard geometry: shard
        count, per-shard row-block height (the bucketed max), and the
        per-shard LIVE lane counts (``np.bincount`` the caller already
        holds). Disabled: one attribute check, zero allocations."""
        shards = self._shards  # gomelint: disable=GL402 — lock-free fast
        if shards is None:  # check; the locked append below re-validates
            return
        with self._lock:
            if self._shards is not None:
                self._shards.append((
                    int(n_shards),
                    int(rows_per_shard),
                    tuple(int(c) for c in live_counts),
                ))

    # ------------------------------------------------------------------
    # reports

    def shard_report(self) -> dict:
        """Aggregate view of the recent dense mesh dispatches: per-shard
        dispatched rows vs live lanes and the skew ratio
        (max-shard-live / mean-shard-live — 1.0 is perfectly balanced;
        the dense packer's per-shard MAX bucketing makes dispatched rows
        scale with this number)."""
        with self._lock:
            if self._shards is None:
                return {"enabled": False}
            items = list(self._shards)
        if not items:
            return {"enabled": True, "dispatches": 0}
        skews, rows_pll = [], []
        for d, r_s, counts in items:
            live = sum(counts)
            if live:
                skews.append(max(counts) * d / live)
                rows_pll.append(r_s * d / live)
        d, r_s, counts = items[-1]
        live = sum(counts) or 1
        return {
            "enabled": True,
            "dispatches": len(items),
            "last": {
                "n_shards": d,
                "rows_per_shard": r_s,
                "dispatched_rows": d * r_s,
                "live_per_shard": list(counts),
                "skew": round(max(counts) * d / live, 4),
                "rows_per_live_lane": round(d * r_s / live, 4),
            },
            "skew_p50": round(_median(skews), 4) if skews else None,
            "rows_per_live_lane_p50": (
                round(_median(rows_pll), 4) if rows_pll else None
            ),
        }

    def capture_report(self, dtype: str = "int32", repeats: int = 8) -> dict:
        """Run a measured-roofline capture now, push it onto the ring,
        and (re)bind the per-entry gauges. Seconds of work — ops
        surface, never the dispatch path."""
        with self._lock:
            log_dir = self._log_dir
        rep = measured_entry_report(dtype, repeats=repeats, log_dir=log_dir)
        with self._lock:
            if self._reports is not None:
                self._reports.append(rep)
                self._captures += 1
        self._export_entries(rep)
        return rep

    def last_report(self) -> dict | None:
        with self._lock:
            if not self._reports:
                return None
            return self._reports[-1]

    def payload(
        self, dtype: str = "int32", refresh: bool = False, repeats: int = 4
    ) -> dict:
        """The ops ``/profile`` JSON body. Armed with no capture yet (or
        ``?refresh=1``) it captures on demand; the errors a capture can
        hit degrade to an ``error`` field, never a 500."""
        if not self.enabled:
            return {
                "enabled": False, "captures": 0, "report": None,
                "shards": {"enabled": False},
            }
        rep = None if refresh else self.last_report()
        err = None
        if rep is None:
            try:
                rep = self.capture_report(dtype, repeats=repeats)
            except Exception as exc:  # pragma: no cover - backend gaps
                err = f"{type(exc).__name__}: {exc}"
        with self._lock:
            n = self._captures
        out = {"enabled": True, "captures": n, "report": rep,
               "shards": self.shard_report()}
        if err:
            out["error"] = err
        return out

    # ------------------------------------------------------------------
    # gauges

    def _export(self, reg) -> None:
        reg.callback_gauge(
            "gome_profile_captures_total",
            "Measured-roofline captures taken since arm",
            lambda: self._captures,  # gomelint: disable=GL402 — see _export
        )
        reg.callback_gauge(
            "gome_profile_shard_skew",
            "p50 max/mean live-lanes-per-shard over recent dense mesh "
            "dispatches (1.0 = balanced)",
            lambda: self.shard_report().get("skew_p50") or 0.0,
        )
        reg.callback_gauge(
            "gome_profile_shard_rows_per_live_lane",
            "p50 dispatched-rows per live lane over recent dense mesh "
            "dispatches (ROADMAP open item 2 targets <= 2.0)",
            lambda: self.shard_report().get("rows_per_live_lane_p50") or 0.0,
        )
        self._registry = reg  # single-writer: install() caller

    def _export_entries(self, rep: dict) -> None:
        reg = getattr(self, "_registry", None)
        if reg is None:
            return
        specs = (
            ("gome_profile_device_us",
             "Measured per-call device time (us) from the last capture",
             "device_us_per_call"),
            ("gome_profile_achieved_gflops",
             "Achieved GFLOP/s (analytic flops / measured device time)",
             "achieved_gflops"),
            ("gome_profile_achieved_gbps",
             "Achieved GB/s (analytic bytes / measured device time)",
             "achieved_gbps"),
            ("gome_profile_efficiency_pct",
             "Achieved GFLOP/s as % of the roofline ceiling",
             "efficiency_pct"),
        )
        for entry, row in rep.get("entries", {}).items():
            if "error" in row:
                continue
            for name, help_, field in specs:
                reg.callback_gauge(
                    name, help_,
                    lambda e=entry, f=field: self._entry_field(e, f),
                    labels={"entry": entry},
                )

    def _entry_field(self, entry: str, field: str) -> float:
        rep = self.last_report()
        if not rep:
            return 0.0
        v = (rep.get("entries", {}).get(entry) or {}).get(field)
        return float(v) if v is not None else 0.0


PROFILER = Profiler()

"""Fleet observability — metric federation + cross-process trace stitching.

Every observability surface before this module is process-local: one
registry, one flight recorder, one timeline ring, one health monitor.
ROADMAP open item 3 ("one book, many doors") makes the next era an
N-gateway x M-consumer pod — and CoinTossX (arXiv:2102.10925) / JAX-LOB
(arXiv:2308.13289) both publish their headline numbers as FLEET
aggregates, not per-process bests. This module is the instrument panel
that has to exist before that scale-out PR can carry a before/after
story:

  * **Metric federation** — :class:`FleetAggregator` polls N member
    processes' ops endpoints (``/metrics``, ``/healthz``, ``/timeline``,
    ``/durability``) and serves ONE merged view from its own ops server
    (``/fleet``). The exposition merge lives in ``utils.metrics``
    (``parse_exposition``/``merge_expositions``): counters SUM, same-
    bucket histograms merge, gauges union under a new ``proc`` label —
    lossless by contract (per-family totals equal the sum of members,
    pinned in tests/test_fleet.py).

  * **Trace stitching** — :func:`stitch_journeys` joins flight-recorder
    exports (``FlightRecorder.export``) by trace id across process
    boundaries. The gateway process records ``ingress``/``enqueue`` and
    never sees the consumer-side ``complete()``; the consumer process
    records ``bus_transit`` onward. Each process timestamps with its OWN
    ``time.perf_counter`` epoch, so the halves live on unrelated clocks:
    the ``"<id>@<t>"`` wire context gives every ``bus_transit`` span a
    sender-clock t0 and a receiver-clock t1, and the MINIMUM observed
    (t1 - t0) over all joined traces estimates the receiver-vs-sender
    clock offset (min-delay estimation: the fastest hop bounds transit
    from above, same idea as NTP's minimum-RTT filter). Receiver spans
    shift onto the sender clock; the stitched journey renders as one
    Chrome-trace timeline with per-process tracks
    (:func:`stitched_chrome_trace`).

  * **Seq audit** — the PR-10 ``SeqTracker`` state each member publishes
    under ``/durability`` rolls up fleet-wide: zero dupes + zero gaps
    across every partition is the exactly-once verdict
    ``scripts/fleet_drill.py`` gates on.

Hot-path contract (same as TRACER/JOURNAL/TIMELINE/HOSTPROF/FAULTS): the
module-level ``FLEET`` is DISABLED by default — ``poll()`` degrades to
one attribute check and ZERO allocations (pinned by the
``sys.getallocatedblocks`` guard in tests/test_fleet.py). ``install()``
arms it with a member map; the polling thread runs only between
``start()``/``stop()``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from ..utils.metrics import (
    REGISTRY,
    Registry,
    family_total,
    merge_expositions,
    parse_exposition,
    render_exposition,
)


def _default_fetch(url: str, timeout_s: float) -> str:
    """GET one member endpoint. An HTTP error status still returns the
    body — a 503 /healthz carries the full health JSON and the
    aggregator must see WHY the member is unhealthy, not just that the
    fetch 'failed'."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.read().decode()


# -- trace stitching -------------------------------------------------------


def estimate_offsets(exports: dict[str, dict]) -> dict[tuple, float]:
    """{(sender, receiver): offset_s} — the receiver-clock-minus-sender-
    clock estimate per process pair, from the minimum observed
    ``bus_transit`` delta (t0 is the sender's clock carried in the wire
    context, t1 the receiver's clock at receipt; the fastest hop is the
    tightest upper bound on true transit, so its delta is the best
    offset estimate available without a clock protocol). The sender of
    a trace is the process holding its ``ingress`` span."""
    offsets: dict[tuple, float] = {}
    by_trace = _index_by_trace(exports)
    for procs in by_trace.values():
        sender = _sender_of(procs)
        if sender is None:
            continue
        for proc, j in procs.items():
            if proc == sender:
                continue
            for span in j["spans"]:
                if span[0] == "bus_transit":
                    delta = span[2] - span[1]
                    key = (sender, proc)
                    if key not in offsets or delta < offsets[key]:
                        offsets[key] = delta
    return offsets


def _index_by_trace(exports: dict[str, dict]) -> dict[str, dict[str, dict]]:
    by_trace: dict[str, dict[str, dict]] = {}
    for proc, exp in exports.items():
        if not exp:
            continue
        for j in exp.get("journeys", ()):
            by_trace.setdefault(j["trace_id"], {})[proc] = j
    return by_trace


def _sender_of(procs: dict[str, dict]) -> str | None:
    for proc, j in procs.items():
        if any(span[0] == "ingress" for span in j["spans"]):
            return proc
    return None


def stitch_journeys(exports: dict[str, dict]) -> dict:
    """Join per-process flight-recorder exports into cross-process
    journeys on the SENDER's clock. `exports` maps process name ->
    ``FlightRecorder.export()`` dict (or None for an unreachable
    member). Returns::

        {"journeys": [...], "offsets": {"gw->con": s}, "traces": N,
         "joined": M}

    where each stitched journey carries per-span process attribution::

        {"trace_id", "procs": [...], "sender", "spans":
         [{"proc", "stage", "t0", "t1"}, ...], "start", "end",
         "duration_s"}

    Receiver-process spans shift by -offset onto the sender clock —
    EXCEPT ``bus_transit``, whose t0 is already sender-clock (carried in
    the wire context): only its t1 shifts. Single-process traces are not
    stitched (they are already whole in their member's /trace)."""
    by_trace = _index_by_trace(exports)
    offsets = estimate_offsets(exports)
    journeys = []
    for tid, procs in sorted(by_trace.items()):
        if len(procs) < 2:
            continue
        sender = _sender_of(procs)
        if sender is None:
            continue
        spans = []
        for proc, j in procs.items():
            off = 0.0 if proc == sender else offsets.get((sender, proc))
            if off is None:
                continue  # no bus_transit joined this pair — can't align
            for span in j["spans"]:
                stage, t0, t1 = span[0], span[1], span[2]
                if proc != sender:
                    if stage == "bus_transit":
                        t1 = t1 - off  # t0 already sender-clock
                    else:
                        t0, t1 = t0 - off, t1 - off
                spans.append({"proc": proc, "stage": stage,
                              "t0": t0, "t1": t1})
        if len({s["proc"] for s in spans}) < 2:
            continue
        spans.sort(key=lambda s: s["t0"])
        start = min(s["t0"] for s in spans)
        end = max(s["t1"] for s in spans)
        journeys.append(
            {
                "trace_id": tid,
                "procs": sorted({s["proc"] for s in spans}),
                "sender": sender,
                "spans": spans,
                "start": start,
                "end": end,
                "duration_s": end - start,
            }
        )
    return {
        "journeys": journeys,
        "offsets": {f"{a}->{b}": off for (a, b), off in sorted(offsets.items())},
        "traces": len(by_trace),
        "joined": len(journeys),
    }


def stitched_chrome_trace(stitch: dict) -> dict:
    """A :func:`stitch_journeys` result as Chrome trace-event JSON with
    one pid (track group) per PROCESS — load in Perfetto and the
    gateway's ingress/enqueue sit above the consumer's bus_transit/
    device_execute on one shared (sender-clock) time axis."""
    journeys = stitch.get("journeys", ())
    events: list[dict] = []
    procs: list[str] = []
    for j in journeys:
        for p in j["procs"]:
            if p not in procs:
                procs.append(p)
    pid_of = {p: i + 1 for i, p in enumerate(procs)}
    for p in procs:
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid_of[p],
                "tid": 0,
                "args": {"name": p},
            }
        )
    t_min = min((j["start"] for j in journeys), default=0.0)
    for tid_ix, j in enumerate(journeys):
        for span in j["spans"]:
            events.append(
                {
                    "name": span["stage"],
                    "cat": "order",
                    "ph": "X",
                    "pid": pid_of[span["proc"]],
                    "tid": tid_ix,
                    "ts": (span["t0"] - t_min) * 1e6,
                    "dur": max(span["t1"] - span["t0"], 0.0) * 1e6,
                    "args": {"trace_id": j["trace_id"],
                             "proc": span["proc"]},
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- the aggregator --------------------------------------------------------


class FleetAggregator:
    """Polls N member ops endpoints and serves the merged fleet view.

    Disabled by default: ``poll()`` returns None after one attribute
    check (zero allocations — the house singleton contract).
    ``install(members={name: "http://host:port"})`` arms it;
    ``start()`` runs the periodic poller on a daemon thread (``poll()``
    also works on demand — tests and the drill drive it directly)."""

    def __init__(self):
        self.interval_s = 1.0  # single-writer: install() caller
        self.timeout_s = 2.0  # single-writer: install() caller
        self._lock = threading.Lock()
        self._members: dict | None = None  # guarded by self._lock (arm state)
        self._fetch = _default_fetch  # single-writer: install() caller
        self._registry: Registry = REGISTRY  # single-writer: install()/disable() caller
        self._last: dict = {}  # guarded by self._lock — latest member snapshots
        self._last_ok: dict = {}  # guarded by self._lock — per-member last fully-successful poll (clock time)
        self.stale_after_s = 3.0  # single-writer: install() caller
        self._clock = time.monotonic  # single-writer: install() caller
        self._polls = 0  # guarded by self._lock
        self._unhealthy_polls = 0  # guarded by self._lock
        self._degraded_polls = 0  # guarded by self._lock
        self._fetch_errors = 0  # guarded by self._lock
        self._thread: threading.Thread | None = None  # single-writer: start()/stop() caller
        self._stop = threading.Event()

    @property
    def enabled(self) -> bool:
        # Off-lock read is the fast check (same benign-race contract as
        # TimelineSampler.enabled / Tracer.recorder).
        return self._members is not None  # gomelint: disable=GL402

    # -- lifecycle ---------------------------------------------------------
    def install(
        self,
        members: dict[str, str],
        interval_s: float = 1.0,
        timeout_s: float = 2.0,
        registry: Registry | None = None,
        fetch=None,
        stale_after_s: float | None = None,
        clock=None,
    ) -> "FleetAggregator":
        """Arm the aggregator over `members` ({name: base URL of that
        process's ops server}). `fetch` is injectable for tests (a
        callable ``(url, timeout_s) -> str``); `registry` receives the
        ``gome_fleet_*`` gauges (process REGISTRY by default).
        `stale_after_s` bounds how old a member's last successful poll
        may be before it is surfaced as STALE/down (default 3x the poll
        interval — one missed sweep is noise, three is an outage);
        `clock` is injectable for the staleness tests."""
        if not members:
            raise ValueError("fleet members must be a non-empty {name: url}")
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        if stale_after_s is not None and stale_after_s <= 0:
            raise ValueError(
                f"stale_after_s must be positive, got {stale_after_s}"
            )
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = (
            float(stale_after_s)
            if stale_after_s is not None
            else 3.0 * self.interval_s
        )
        if clock is not None:
            self._clock = clock
        if fetch is not None:
            self._fetch = fetch
        if registry is not None:
            self._registry = registry
        with self._lock:
            self._members = {
                str(k): str(v).rstrip("/") for k, v in members.items()
            }
            self._last = {}
            self._last_ok = {}
            self._polls = 0
            self._unhealthy_polls = 0
            self._degraded_polls = 0
            self._fetch_errors = 0
        self._export(self._registry)
        return self

    def disable(self) -> None:
        """Back to the zero-overhead state: stops the thread, drops the
        member map and snapshots, and re-binds the process REGISTRY (a
        test's private registry must not stick to the singleton)."""
        self.stop()
        with self._lock:
            self._members = None
            self._last = {}
            self._last_ok = {}
            self._polls = 0
            self._unhealthy_polls = 0
            self._degraded_polls = 0
            self._fetch_errors = 0
        self._fetch = _default_fetch
        self._registry = REGISTRY
        self._clock = time.monotonic

    # -- polling -----------------------------------------------------------
    def poll(self) -> dict | None:
        """Scrape every member once; returns {name: member state} or
        None while disabled. Disabled = one attribute check, zero
        allocations (the guarded hot-path contract — an embedding
        service may call this unconditionally)."""
        members = self._members  # gomelint: disable=GL402 — fast check;
        if members is None:  # disabled-state contract, re-checked below
            return None
        snap = {name: self._scrape_member(url) for name, url in members.items()}
        n_unhealthy = sum(1 for m in snap.values() if not m["healthy"])
        n_degraded = sum(1 for m in snap.values() if m["degraded"])
        n_errors = sum(1 for m in snap.values() if m["error"] is not None)
        now = self._clock()
        with self._lock:
            if self._members is None:  # disabled between check and lock
                return None
            self._polls += 1
            if n_unhealthy:
                self._unhealthy_polls += 1
            if n_degraded:
                self._degraded_polls += 1
            self._fetch_errors += n_errors
            for name, st in snap.items():
                if st["error"] is None:
                    self._last_ok[name] = now
            self._last = snap
        return snap

    # -- member liveness (round 12) ----------------------------------------
    def poll_age_s(self, name: str) -> float | None:
        """Seconds since `name`'s last fully-successful scrape, or None
        if it has never been scraped successfully."""
        t = self._last_ok.get(name)  # gomelint: disable=GL402 — stale read OK
        return None if t is None else max(self._clock() - t, 0.0)

    def member_up(self, name: str) -> bool:
        """True while `name`'s latest scrape succeeded AND is fresh
        (poll age within stale_after_s) — the gome_fleet_member_up
        gauge value. An unreachable or stale member reads 0, never a
        silently-served stale merge."""
        st = self._last.get(name)  # gomelint: disable=GL402 — stale read OK
        if st is None or st["error"] is not None:
            return False
        age = self.poll_age_s(name)
        return age is not None and age <= self.stale_after_s

    def _scrape_member(self, url: str) -> dict:
        """One member's /healthz + /metrics + /durability + /timeline,
        as a state dict. A partially-reachable member keeps whatever
        fetched before the failure; `error` names the first failure."""
        state: dict = {
            "url": url,
            "healthy": False,
            "degraded": False,
            "error": None,
            "health": None,
            "families": None,
            "seq": None,
            "durability": None,
            "timeline": (),
            "placement": None,
        }
        try:
            hz = json.loads(self._fetch(url + "/healthz", self.timeout_s))
            state["health"] = hz
            state["healthy"] = bool(hz.get("healthy"))
            detail = hz.get("detail")
            if isinstance(detail, dict):
                state["degraded"] = bool(detail.get("degraded"))
            state["families"] = parse_exposition(
                self._fetch(url + "/metrics", self.timeout_s)
            )
            dur = json.loads(self._fetch(url + "/durability", self.timeout_s))
            state["durability"] = dur
            state["seq"] = (dur or {}).get("matchfeed")
            tl = json.loads(self._fetch(url + "/timeline", self.timeout_s))
            state["timeline"] = list((tl or {}).get("samples", ()))[-8:]
        except Exception as exc:  # one dead member must not kill the poll
            state["error"] = f"{type(exc).__name__}: {exc}"
        try:
            # Placement scrape rides its own try: a member predating the
            # /placement surface (or running with it off) must not mark
            # the whole member unhealthy — its health/metrics above stay.
            state["placement"] = json.loads(
                self._fetch(url + "/placement", self.timeout_s)
            )
        except Exception:
            state["placement"] = None
        return state

    def start(self) -> "FleetAggregator":
        """Run the periodic poller on a daemon thread (idempotent). The
        cadence is fixed at install() time — one config point keeps
        interval_s genuinely single-writer."""
        if self._members is None:  # gomelint: disable=GL402 — arm check;
            # a disable() racing start() is caught by poll()'s own
            # locked re-check (the thread then records nothing)
            raise RuntimeError("install() the aggregator before start()")
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-aggregator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the poller thread (snapshots survive)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll()
            except Exception:  # a broken member must not kill the thread
                pass

    # -- trace stitching over the fleet ------------------------------------
    def journeys(self) -> dict[str, dict]:
        """{member: FlightRecorder export} fetched from every member's
        ``/trace?format=journeys`` (None for a member whose fetch
        failed); {} while disabled."""
        members = self._members  # gomelint: disable=GL402 — see poll()
        if members is None:
            return {}
        out = {}
        for name, url in members.items():
            try:
                out[name] = json.loads(
                    self._fetch(url + "/trace?format=journeys", self.timeout_s)
                )
            except Exception:
                out[name] = None
        return out

    def stitch(self) -> dict:
        """Cross-process journeys joined by trace id, on the sender
        clock (see :func:`stitch_journeys`)."""
        return stitch_journeys(self.journeys())

    # -- views -------------------------------------------------------------
    def rollup(self) -> dict:
        with self._lock:
            members = self._members
            return {
                "members": len(members or ()),
                "polls": self._polls,
                "unhealthy_polls": self._unhealthy_polls,
                "degraded_polls": self._degraded_polls,
                "fetch_errors": self._fetch_errors,
            }

    def payload(self) -> dict:
        """The /fleet wire form. Uses the latest poll's snapshots (one
        synchronous poll happens here if none exist yet); the merge runs
        at read time so /fleet always reflects the newest member
        scrapes. A merge failure (type conflict, bucket mismatch) lands
        as ``metrics.error`` — the health/seq surfaces must survive a
        malformed member exposition."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            snap = dict(self._last)
        if not snap:
            snap = self.poll() or {}
        members_out = {}
        exps: dict[str, dict] = {}
        seq_procs: dict[str, dict] = {}
        timeline: dict[str, list] = {}
        unreachable = []
        for name, st in snap.items():
            up = self.member_up(name)
            age = self.poll_age_s(name)
            stale = age is None or age > self.stale_after_s
            members_out[name] = {
                "url": st["url"],
                "healthy": st["healthy"],
                "degraded": st["degraded"],
                "error": st["error"],
                "health": st["health"],
                "seq": st["seq"],
                "up": up,
                "poll_age_s": age,
                "stale": stale,
            }
            if not up:
                unreachable.append(name)
            if st["families"] is not None:
                exps[name] = st["families"]
            if isinstance(st["seq"], dict):
                seq_procs[name] = st["seq"]
            timeline[name] = list(st["timeline"])
        try:
            merged = merge_expositions(exps) if exps else {}
            metrics = {
                "exposition": render_exposition(merged) if merged else "",
                "families": {
                    n: {"type": f.typ, "total": family_total(f)}
                    for n, f in merged.items()
                },
            }
        except ValueError as exc:
            metrics = {"error": str(exc)}
        fleet_seq = {
            k: sum(int(s.get(k, 0)) for s in seq_procs.values())
            for k in ("observed", "dupes", "gaps")
        }
        return {
            "enabled": True,
            "placement": self._placement_rollup(snap),
            "members": members_out,
            # Members whose latest scrape failed or went stale — callers
            # (and the fleet drill verdict) see explicitly WHOSE data is
            # missing from the merge instead of a silently thinner view.
            "unreachable": sorted(unreachable),
            "stale_after_s": self.stale_after_s,
            "rollup": self.rollup(),
            "metrics": metrics,
            "seq": {"procs": seq_procs, "fleet": fleet_seq},
            "timeline": timeline,
        }

    # -- placement flow rollup ---------------------------------------------
    def _placement_rollup(self, snap: dict) -> dict | None:
        """Fleet-wide symbol-flow view from the members' /placement
        scrapes: per-member admitted-order share (the live form of
        FLEET_r01's imbalance table — max over mean of member order
        totals) and the merged heavy-hitter table (obs.placement.
        SpaceSaving sketches fold losslessly, so the rollup is exact
        whichever order members merge). None while no member reports an
        armed observatory."""
        from .placement import SpaceSaving

        members: dict[str, dict] = {}
        rollup = None
        for name in sorted(snap):
            pl = snap[name].get("placement")
            if not (isinstance(pl, dict) and pl.get("enabled")):
                continue
            members[name] = {"admits": int(pl.get("admits", 0))}
            blob = (pl.get("sketch") or {}).get("bytes_hex")
            if not blob:
                continue
            try:
                sk = SpaceSaving.from_bytes(bytes.fromhex(blob))
            except ValueError:
                members[name]["sketch_error"] = "undecodable"
                continue
            if rollup is None:
                rollup = sk
            else:
                rollup.merge(sk)
        if not members:
            return None
        total = sum(m["admits"] for m in members.values())
        for m in members.values():
            m["order_share"] = (
                round(m["admits"] / total, 4) if total else 0.0
            )
        return {
            "members": members,
            "partition_imbalance_max_over_mean": self.partition_imbalance(),
            "flow": None if rollup is None else {
                "total": rollup.total,
                "tracked": rollup.tracked,
                "top": rollup.top(16),
            },
        }

    def partition_imbalance(self) -> float:
        """Live partition order imbalance: max over mean of per-member
        admitted-order totals from the latest placement scrapes (1.0 =
        perfectly even, FLEET_r01 measured 1.56 before the fix). 0.0
        while fewer than one member reports an armed observatory."""
        with self._lock:
            snap = dict(self._last)
        admits = [
            int(st["placement"].get("admits", 0))
            for st in snap.values()
            if isinstance(st.get("placement"), dict)
            and st["placement"].get("enabled")
        ]
        total = sum(admits)
        if not admits or not total:
            return 0.0
        return max(admits) / (total / len(admits))

    # -- metrics export ----------------------------------------------------
    def _export(self, registry: Registry) -> None:
        """Scrape-time ``gome_fleet_*`` gauges on the AGGREGATOR's own
        exposition (they describe the aggregation, so they ride the
        gauge union under ``proc`` if an aggregator is itself a fleet
        member). Off-lock int reads on purpose — a scrape must never
        contend with a poll; stale, never torn."""
        registry.callback_gauge(
            "gome_fleet_members",
            "member processes the fleet aggregator is polling",
            lambda: len(self._members or ()),  # gomelint: disable=GL402
        )
        registry.callback_gauge(
            "gome_fleet_polls_total",
            "fleet poll sweeps completed since install",
            lambda: self._polls,  # gomelint: disable=GL402 — see _export
        )
        registry.callback_gauge(
            "gome_fleet_unhealthy_polls_total",
            "poll sweeps that saw >=1 unhealthy member",
            lambda: self._unhealthy_polls,  # gomelint: disable=GL402
        )
        registry.callback_gauge(
            "gome_fleet_degraded_polls_total",
            "poll sweeps that saw >=1 degraded member (breaker/spill)",
            lambda: self._degraded_polls,  # gomelint: disable=GL402
        )
        registry.callback_gauge(
            "gome_fleet_fetch_errors_total",
            "member endpoint fetches that failed",
            lambda: self._fetch_errors,  # gomelint: disable=GL402
        )
        registry.callback_gauge(
            "gome_fleet_partition_imbalance",
            "max/mean of per-member admitted-order totals from the "
            "latest placement scrapes (1.0 = even; 0 = no data)",
            self.partition_imbalance,
        )
        # Per-member liveness: one labeled child per member name (the
        # member set is fixed at install time). 1 = latest scrape
        # succeeded and is fresh; 0 = unreachable or stale.
        for name in (self._members or {}):  # gomelint: disable=GL402
            registry.callback_gauge(
                "gome_fleet_member_up",
                "1 while the member's latest poll succeeded and is fresh "
                "(within stale_after_s); 0 = unreachable or stale",
                (lambda n: lambda: float(self.member_up(n)))(name),
                labels={"proc": name},
            )


#: Process-global aggregator (disabled until something installs a member
#: map — service boot wires it from the ``fleet:`` config section).
FLEET = FleetAggregator()

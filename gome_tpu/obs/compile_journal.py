"""Compile journal — a bounded record of jit trace+compile events.

The span taxonomy (utils.trace) already SPLITS dispatch cost into
``compile_miss`` / ``compile_hit``, but a histogram can only say that a
compile happened, not WHICH shape caused it — and the invisible-latency
cliff the ROADMAP calls out is always a specific first-seen combo
arriving mid-traffic. The journal records, per miss on the
``engine.frames`` first-seen-combo path (``BatchEngine.combo_seen`` /
``record_combo``): the full dispatch combo key, the
trace+compile wall-clock it cost, and an analytic detail block (grid
cells, op-grid / record / fetch-buffer bytes, scatter-jaxpr op count).
Operators read it three ways:

  * ``gome_compile_seconds{entry=...}`` histograms in ``/metrics``
    (count = compiles this process has paid, sum = wall-clock lost);
  * the ops ``/cost`` endpoint (JSON, ``service.ops``);
  * ``scripts/obs_snapshot.py`` dumps it as a CI artifact.

Hot-path contract (same as ``utils.trace.Tracer``): the module-level
``JOURNAL`` is DISABLED by default — every hook degrades to one attribute
check and zero allocations (asserted by tests/test_obs.py with the same
``sys.getallocatedblocks`` guard as tests/test_trace.py). ``install()``
arms it — service boot wires it from the ops config (``ops.cost``).
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque

from ..utils.metrics import REGISTRY, Registry

#: Compile wall-clock buckets: traces are ~0.1-1s on host CPU, AOT
#: compiles tens of seconds on a tunneled device — the default latency
#: buckets top out at 2.5s and would flatten exactly the tail we watch.
COMPILE_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0,
)


class CompileJournal:
    """Bounded journal of compile events keyed by entry name.

    Disabled by default: ``record`` returns after one attribute check.
    ``install(keep_n=...)`` arms it with a ring of the last ``keep_n``
    events plus per-entry running totals (count / seconds), which survive
    ring eviction — the ring answers "what just compiled", the totals
    answer "how much compile has this process paid"."""

    def __init__(self):
        self.clock = time.perf_counter  # single-writer: install() caller
        self._lock = threading.Lock()
        self._entries: deque | None = None  # guarded by self._lock
        self._totals: dict[str, list] = {}  # guarded by self._lock
        self._registry: Registry = REGISTRY  # single-writer: install() caller

    @property
    def enabled(self) -> bool:
        # Off-lock read is the hot-path fast check: the reference read is
        # atomic and mutators re-check under the lock (same benign-race
        # contract as Tracer.recorder).
        return self._entries is not None  # gomelint: disable=GL402

    def install(
        self,
        keep_n: int = 256,
        registry: Registry | None = None,
        clock=None,
    ) -> "CompileJournal":
        """Arm the journal. `registry` receives the
        ``gome_compile_seconds{entry=...}`` family (the process REGISTRY
        by default; tests pass a private one); `clock` is injectable for
        deterministic tests."""
        if keep_n <= 0:
            raise ValueError(f"keep_n must be positive, got {keep_n}")
        if registry is not None:
            self._registry = registry
        if clock is not None:
            self.clock = clock
        with self._lock:
            self._entries = deque(maxlen=keep_n)
            self._totals = {}
        return self

    def disable(self) -> None:
        """Back to the zero-overhead state (hooks become no-ops again)."""
        with self._lock:
            self._entries = None
            self._totals = {}

    def record(
        self, entry: str, key, seconds: float, detail: dict | None = None
    ) -> None:
        """One compile event. `key` is the shape-combo tuple that missed;
        `seconds` the trace+compile wall-clock the caller measured;
        `detail` an optional analytic block (see frame_combo_detail).
        No-op (one attribute check) while disabled."""
        if self._entries is None:  # gomelint: disable=GL402 — fast check;
            return  # disabled-state contract: zero work, re-checked locked
        rec = {
            "entry": entry,
            "key": tuple(key) if isinstance(key, (tuple, list)) else key,
            "seconds": float(seconds),
            "ts": time.time(),
            "detail": detail,
        }
        with self._lock:
            if self._entries is None:  # disabled between check and lock
                return
            self._entries.append(rec)
            t = self._totals.setdefault(entry, [0, 0.0])
            t[0] += 1
            t[1] += seconds
        self._registry.histogram(
            "gome_compile_seconds",
            "jit trace+compile wall-clock per first-seen shape combo",
            buckets=COMPILE_BUCKETS,
            labels={"entry": entry},
        ).observe(seconds)

    # -- views -------------------------------------------------------------
    def entries(self) -> list[dict]:
        """Ring contents, oldest first (copies — safe to mutate)."""
        with self._lock:
            return [dict(e) for e in (self._entries or ())]

    def summary(self) -> dict:
        """{entry: {"count", "seconds"}} — running totals, NOT bounded by
        the ring (evicted events still count here)."""
        with self._lock:
            return {
                name: {"count": c, "seconds": s}
                for name, (c, s) in self._totals.items()
            }

    def as_dict(self) -> dict:
        """The /cost wire form."""
        return {
            "enabled": self.enabled,
            "entries": self.entries(),
            "summary": self.summary(),
        }

    def export(self) -> dict:
        """The artifact wire form consumed by the GL906 escape check
        (``analysis.surface.check_journal_escape``): ``as_dict`` plus a
        schema tag so soak/chaos/obs_snapshot dumps stay parseable as
        the format evolves. Every recorded ``frame_dispatch`` key is
        checked against the committed combo universe."""
        return {"schema": "gome-compile-journal/1", **self.as_dict()}


#: Process-global journal (disabled until something installs it — the
#: service wires it from ``ops.cost`` at boot, service.app).
JOURNAL = CompileJournal()


# -- analytic combo detail -------------------------------------------------

#: DeviceOp field split (book.GRID_I32_FIELDS): 3 int32 control columns,
#: 4 book-dtype value columns. Kept as plain ints so the detail block
#: never imports the engine on the hot path.
_GRID_I32_FIELDS = 3
_GRID_VAL_FIELDS = 4
#: StepOutput record tensors with a [R, T, K] record axis (step.py).
_RECORD_TENSORS = 5


@functools.lru_cache(maxsize=256)
def _scatter_eqn_count(dtype_name: str, n_rows: int, t_grid: int) -> int:
    """jaxpr equation count of the device-side grid scatter-builder for
    one (dtype, R, T) shape — the jit the miss just traced. Memoized, and
    traced at a fixed small m_pad (the eqn count is independent of the
    packed-op axis length). Returns -1 when tracing is unavailable."""
    try:
        import jax
        import numpy as np

        from ..engine import frames

        fn = frames._scatter_grid_fn(dtype_name, n_rows, t_grid)
        cols = np.zeros((7, 64), np.dtype(dtype_name))
        flat = np.full(64, n_rows * t_grid, np.int32)
        jaxpr = jax.make_jaxpr(fn)(cols, flat).jaxpr
        # unwrap the jit's own pjit eqn: the BODY op count is the signal
        while len(jaxpr.eqns) == 1 and str(jaxpr.eqns[0].primitive) == "pjit":
            jaxpr = jaxpr.eqns[0].params["jaxpr"].jaxpr
        return len(jaxpr.eqns)
    except Exception:
        return -1


# gomesurface: combo(replay)
def frame_combo_detail(dtype_name: str, combo: tuple) -> dict:
    """Analytic cost block for one frame dispatch combo
    (engine.frames.submit_frame records tuples of (n_rows, t_grid, cap_g,
    dense, m_pad, k_rec, e_fills, e_cancels, totals_len)): grid cell
    count, host->device op-grid bytes, the step's [R, T, K] record-tensor
    bytes, the frame-level fetch-buffer bytes, and the scatter jaxpr's op
    count. Pure arithmetic plus one memoized abstract trace — called only
    on an enabled-journal compile MISS, which already paid a full
    trace+compile."""
    import numpy as np

    (
        n_rows, t_grid, cap_g, dense, m_pad, k_rec,
        e_fills, e_cancels, totals_len,
    ) = combo
    itemsize = np.dtype(dtype_name).itemsize
    wide = max(4, itemsize)  # compaction buffers: result_type(int32, dtype)
    cells = n_rows * t_grid
    return {
        "n_rows": int(n_rows),
        "t_grid": int(t_grid),
        "cap": int(cap_g),
        "dense": bool(dense),
        "m_pad": int(m_pad),
        "k_rec": int(k_rec),
        "grid_cells": int(cells),
        # packed columns [7, m_pad] + flat positions [m_pad]: what the
        # host actually uploads per dispatch of this shape
        "upload_bytes": int(m_pad * (7 * itemsize + 4)),
        # the scattered DeviceOp grid resident on device
        "ops_grid_bytes": int(
            cells * (_GRID_I32_FIELDS * 4 + _GRID_VAL_FIELDS * itemsize)
        ),
        # step record tensors [R, T, K] x 5 (dominant step output)
        "record_bytes": int(cells * k_rec * _RECORD_TENSORS * itemsize),
        # frame-level compaction buffers (fills[7, e_f] + cancels[2, e_c]
        # + totals[len, 4]) — the device->host fetch ceiling
        "fetch_buffer_bytes": int(
            (7 * e_fills + 2 * e_cancels) * wide + totals_len * 4 * 4
        ),
        "scatter_jaxpr_eqns": _scatter_eqn_count(
            dtype_name, int(n_rows), int(t_grid)
        ),
    }

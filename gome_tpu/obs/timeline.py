"""Host-side timeline sampler — the observability layer for the TIME axis.

Everything PRs 2 and 5 built observes a point in time: a span is one
order's trip, the compile journal one miss, the live-buffer monitor one
scrape. Nothing records how the process evolves over minutes — which is
exactly what the steady-state claims need (ROADMAP open item 5: the
latency projection cites configurations no run executed; the throughput
headline has zero contention telemetry). CoinTossX (arXiv:2102.10925)
treats hours-scale soak with continuous recording as the bar for calling
a matching engine production-grade; this module is the recorder.

:class:`TimelineSampler` periodically snapshots, into a bounded ring:

  * host RSS (``/proc/self/statm``) and ``resource.getrusage`` deltas
    since arming — CPU user/system split, involuntary context switches
    (``ru_nivcsw`` — the contention telemetry the bench headline lacked),
    major faults;
  * frames/orders the engine has applied (the ``note_frame`` hot-path
    hook — cumulative counters, so inter-sample throughput is a diff);
  * registered PROBES — zero-arg callables returning a JSON-able dict,
    sampled at snapshot time. :func:`service_timeline` wires the standard
    set: engine stats + cap + geometry-manifest hash, live-buffer
    count/bytes (obs.live), compile-journal totals, order-queue backlog,
    and FrameBatcher spill/degraded state when a batcher exists.

Operators read it three ways: the ops ``/timeline`` endpoint (JSON
series), ``gome_timeline_*`` scrape-time gauges in ``/metrics``, and
``scripts/soak.py`` which records a run's series into ``SOAK_*.json`` and
turns it into pass/fail verdicts (flat live buffers, bounded RSS slope,
stable geometry manifest).

Hot-path contract (same as ``utils.trace.Tracer`` and the compile
journal): the module-level ``TIMELINE`` is DISABLED by default — the one
hook on the frame hot path (``note_frame``) degrades to a single
attribute check and zero allocations (pinned by tests/test_timeline.py
with the ``sys.getallocatedblocks`` guard). ``install()`` arms it —
service boot wires it from the ops config (``ops.timeline``).
"""

from __future__ import annotations

import hashlib
import json
import os
import resource
import threading
import time

from collections import deque

from ..utils.metrics import REGISTRY, Registry

try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):  # non-POSIX fallback
    _PAGE = 4096


def host_rss_bytes() -> int:
    """Current resident set size in bytes. ``/proc/self/statm`` is the
    live value; ``ru_maxrss`` (the high-water mark, KiB on Linux) is the
    fallback where /proc is unavailable — a high-water mark cannot show
    shrinkage, but its SLOPE still bounds growth, which is what the soak
    verdict reads."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except Exception:
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def geometry_manifest_hash(engine) -> str:
    """Stable short hash of one BatchEngine's shape manifest (floors +
    recorded dispatch combos). At steady state this MUST stop changing:
    a drifting hash mid-soak means the flow is still minting compiled
    shapes — every mint is an invisible ~1s host re-trace tax the
    steady-state story cannot carry."""
    m = engine.shape_manifest()
    blob = json.dumps(m, sort_keys=True, default=int)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


class TimelineSampler:
    """Bounded time-series recorder of host/process/engine state.

    Disabled by default: ``note_frame`` returns after one attribute
    check, ``sample()`` returns None. ``install(interval_s=..,
    keep_n=..)`` arms it with a ring of the last ``keep_n`` samples;
    ``start()`` runs the periodic sampler on a daemon thread (``sample()``
    can also be driven manually — tests script the clock)."""

    def __init__(self):
        self.clock = time.monotonic  # single-writer: install() caller
        self.interval_s = 1.0  # single-writer: install()/start() caller
        self._lock = threading.Lock()
        self._samples: deque | None = None  # guarded by self._lock
        self._frames = 0  # guarded by self._lock
        self._orders = 0  # guarded by self._lock
        self._probes: dict[str, object] = {}
        self._rusage0 = None  # single-writer: install()/disable() caller
        self._registry: Registry = REGISTRY  # single-writer: install()/disable() caller
        self._thread: threading.Thread | None = None  # single-writer: start()/stop() caller
        self._stop = threading.Event()

    @property
    def enabled(self) -> bool:
        # Off-lock read is the hot-path fast check (same benign-race
        # contract as CompileJournal.enabled / Tracer.recorder).
        return self._samples is not None  # gomelint: disable=GL402

    # -- lifecycle ---------------------------------------------------------
    def install(
        self,
        interval_s: float = 1.0,
        keep_n: int = 512,
        registry: Registry | None = None,
        clock=None,
    ) -> "TimelineSampler":
        """Arm the sampler. `registry` receives the ``gome_timeline_*``
        gauges (process REGISTRY by default; tests pass a private one);
        `clock` is injectable for deterministic tests. The rusage
        baseline is taken HERE, so every sample's CPU/ctx-switch/fault
        fields are deltas over the armed window."""
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        if keep_n <= 0:
            raise ValueError(f"keep_n must be positive, got {keep_n}")
        self.interval_s = float(interval_s)
        if registry is not None:
            self._registry = registry
        if clock is not None:
            self.clock = clock
        with self._lock:
            self._samples = deque(maxlen=keep_n)
            self._frames = 0
            self._orders = 0
        self._rusage0 = resource.getrusage(resource.RUSAGE_SELF)
        self._export(self._registry)
        return self

    def disable(self) -> None:
        """Back to the zero-overhead state: stops the thread, drops the
        ring AND the probes (probes hold references into a service), and
        re-binds the process REGISTRY (a test's private registry must
        not stick to the singleton past its test)."""
        self.stop()
        with self._lock:
            self._samples = None
            self._frames = 0
            self._orders = 0
        self._probes.clear()
        self._registry = REGISTRY

    def register(self, name: str, fn) -> "TimelineSampler":
        """Add a probe: a zero-arg callable returning a JSON-able dict,
        evaluated at every sample. A raising probe lands as
        ``{"error": ...}`` in its slot — one dead subsystem must not
        blind the whole timeline."""
        self._probes[name] = fn
        return self

    # -- hot-path hook -----------------------------------------------------
    def note_frame(self, n_orders: int = 0) -> None:
        """One applied frame (engine.frames._assemble). Disabled = one
        attribute check, zero allocations."""
        if self._samples is None:  # gomelint: disable=GL402 — fast check;
            return  # disabled-state contract, re-checked under the lock
        with self._lock:
            if self._samples is None:
                return
            self._frames += 1
            self._orders += int(n_orders)

    # -- sampling ----------------------------------------------------------
    def sample(self) -> dict | None:
        """Take one snapshot now; returns the sample (a copy) or None
        while disabled."""
        if self._samples is None:  # gomelint: disable=GL402
            return None
        base = self._rusage0
        ru = resource.getrusage(resource.RUSAGE_SELF)
        rec: dict = {
            "ts": time.time(),
            "t": self.clock(),
            "rss_bytes": host_rss_bytes(),
            "cpu_utime_s": round(ru.ru_utime - base.ru_utime, 6),
            "cpu_stime_s": round(ru.ru_stime - base.ru_stime, 6),
            "majflt": ru.ru_majflt - base.ru_majflt,
            "nvcsw": ru.ru_nvcsw - base.ru_nvcsw,
            "nivcsw": ru.ru_nivcsw - base.ru_nivcsw,
        }
        with self._lock:
            rec["frames"] = self._frames
            rec["orders"] = self._orders
        for name, fn in list(self._probes.items()):
            try:
                rec[name] = fn()
            except Exception as exc:
                rec[name] = {"error": str(exc)}
        with self._lock:
            if self._samples is None:  # disabled between check and lock
                return None
            self._samples.append(rec)
        return dict(rec)

    def start(self, interval_s: float | None = None) -> "TimelineSampler":
        """Run the periodic sampler on a daemon thread (idempotent)."""
        if self._samples is None:  # gomelint: disable=GL402 — arm check;
            # a disable() racing start() is caught by sample()'s own
            # locked re-check (the thread then records nothing)
            raise RuntimeError("install() the sampler before start()")
        if interval_s is not None:
            if interval_s <= 0:
                raise ValueError(
                    f"interval_s must be positive, got {interval_s}"
                )
            self.interval_s = float(interval_s)
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="timeline-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the sampler thread (the ring and its samples survive)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # a broken probe must not kill the thread
                pass

    # -- views -------------------------------------------------------------
    def series(self) -> list[dict]:
        """Ring contents, oldest first (copies — safe to mutate)."""
        with self._lock:
            return [dict(s) for s in (self._samples or ())]

    def latest(self) -> dict | None:
        with self._lock:
            if not self._samples:
                return None
            return dict(self._samples[-1])

    def as_dict(self) -> dict:
        """The /timeline wire form."""
        return {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "samples": self.series(),
        }

    # -- metrics export ----------------------------------------------------
    def _export(self, registry: Registry) -> None:
        """Scrape-time ``gome_timeline_*`` gauges: live host/process
        reads plus the sampler's own counters. Re-installs rebind the
        callbacks (callback_gauge contract). The counter reads are
        off-lock on purpose — a /metrics scrape must never contend with
        the frame hot path; an int read is a single bytecode op under
        the GIL (merely stale, never torn)."""
        registry.callback_gauge(
            "gome_timeline_rss_bytes",
            "host resident set size (bytes, /proc/self/statm)",
            host_rss_bytes,
        )
        registry.callback_gauge(
            "gome_timeline_cpu_seconds_total",
            "process CPU seconds (user+system, getrusage)",
            lambda: (
                lambda ru: ru.ru_utime + ru.ru_stime
            )(resource.getrusage(resource.RUSAGE_SELF)),
        )
        registry.callback_gauge(
            "gome_timeline_involuntary_ctx_switches_total",
            "involuntary context switches (ru_nivcsw — core contention)",
            lambda: resource.getrusage(resource.RUSAGE_SELF).ru_nivcsw,
        )
        registry.callback_gauge(
            "gome_timeline_major_faults_total",
            "major page faults (ru_majflt)",
            lambda: resource.getrusage(resource.RUSAGE_SELF).ru_majflt,
        )
        registry.callback_gauge(
            "gome_timeline_samples",
            "samples currently held in the timeline ring",
            lambda: len(self._samples or ()),  # gomelint: disable=GL402
        )
        registry.callback_gauge(
            "gome_timeline_frames_total",
            "frames applied since the timeline was armed",
            lambda: self._frames,  # gomelint: disable=GL402 — see _export
        )
        registry.callback_gauge(
            "gome_timeline_orders_total",
            "orders applied since the timeline was armed",
            lambda: self._orders,  # gomelint: disable=GL402 — see _export
        )


#: Process-global sampler (disabled until something installs it — the
#: service wires it from ``ops.timeline`` at boot, service.app).
TIMELINE = TimelineSampler()


# -- standard probes -------------------------------------------------------


def service_timeline(service, sampler: TimelineSampler | None = None):
    """Register the standard probe set for one EngineService / MatchEngine
    (every read happens at SAMPLE time through closures — nothing on the
    hot path, and engine growth/restore is always reflected):

      engine   — order/device-call/escalation/fallback totals, current
                 cap + n_slots, compiled-combo count, and the
                 geometry-manifest hash (steady state ⇒ hash holds still)
      live     — process live device-buffer count/bytes (obs.live; no gc
                 pass — sampling must stay cheap)
      compile  — compile-journal running totals (count + seconds paid)
      queue    — doOrder backlog (published minus committed offsets)
      batcher  — FrameBatcher buffered/spill/degraded state (only when
                 the service's gateway runs one)
      persist  — snapshot cadence + recovery state (only when the service
                 runs a Persister)
      placement — cumulative dispatch occupancy (dispatched/live/padding
                 rows, obs.placement; {} while the observatory is off)
    """
    tl = sampler or TIMELINE
    engine = getattr(service, "engine", service)
    batch = getattr(engine, "batch", engine)

    def engine_probe():
        st = batch.stats
        return {
            "orders_total": int(st.orders),
            "device_calls": int(st.device_calls),
            "cap_escalations": int(st.cap_escalations),
            "frame_fallbacks": int(st.frame_fallbacks),
            "cap": int(batch.config.cap),
            "n_slots": int(batch.n_slots),
            "seen_combos": batch.combo_count(),
            "geometry_hash": geometry_manifest_hash(batch),
        }

    tl.register("engine", engine_probe)

    def live_probe():
        from .live import live_array_stats

        return live_array_stats(collect=False)

    tl.register("live", live_probe)

    def compile_probe():
        from .compile_journal import JOURNAL

        s = JOURNAL.summary()
        return {
            "compiles": sum(v["count"] for v in s.values()),
            "compile_seconds": round(
                sum(v["seconds"] for v in s.values()), 6
            ),
        }

    tl.register("compile", compile_probe)

    q = getattr(getattr(service, "bus", None), "order_queue", None)
    if (
        q is not None
        and hasattr(q, "end_offset")
        and hasattr(q, "committed")
    ):
        tl.register(
            "queue",
            lambda: {"order_backlog": int(q.end_offset() - q.committed())},
        )

    bus = getattr(service, "bus", None)
    bus_queues = [
        bq
        for bq in (
            getattr(bus, "order_queue", None),
            getattr(bus, "match_queue", None),
        )
        if bq is not None and hasattr(bq, "depth")
    ]
    if bus_queues:
        # Per-queue depth/lag (Queue.depth — local-state read, no broker
        # I/O even on amqp): the per-partition fan-in telemetry the fleet
        # verdicts read. The "queue" probe above stays as-is — the soak
        # verdicts key on its order_backlog field.
        def bus_probe():
            return {
                bq.name: {
                    "depth": int(bq.depth()),
                    "committed": int(bq.committed()),
                }
                for bq in bus_queues
            }

        tl.register("bus", bus_probe)

    gw = getattr(service, "gateway", None)
    batcher = getattr(gw, "batcher", None) or getattr(gw, "_batcher", None)
    if batcher is not None:

        def batcher_probe():
            s = batcher.stats()
            return {
                "buffered": int(s["buffered"]),
                "spill_depth": int(s["spill_depth"]),
                "degraded": bool(s["degraded"]),
                "degraded_seconds_total": round(
                    float(s["degraded_seconds_total"]), 3
                ),
            }

        tl.register("batcher", batcher_probe)

    persist = getattr(service, "persist", None)
    if persist is not None and hasattr(persist, "probe"):
        # Snapshot cadence + recovery state (persist.snapshot.Persister) —
        # soak verdicts can now see whether snapshots kept their cadence.
        tl.register("persist", persist.probe)

    def placement_probe():
        # Occupancy history (obs.placement): cumulative dispatched/live/
        # padding rows per sample, so padding drift rides /timeline next
        # to RSS and queue depth. {} while the observatory is disarmed.
        from .placement import PLACEMENT

        return PLACEMENT.occupancy_probe()

    tl.register("placement", placement_probe)
    return tl

"""Device-level observability (ISSUE 5).

PR 2 gave the service spans, /metrics, and a flight recorder — all HOST
clocks. Everything below the `device_execute` span was still a black box:
what a compiled entry actually costs in FLOPs and HBM bytes, what a
compile miss costs in wall-clock, what device memory the engine holds at
steady state, and whether PR 4's buffer donation delivered the footprint
win CPU timing could not see. This package closes that gap with four
cooperating pieces:

  * ``costmodel``  — per-entry FLOPs / bytes-accessed / HBM attribution
    pulled from compiled executables' ``cost_analysis()`` /
    ``memory_analysis()``, reusing the ``analysis.envelope.traced_entries``
    memo's canonical geometry; includes the donation-effectiveness report
    (public entry vs its ``_donating`` twin).
  * ``compile_journal`` — a bounded journal of jit trace+compile events,
    hooked on the first-seen-combo miss path in ``engine.frames``
    (``BatchEngine.record_combo`` is the single writer);
    exported as ``gome_compile_seconds{entry=...}`` metrics and the ops
    ``/cost`` endpoint. Same hot-path contract as ``utils.trace``:
    disabled (the default) it costs one attribute check and ZERO
    allocations.
  * ``live`` — tagged ``jax.live_arrays()`` snapshots (per-subsystem
    HBM-residency gauges) and a steady-state leak detector.
  * ``profiler`` — the MEASURED axis (ISSUE 9): programmatic
    ``jax.profiler`` capture with a trace-event parser that joins
    per-entry device time against the analytic flops/bytes — achieved
    GFLOP/s, achieved GB/s, efficiency vs the roofline ceiling — plus
    per-shard dispatched-rows/execution telemetry on the mesh path;
    exported as ``gome_profile_*`` gauges and the ops ``/profile``
    endpoint. ``PROFILER`` follows the same disabled-singleton hot-path
    contract.
  * ``timeline`` — the TIME axis (ISSUE 6): a bounded host-side sampler
    recording RSS, getrusage deltas, live-buffer counts, compile totals,
    queue depth, and the geometry-manifest hash over a run; exported as
    the ops ``/timeline`` endpoint + ``gome_timeline_*`` gauges and
    consumed by ``scripts/soak.py`` for the steady-state verdicts.
  * ``hostprof`` — the HOST-CPU axis (ISSUE 10): an in-process sampling
    profiler (SIGPROF/setitimer with a daemon-thread fallback) whose
    samples join against the tracer stage taxonomy — measured ns/order
    per host stage, the gateway admit split function-by-function, and
    the host-vs-device roofline (``HOSTPROF_r01.json``); exported as
    the ops ``/hostprof`` endpoint + ``gome_hostprof_*`` gauges.
    ``HOSTPROF`` follows the same disabled-singleton hot-path contract
    (the gateway calls ``note_admit`` per accepted order).
  * ``fleet`` — the PROCESS axis (ISSUE 13): a :class:`FleetAggregator`
    that polls N member processes' ops endpoints and serves the merged
    view (``/fleet``) — counters summed, same-bucket histograms merged,
    gauges unioned under a ``proc`` label (the exposition parse/merge
    engine lives in ``utils.metrics``) — plus cross-process trace
    stitching (journeys joined by trace id across gateway/consumer
    processes, clock offset estimated from the ``"<id>@<t>"`` wire
    contexts). ``FLEET`` follows the same disabled-singleton hot-path
    contract; ``scripts/fleet_drill.py`` publishes ``FLEET_r01.json``
    from a real 2-gateway x 2-consumer subprocess fleet.
  * ``capacity`` — the LOAD axis (ISSUE 17): coordinated-omission-safe
    latency recording (:class:`~capacity.LogHistogram`, mergeable /
    byte-stable across processes), the open-loop intended-arrival
    schedule, saturation-knee detection, and bottleneck attribution;
    ``CAPACITY`` serves the committed sweep verdict
    (``CAPACITY_r01.json``) as the ops ``/capacity`` payload +
    ``gome_capacity_*`` gauges. ``scripts/capacity.py`` drives the
    offered-rate ladder against the single-process service and the
    real 2x2 fleet.
  * ``scripts/perf_ratchet.py`` — gates the deterministic analytic
    metrics (flops/order, bytes/order, peak HBM, compile count) against
    the committed ``PERF_BASELINE.json`` in CI.

Import discipline: this ``__init__`` pulls in only ``compile_journal``,
``timeline``, ``hostprof``, and ``capacity`` (all dependency-free) so
``engine.frames``
/ ``service.gateway`` can import the JOURNAL/TIMELINE/HOSTPROF
singletons without a cycle; ``costmodel`` (which imports the engine),
``live``, and ``profiler`` load lazily on first attribute access
(engine.batch imports ``obs.profiler`` directly — the module keeps jax
and the engine out of its import path on purpose).
"""

from __future__ import annotations

from .capacity import CAPACITY, LogHistogram, OpenLoopSchedule
from .compile_journal import JOURNAL, CompileJournal, frame_combo_detail
from .hostprof import HOSTPROF, HostSampler
from .timeline import TIMELINE, TimelineSampler, service_timeline

__all__ = [
    "JOURNAL",
    "CompileJournal",
    "frame_combo_detail",
    "TIMELINE",
    "TimelineSampler",
    "service_timeline",
    "HOSTPROF",
    "HostSampler",
    "CAPACITY",
    "LogHistogram",
    "OpenLoopSchedule",
    "capacity",
    "hostprof",
    "costmodel",
    "fleet",
    "live",
    "profiler",
]


def __getattr__(name):
    if name in ("costmodel", "fleet", "live", "profiler"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Analytic device cost model — XLA cost/memory attribution per entry.

Every compiled engine entry carries an exact, DETERMINISTIC description
of what it costs: XLA's ``cost_analysis()`` (FLOPs, bytes accessed) and
``memory_analysis()`` (argument / output / temp / aliased buffer sizes)
on the compiled executable. The matching engine's throughput story has so
far been wall-clock only — meaningful on the noisy dev tunnel but blind
to WHAT the device does per order, and useless as a CI regression signal
(JAX-LOB and CoinTossX both make per-kernel op/memory accounting the
primary honesty check for a vectorized matching engine). This module
turns the attribution into first-class data:

  * :func:`entry_report` — one row per engine device entry (batch_step,
    dense_batch_step, lane_scan, compact_accum, the grid scatter-builder)
    and per donation twin: flops, bytes accessed, arithmetic intensity,
    argument/output/temp/alias bytes, peak HBM, jaxpr op count, and
    per-order normalizations.
  * :func:`donation_report` — each public entry vs its ``_donating``
    twin: alias bytes (what XLA actually reused) and the peak-HBM delta —
    finally measuring the footprint win PR 4 could only argue for
    ("the win is device HBM footprint, which CPU timing cannot see").
  * :func:`ratchet_metrics` — the flat {name: value} dict
    ``scripts/perf_ratchet.py`` gates against ``PERF_BASELINE.json``.
  * :func:`bench_analytics` — the compact block ``bench.py`` folds into
    its JSON payload next to orders/sec.

Geometry and trace reuse: the entries are lowered at the SAME canonical
small geometry as ``analysis.envelope.traced_entries`` (cap=8,
max_fills=4, S=2, T=4), consuming the memo's recorded args directly — the
cost model introduces no new trace geometry, and the per-(entry, dtype)
report is memoized so /cost, bench, and the ratchet share one set of
compiled executables per process. Peak HBM here is the analytic live-set
bound ``argument + output + temp - alias`` (donated/aliased buffers are
shared between an argument and an output, so they count once); on CPU
and TPU alike these numbers come from the compiled executable, not a
measurement, which is what makes them CI-gateable.

Skip-safety: backends may return ``None`` from ``cost_analysis`` /
``memory_analysis``; the report then carries ``None`` fields and callers
(tests, the ratchet) skip those metrics instead of failing.
"""

from __future__ import annotations

import warnings

#: Memoized per (dtype, ) report: one lowering+compile set per process.
_REPORT_CACHE: dict[str, list[dict]] = {}

#: Entries whose jaxpr is a single pjit wrapper (batch/dense/kernel
#: steps): the INNER jaxpr carries the real op count; unwrap one level.
_WRAPPER_PRIMS = ("pjit", "custom_jvp_call", "custom_vjp_call")


def _x64_ctx(dtype: str):
    from jax.experimental import disable_x64, enable_x64

    return enable_x64() if dtype == "int64" else disable_x64()


def _normalize_cost(ca) -> dict:
    """cost_analysis() returns a list of one dict on older jaxlibs and a
    plain dict on newer ones; None when the backend has no cost model."""
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def _jaxpr_eqn_count(closed) -> int:
    """Equation count of a closed jaxpr, unwrapping a single top-level
    pjit (the jit entries trace to one pjit eqn wrapping the real body)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    eqns = list(jaxpr.eqns)
    while len(eqns) == 1 and str(eqns[0].primitive) in _WRAPPER_PRIMS:
        inner = eqns[0].params.get("jaxpr")
        if inner is None:
            break
        jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
        eqns = list(jaxpr.eqns)
    n = len(eqns)
    return n


def compiled_stats(compiled) -> dict:
    """Cost/memory attribution of one compiled executable. Fields are
    None where the backend declines to report (skip-safe)."""
    cost = _normalize_cost(compiled.cost_analysis())
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes accessed")
    out = {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": (
            float(bytes_accessed) if bytes_accessed is not None else None
        ),
        "arithmetic_intensity": (
            float(flops) / float(bytes_accessed)
            if flops and bytes_accessed
            else None
        ),
        "argument_bytes": None,
        "output_bytes": None,
        "temp_bytes": None,
        "alias_bytes": None,
        "generated_code_bytes": None,
        "peak_hbm_bytes": None,
    }
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        arg = int(ma.argument_size_in_bytes)
        outb = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        out.update(
            argument_bytes=arg,
            output_bytes=outb,
            temp_bytes=temp,
            alias_bytes=alias,
            generated_code_bytes=int(ma.generated_code_size_in_bytes),
            # live-set bound: aliased (donated) buffers are one physical
            # buffer serving both an argument and an output
            peak_hbm_bytes=arg + outb + temp - alias,
        )
    return out


def entry_report(dtype: str = "int32") -> list[dict]:
    """One attribution row per compiled engine entry at the canonical
    envelope geometry. Memoized per dtype (the /cost endpoint, bench, and
    the perf ratchet share one compile set)."""
    if dtype in _REPORT_CACHE:
        return _REPORT_CACHE[dtype]
    from ..analysis.envelope import traced_entries

    rows: list[dict] = []
    with _x64_ctx(dtype):
        for rec in traced_entries(dtype):
            jits = rec.get("jits")
            if not jits or "args" not in rec:
                continue
            n_ops = int(rec.get("n_ops", 0)) or None
            for label, fn in jits:
                with warnings.catch_warnings():
                    # donating twins at tiny geometry warn about unusable
                    # donated buffers — deliberate (engine.batch)
                    warnings.simplefilter("ignore")
                    try:
                        lowered = fn.lower(*rec["args"])
                        compiled = lowered.compile()
                    except Exception as exc:  # backend-specific gaps
                        rows.append({
                            "entry": label,
                            "context": rec["context"],
                            "error": f"{type(exc).__name__}: {exc}",
                        })
                        continue
                stats = compiled_stats(compiled)
                stats.update(
                    entry=label,
                    context=rec["context"],
                    n_ops=n_ops,
                    jaxpr_eqns=_jaxpr_eqn_count(rec["closed"]),
                    flops_per_order=(
                        stats["flops"] / n_ops
                        if stats["flops"] is not None and n_ops
                        else None
                    ),
                    bytes_per_order=(
                        stats["bytes_accessed"] / n_ops
                        if stats["bytes_accessed"] is not None and n_ops
                        else None
                    ),
                )
                rows.append(stats)
    _REPORT_CACHE[dtype] = rows
    return rows


#: Donation-report geometry: cap = the engine's smallest cap class
#: (batch.CAP_CLASS_MIN), S=8 lanes, T=32 deep. The envelope memo's toy
#: geometry (cap=8, T=4) is the right cost for the DTYPE audit but too
#: small to measure donation — XLA layout padding at an 8-slot book is
#: tens of bytes either way and swamps the aliasing signal; at the
#: smallest REAL book class the donated-buffer reuse dominates and the
#: twin-vs-public comparison is stable.
_DONATION_GEOMETRY = (64, 8, 32)  # (cap, S, T)

_DONATION_CACHE: dict[str, list[dict]] = {}


def donation_report(dtype: str = "int32") -> list[dict]:
    """Donation effectiveness: each public entry vs its ``_donating``
    twin (engine.batch pairs them; PR 4's GL6xx application), compiled
    at the smallest realistic book class (_DONATION_GEOMETRY). Positive
    ``peak_hbm_saved_bytes`` / nonzero twin ``alias_bytes`` is the
    measured footprint win PR 4 could only argue for; a backend that
    does not implement donation reports zero savings — the twin's peak
    is still never WORSE than the public entry's, which tests pin."""
    if dtype in _DONATION_CACHE:
        return _DONATION_CACHE[dtype]
    import jax
    import jax.numpy as jnp

    from ..engine.batch import (
        batch_step,
        batch_step_donating,
        dense_batch_step,
        dense_batch_step_donating,
        lane_scan,
        lane_scan_donating,
    )
    from ..engine.book import BookConfig, DeviceOp, init_books

    cap, s, t = _DONATION_GEOMETRY
    out: list[dict] = []
    with _x64_ctx(dtype):
        config = BookConfig(cap=cap, max_fills=4, dtype=jnp.dtype(dtype))
        dt = jnp.dtype(dtype)
        books = init_books(config, s)
        op_grid = DeviceOp(**{
            f: jnp.zeros(
                (s, t),
                jnp.int32 if f in ("action", "side", "is_market") else dt,
            )
            for f in DeviceOp._fields
        })
        one_book = jax.tree.map(lambda a: a[0], books)
        ops_lane = jax.tree.map(lambda a: a[0], op_grid)
        lane_ids = jnp.zeros((s,), jnp.int32)
        pairs = (
            ("batch_step", batch_step, batch_step_donating,
             (config, books, op_grid)),
            ("dense_batch_step", dense_batch_step,
             dense_batch_step_donating, (config, books, lane_ids, op_grid)),
            ("lane_scan", lane_scan, lane_scan_donating,
             (config, one_book, ops_lane)),
        )
        # pairs is a host tuple (the arrays inside are lowered, never
        # iterated), and this report runs off-clock at boot/scrape time.
        for name, pub_fn, twin_fn, args in pairs:  # gomelint: disable=GL503
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                try:
                    pub = compiled_stats(pub_fn.lower(*args).compile())
                    twin = compiled_stats(twin_fn.lower(*args).compile())
                except Exception as exc:
                    out.append({
                        "entry": name,
                        "error": f"{type(exc).__name__}: {exc}",
                    })
                    continue
            saved = None
            if (
                pub["peak_hbm_bytes"] is not None
                and twin["peak_hbm_bytes"] is not None
            ):
                saved = pub["peak_hbm_bytes"] - twin["peak_hbm_bytes"]
            out.append({
                "entry": name,
                "geometry": {"cap": cap, "s": s, "t": t},
                "public_peak_hbm_bytes": pub["peak_hbm_bytes"],
                "donating_peak_hbm_bytes": twin["peak_hbm_bytes"],
                "peak_hbm_saved_bytes": saved,
                "donating_alias_bytes": twin["alias_bytes"],
                "donation_effective": bool(twin["alias_bytes"]),
            })
    _DONATION_CACHE[dtype] = out
    return out


#: The entries the perf ratchet gates (the engine's hot-path graphs).
RATCHET_ENTRIES = (
    "batch_step", "dense_batch_step", "lane_scan", "compact_accum",
    "scatter_grid",
)


def ratchet_metrics(dtype: str = "int32") -> dict:
    """Flat {metric: value} for scripts/perf_ratchet.py — lower is better
    for every metric. Metrics the backend declines to report are simply
    absent (the ratchet skips them)."""
    out: dict[str, float] = {}
    for r in entry_report(dtype):
        if "error" in r or r["entry"] not in RATCHET_ENTRIES:
            continue
        name = r["entry"]
        if r.get("flops_per_order") is not None:
            out[f"{name}.flops_per_order"] = round(r["flops_per_order"], 3)
        if r.get("bytes_per_order") is not None:
            out[f"{name}.bytes_per_order"] = round(r["bytes_per_order"], 3)
        if r.get("peak_hbm_bytes") is not None:
            out[f"{name}.peak_hbm_bytes"] = int(r["peak_hbm_bytes"])
    return out


def bench_analytics(dtype: str = "int32") -> dict:
    """The compact analytic block bench.py folds into its JSON payload:
    per-entry flops/order, bytes/order, peak HBM, plus the donation
    savings — so BENCH_*.json snapshots carry the analytic trajectory
    alongside wall-clock orders/sec."""
    entries = {}
    for r in entry_report(dtype):
        if "error" in r or r["entry"] not in RATCHET_ENTRIES:
            continue
        entries[r["entry"]] = {
            "flops_per_order": r.get("flops_per_order"),
            "bytes_per_order": r.get("bytes_per_order"),
            "arithmetic_intensity": r.get("arithmetic_intensity"),
            "peak_hbm_bytes": r.get("peak_hbm_bytes"),
        }
    return {
        "dtype": dtype,
        "entries": entries,
        "donation": {
            d["entry"]: d["peak_hbm_saved_bytes"]
            for d in donation_report(dtype)
        },
    }


def clear_cache() -> None:
    """Drop the memoized reports (tests that reconfigure jax call this)."""
    _REPORT_CACHE.clear()
    _DONATION_CACHE.clear()

"""Host-path observability: in-process sampling profiler, per-stage
gateway CPU attribution, and the host roofline (ISSUE 10).

Four rounds of observability (PRs 2, 5, 6, 8) made everything from gRPC
arrival to XLA execution visible — except the host CPU itself. ROADMAP
open item 1 names the gateway's per-order Python loop as the system-wide
bottleneck (~25-39K orders/sec admitted vs ~1M/sec/core consumed vs
~14M/sec matched on device), but until this module the only host
profiling in the tree was an offline consumer-only cProfile script. This
module is the host-CPU mirror of the device profiler (obs.profiler):

  * ``HostSampler`` — an in-process sampling profiler. Two capture
    modes around one stack walker (``sys._current_frames`` + the
    interrupted frame):

      signal  ``SIGPROF`` via ``signal.setitimer(ITIMER_PROF)`` — paced
              by process CPU time, so samples/period ≈ CPU seconds. The
              handler runs on the main thread, which means (a) it can
              only be armed FROM the main thread and (b) a main thread
              blocked in a C call (``server.wait_for_termination``)
              delays delivery — perfect for drills, wrong for the live
              service.
      thread  a daemon thread polling ``sys._current_frames()`` — paced
              by wall clock, samples blocked threads too (a wall
              profile), works from any thread and under pytest. The
              live-service default.

    ``mode="auto"`` picks signal when armed from the main thread and
    ``setitimer`` exists, else thread. Samples aggregate to bounded
    state: a ``deque(maxlen=keep)`` ring of recent raw stacks plus a
    capped distinct-stack counter (overflow lands in a ``<overflow>``
    bucket), with frames collapsed to ``module:function`` nodes and
    collapsed-stack/flamegraph text output (``root;...;leaf count``).

    Concurrency contract: sampler state (``_counts``/``_ring``) has ONE
    writer at a time — the SIGPROF handler (main thread) or the sampler
    daemon — mutating via single C-level ops (dict item set, deque
    append). Readers snapshot with ``dict(...)``/``list(...)``, also
    single C-level ops. No lock: the signal handler interrupts the main
    thread between bytecodes, so taking a lock there could deadlock
    against a reader holding it on the same thread.

  * ``stage_join()`` — joins samples against the tracer's stage
    taxonomy: each stack is attributed to the DEEPEST frame matching a
    ``STAGE_RULES`` entry, splitting the gateway admit path
    function-by-function (``_validate_add`` → validate,
    ``order_from_request`` → order_build, ``_mark`` → mark,
    ``_traced_emit``/``_emit`` → enqueue) plus codec encode/decode,
    batcher flush, and consumer drain. Measured wall time is
    distributed over samples, so per-stage **ns/order** always sums to
    the measured window and coverage (the attributed-sample fraction)
    is an explicit honesty number, never silently assumed.

  * ``gateway_drill()`` — a deterministic, host-only admit-loop drill:
    pre-built OrderRequests through a real ``OrderGateway`` on a real
    in-process bus (LocalPrePool-backed mark; no jax, no engine) under
    the sampler. Yields measured admit ns/order, achievable
    orders/sec/core, and the per-stage split.

  * ``host_roofline()`` / ``hostprof_artifact()`` — the committed
    table (``HOSTPROF_r01.json``): measured gateway admit
    orders/sec/core next to the committed consumer and device numbers,
    making the ~30x front-door mismatch one artifact instead of a
    ROADMAP sentence. This is the before/after baseline open item 1's
    columnar front-door rework will be judged against.

``HOSTPROF`` is the process singleton behind the ops ``/hostprof``
endpoint and the ``gome_hostprof_*`` gauges, armed from the
``ops.hostprof`` / ``hostprof_hz`` / ``hostprof_keep`` config knobs
(service.app, thread mode). Same hot-path contract as
TRACER/JOURNAL/TIMELINE/PROFILER: disabled (the default) its
``note_admit`` hook — called from the gateway on every accepted order —
costs one attribute check and ZERO allocations (pinned by
``sys.getallocatedblocks`` in tests).

Import discipline: NO jax and NO service imports at module scope —
``service.gateway`` imports ``HOSTPROF`` at import time, and the pure
pieces (sampler, stage join) must stay testable without a backend. The
drill imports the gateway/bus lazily.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque

from ..utils.metrics import REGISTRY

#: Live-sampler default cadence (Hz). Deliberately low and non-round: the
#: thread-mode sampler walks every thread's stack per tick, so the live
#: service pays ~hz * n_threads frame walks per second; 67 Hz keeps that
#: well under 1% of a core while still resolving percent-level stages
#: over a minute of traffic. Drills use their own, much higher rate.
DEFAULT_HZ = 67.0
#: Drill cadence (Hz): CPU-paced SIGPROF at ~1ms resolves a sub-second
#: admit loop into hundreds of samples.
DRILL_HZ = 997.0
DEFAULT_KEEP = 4096

#: Cap on DISTINCT aggregated stacks; past it, new stacks land in the
#: overflow bucket so sampler memory is bounded no matter the workload.
MAX_STACKS = 4096
MAX_DEPTH = 48

_OVERFLOW = ("<overflow>",)

#: The host stage taxonomy — the tracer's span names (utils.trace STAGES)
#: projected onto host CPU, plus the admit-path function splits the
#: tracer cannot see (its ingress span covers validate/build/mark as one
#: region). Order is the report's display order.
HOST_STAGES = (
    "ingress",        # gateway handler shell (pb response build, dispatch)
    "validate",       # OrderGateway._validate_add
    "order_build",    # order_from_request + fixed.scale
    "mark",           # pre-pool mark (MatchEngine.mark / prepool)
    "enqueue",        # _traced_emit/_emit + batcher.submit
    "codec_encode",   # bus.codec / bus.colwire encode
    "batch_flush",    # FrameBatcher flush path
    "codec_decode",   # bus.codec / bus.colwire / ordercodec decode
    "consumer_drain", # service.consumer (incl. engine time under it)
)

#: Stages that are the gateway admit path — the numerator of the live
#: admit orders/sec/core gauge.
ADMIT_STAGES = (
    "ingress", "validate", "order_build", "mark", "enqueue",
    "codec_encode", "batch_flush",
)

#: (module suffix, function name | None = any, stage). First match wins;
#: exact-function rules sit above module wildcards so e.g. a colwire
#: decode frame under the consumer module still classifies codec_decode.
STAGE_RULES = (
    ("service.gateway", "_validate_add", "validate"),
    ("service.gateway", "order_from_request", "order_build"),
    ("gome_tpu.fixed", "scale", "order_build"),
    # Columnar admit core (round 11): the array-native equivalents of the
    # scalar stages above, mapped onto the SAME stage names so r01/r02
    # profiles stay comparable column for column.
    ("service.gateway", "_vector_scale", "validate"),
    ("service.gateway", "_recheck_rows", "validate"),
    ("service.gateway", "_intern", "order_build"),
    ("service.gateway", "orders_from_columns", "order_build"),
    ("service.gateway", "_mark_cols", "mark"),
    ("service.gateway", "_unmark_cols", "mark"),
    ("service.gateway", "_emit_cols", "enqueue"),
    ("service.batcher", "submit_block", "enqueue"),
    ("bus.colwire", "encode_order_block", "codec_encode"),
    ("bus.colwire", "encode_order_frame_blocks", "codec_encode"),
    ("engine.orchestrator", "mark", "mark"),
    ("engine.orchestrator", "unmark", "mark"),
    ("engine.orchestrator", "_prekey", "mark"),
    ("engine.prepool", None, "mark"),
    ("obs.hostprof", "_drill_mark", "mark"),
    ("service.gateway", "_traced_emit", "enqueue"),
    ("service.gateway", "_emit", "enqueue"),
    ("service.batcher", "submit", "enqueue"),
    ("bus.codec", "encode_order", "codec_encode"),
    ("bus.codec", "encode_match_result", "codec_encode"),
    ("bus.colwire", "encode_order_frame", "codec_encode"),
    ("bus.colwire", "encode_event_frame", "codec_encode"),
    ("bus.codec", "decode_order", "codec_decode"),
    ("bus.codec", "decode_match_result", "codec_decode"),
    ("bus.colwire", "decode_order_frame", "codec_decode"),
    ("bus.colwire", "decode_event_frame", "codec_decode"),
    ("bus.ordercodec", None, "codec_decode"),
    ("service.batcher", None, "batch_flush"),
    ("service.consumer", None, "consumer_drain"),
    ("service.gateway", "DoOrder", "ingress"),
    ("service.gateway", "DeleteOrder", "ingress"),
    ("service.gateway", "DoOrderBatch", "ingress"),
    ("service.gateway", "DoOrderStream", "ingress"),
    ("service.gateway", "_apply_entries", "ingress"),
    ("service.gateway", "_apply_columnar", "ingress"),
    ("service.gateway", "_begin_trace", "ingress"),
    ("engine.orchestrator", "mark_frame", "mark"),
    ("engine.orchestrator", "unmark_frame", "mark"),
)


# ---------------------------------------------------------------------------
# the sampler


#: code object -> "module:function" node string, so steady-state sampling
#: allocates one string per DISTINCT code object, not per sample. Single
#: writer at a time (the sampling context); dict item set/get are single
#: C-level ops.
_NODE_CACHE: dict = {}


def _frame_node(frame) -> str:
    code = frame.f_code
    node = _NODE_CACHE.get(code)
    if node is None:
        mod = frame.f_globals.get("__name__", "?")
        func = getattr(code, "co_qualname", None) or code.co_name
        node = f"{mod}:{func}"
        _NODE_CACHE[code] = node
    return node


# One capture at a time: start()/stop() and the sampling tick (SIGPROF
# handler or poller thread) are serialized by the capture lifecycle
# (HostProfiler holds its _lock across arm/disarm), and the handler must
# never block, so this class carries NO lock by design.
class HostSampler:  # single-writer: the active capture (see note above)
    """In-process sampling profiler over ``module:function`` stacks.

    ``start()`` arms one of two capture modes (module docstring); both
    feed ``_note()``: a bounded ring of recent raw stacks plus a capped
    distinct-stack counter. ``all_threads=False`` (the drill shape)
    samples only the thread that called ``start()``; ``True`` (the live
    service shape) samples every thread except the sampler's own."""

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        keep: int = DEFAULT_KEEP,
        max_stacks: int = MAX_STACKS,
        max_depth: int = MAX_DEPTH,
        mode: str = "auto",
        all_threads: bool = False,
    ):
        if hz <= 0:
            raise ValueError(f"hz must be positive, got {hz}")
        if mode not in ("auto", "signal", "thread"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        self.hz = float(hz)
        self.keep = max(1, int(keep))
        self.max_stacks = max(1, int(max_stacks))
        self.max_depth = max(1, int(max_depth))
        self.mode = mode
        self.all_threads = all_threads
        self.mode_used: str | None = None
        # Single-writer sampler state (see module docstring): no lock by
        # design — the SIGPROF handler must never block.
        self._counts: dict = {}
        self._ring: deque = deque(maxlen=self.keep)
        self._samples = 0
        self._active = False
        self._target_tid: int | None = None
        self._thread: threading.Thread | None = None
        self._stop_evt: threading.Event | None = None
        self._prev_handler = None
        self._wall_s = 0.0
        self._cpu_s = 0.0
        self._t0 = 0.0
        self._c0 = 0.0

    # -- lifecycle ---------------------------------------------------------

    @staticmethod
    def _signal_ok() -> bool:
        return (
            hasattr(signal, "setitimer")
            and threading.current_thread() is threading.main_thread()
        )

    def start(self) -> "HostSampler":
        if self._active:
            return self
        mode = self.mode
        if mode == "auto" or (mode == "signal" and not self._signal_ok()):
            mode = "signal" if self._signal_ok() else "thread"
        self._target_tid = (
            None if self.all_threads else threading.get_ident()
        )
        self._t0 = time.perf_counter()
        self._c0 = time.process_time()
        if mode == "signal":
            period = 1.0 / self.hz
            self._prev_handler = signal.signal(
                signal.SIGPROF, self._on_sigprof
            )
            signal.setitimer(signal.ITIMER_PROF, period, period)
        else:
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._poll_loop, name="gome-hostprof", daemon=True
            )
            self._thread.start()
        self.mode_used = mode
        self._active = True
        return self

    def stop(self) -> None:
        if not self._active:
            return
        if self.mode_used == "signal":
            signal.setitimer(signal.ITIMER_PROF, 0.0, 0.0)
            signal.signal(signal.SIGPROF, self._prev_handler or signal.SIG_DFL)
            self._prev_handler = None
        else:
            self._stop_evt.set()
            self._thread.join(timeout=2.0)
            self._thread = None
            self._stop_evt = None
        self._wall_s += time.perf_counter() - self._t0
        self._cpu_s += time.process_time() - self._c0
        self._active = False

    # -- capture -----------------------------------------------------------

    def _on_sigprof(self, signum, frame) -> None:
        # `frame` is the interrupted main-thread frame — NOT this
        # handler's — so profiler frames never pollute main-thread stacks.
        if self._target_tid is not None:
            self._note(self._walk(frame))
            return
        current = sys._current_frames()
        current[threading.get_ident()] = frame
        self._record(current, skip_tid=None)

    def _poll_loop(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        evt = self._stop_evt
        while not evt.wait(period):
            self._record(sys._current_frames(), skip_tid=me)

    def _record(self, frames_by_tid: dict, skip_tid: int | None) -> None:
        target = self._target_tid
        for tid, frame in frames_by_tid.items():
            if tid == skip_tid:
                continue
            if target is not None and tid != target:
                continue
            self._note(self._walk(frame))

    def _walk(self, frame) -> tuple:
        # Leaf -> root, capped at max_depth (keeps the DEEPEST frames —
        # the ones stage attribution reads; far-root frames drop first).
        nodes = []
        depth = 0
        while frame is not None and depth < self.max_depth:
            nodes.append(_frame_node(frame))
            frame = frame.f_back
            depth += 1
        nodes.reverse()
        return tuple(nodes)

    def _note(self, stack: tuple) -> None:
        if not stack:
            return
        self._samples += 1
        self._ring.append(stack)
        counts = self._counts
        if stack in counts:
            counts[stack] += 1
        elif len(counts) < self.max_stacks:
            counts[stack] = 1
        else:
            counts[_OVERFLOW] = counts.get(_OVERFLOW, 0) + 1

    # -- read side ---------------------------------------------------------

    @property
    def samples(self) -> int:
        return self._samples

    @property
    def wall_s(self) -> float:
        live = time.perf_counter() - self._t0 if self._active else 0.0
        return self._wall_s + live

    @property
    def cpu_s(self) -> float:
        live = time.process_time() - self._c0 if self._active else 0.0
        return self._cpu_s + live

    def counts(self) -> dict:
        """Snapshot of {stack tuple: sample count} (one C-level copy —
        safe against the concurrent writer)."""
        return dict(self._counts)

    def ring(self) -> list:
        """The most recent raw stacks, oldest first."""
        return list(self._ring)

    def node_totals(self) -> dict:
        """{node: {"self": leaf samples, "total": samples anywhere on
        stack}} — the flat ``module:function`` aggregation."""
        out: dict = {}
        for stack, c in self.counts().items():
            for node in set(stack):
                row = out.setdefault(node, {"self": 0, "total": 0})
                row["total"] += c
            out[stack[-1]]["self"] += c
        return out

    def collapsed(self, max_lines: int = 0) -> str:
        """Collapsed-stack text (``root;frame;leaf count`` per line,
        highest count first) — feed to any flamegraph renderer."""
        items = sorted(
            self.counts().items(), key=lambda kv: (-kv[1], kv[0])
        )
        if max_lines > 0:
            items = items[:max_lines]
        return "".join(f"{';'.join(s)} {c}\n" for s, c in items)

    def reset(self) -> None:
        # gomelint: disable=GL704 — reset() is part of the capture
        # lifecycle: it runs before start() arms the tick (or after
        # stop() disarms it), never concurrently with it.
        self._counts = {}  # gomelint: disable=GL704
        self._ring = deque(maxlen=self.keep)  # gomelint: disable=GL704
        self._samples = 0  # gomelint: disable=GL704
        self._wall_s = self._cpu_s = 0.0  # gomelint: disable=GL704
        if self._active:
            self._t0 = time.perf_counter()  # gomelint: disable=GL704
            self._c0 = time.process_time()  # gomelint: disable=GL704


# ---------------------------------------------------------------------------
# stage join (pure)

#: node string -> stage | None memo; nodes repeat far more than they
#: vary, so classification is one dict hit steady-state.
_CLASSIFY_CACHE: dict = {}


def classify_node(node: str) -> str | None:
    """STAGE_RULES verdict for one ``module:function`` node (memoized).
    A rule's function name matches the LAST dotted component of the
    frame's qualname, so ``OrderGateway._validate_add`` matches rule
    function ``_validate_add``."""
    try:
        return _CLASSIFY_CACHE[node]
    except KeyError:
        pass
    mod, _, func = node.partition(":")
    leaf = func.rpartition(".")[2]
    stage = None
    for mod_suffix, fname, st in STAGE_RULES:
        if fname is not None and fname != leaf:
            continue
        if mod.endswith(mod_suffix):
            stage = st
            break
    _CLASSIFY_CACHE[node] = stage
    return stage


def classify_stack(stack: tuple) -> str | None:
    """Deepest-frame-wins: the stage of the deepest frame any rule
    matches, so time inside a mapped function's callees (json.dumps
    under encode_order, dataclass __init__ under order_from_request)
    rolls UP to the mapped function, while a deeper mapped frame
    (colwire decode under consumer.run_once) takes precedence."""
    for node in reversed(stack):
        stage = classify_node(node)
        if stage is not None:
            return stage
    return None


def stage_join(
    counts: dict,
    n_orders: int | None = None,
    window_ns: float | None = None,
) -> dict:
    """Join sampled stacks against the stage taxonomy.

    Measured wall (``window_ns``) is distributed over samples by share —
    ``stage_ns = stage_samples / total_samples * window_ns`` — so the
    per-stage ns/order rows plus the unattributed row always sum to the
    measured window: nothing is invented, and ``coverage_pct`` (the
    attributed share) says how much of the window the taxonomy explains.
    """
    total = sum(counts.values())
    per_stage: dict = {}
    unattributed = 0
    for stack, c in counts.items():
        st = classify_stack(stack)
        if st is None:
            unattributed += c
        else:
            per_stage[st] = per_stage.get(st, 0) + c
    out: dict = {
        "total_samples": total,
        "attributed_samples": total - unattributed,
        "coverage_pct": (
            round(100.0 * (total - unattributed) / total, 2) if total else 0.0
        ),
        "stages": {},
        "unattributed": {"samples": unattributed},
    }
    order = list(HOST_STAGES) + sorted(set(per_stage) - set(HOST_STAGES))
    for st in order:
        c = per_stage.get(st, 0)
        if not c:
            continue
        row = {"samples": c, "pct": round(100.0 * c / total, 2)}
        if n_orders and window_ns and total:
            row["ns_per_order"] = round(
                c / total * window_ns / n_orders, 1
            )
        out["stages"][st] = row
    if n_orders and window_ns and total:
        out["unattributed"]["ns_per_order"] = round(
            unattributed / total * window_ns / n_orders, 1
        )
    return out


# ---------------------------------------------------------------------------
# the gateway admit drill (host-only: no jax, no engine)


def _drill_requests(n: int, seed: int, n_symbols: int = 64,
                    del_every: int = 8) -> list:
    """n pre-built (OrderRequest, is_cancel) pairs, deterministic in
    (n, seed). Pre-built so the sampled loop measures the ADMIT path,
    not request construction."""
    from ..api import order_pb2 as pb

    reqs = []
    for i in range(n):
        k = (i * 2654435761 + seed) & 0xFFFFFFFF  # Knuth hash: cheap, fixed
        reqs.append((
            pb.OrderRequest(
                uuid=f"u{k % 16}",
                oid=f"d{seed}-{i}",
                symbol=f"sym{k % n_symbols}",
                transaction=pb.SALE if k & 1 else pb.BUY,
                price=1.0 + (k % 1000) / 1e4,
                volume=1.0 + (k % 7),
            ),
            i % del_every == del_every - 1,
        ))
    return reqs


def _drill_batches(reqs: list, batch_n: int) -> list:
    """Pre-built OrderBatchRequest protos (cancel masks preserved) from
    _drill_requests pairs — the columnar drill's unit of work. Pre-built
    for the same reason the scalar requests are: the sampled loop
    measures ADMIT, not proto construction."""
    from ..api import order_pb2 as pb

    batches = []
    for i in range(0, len(reqs), batch_n):
        chunk = reqs[i : i + batch_n]
        batches.append(
            pb.OrderBatchRequest(
                orders=[r for r, _ in chunk],
                cancel=[c for _, c in chunk],
            )
        )
    return batches


def _drill_mark(pool, order) -> None:
    """The drill's pre-pool mark: the reference's S:U:O key into a
    LocalPrePool — same work shape as MatchEngine.mark/_prekey without
    constructing an engine (no jax in the drill)."""
    pool.add((order.symbol, order.uuid, order.oid))


def _drill_gateway(columnar: bool = False):
    """A fresh OrderGateway on a fresh in-process bus (per round, so the
    memory queue's log never grows unbounded across rounds). Returns
    (gateway, batcher) — batcher is None on the scalar path; the
    columnar variant gets the bulk pre-pool markers and a FrameBatcher
    whose deadline can never fire mid-round (the drill flushes inside
    its own timing window, then close()s the round's batcher outside
    it)."""
    from ..bus import MemoryQueue, QueueBus
    from ..engine.prepool import LocalPrePool
    from ..service.gateway import OrderGateway

    pool = LocalPrePool()
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    batcher = None
    if columnar:
        from ..service.batcher import FrameBatcher

        try:
            # The columnar path's production marker: the fused C pass
            # (native/hostops.cc, ~8.7M marks/sec). LocalPrePool's numpy
            # row-select is the fallback where the library isn't built.
            from ..engine.prepool import NativePrePool

            pool = NativePrePool()
        except (RuntimeError, OSError):
            pass
        batcher = FrameBatcher(
            bus.order_queue, max_n=2048, max_wait_s=60.0
        )
    gateway = OrderGateway(
        bus,
        accuracy=8,
        mark=lambda order: _drill_mark(pool, order),
        unmark=lambda order: pool.discard(
            (order.symbol, order.uuid, order.oid)
        ),
        mark_frame=pool.mark_frame,
        unmark_frame=pool.unmark_frame,
        batcher=batcher,
        columnar=columnar,
    )
    return gateway, batcher


def gateway_drill(
    n_orders: int = 30_000,
    hz: float = DRILL_HZ,
    seed: int = 7,
    min_samples: int = 350,
    max_rounds: int = 6,
    mode: str = "auto",
    path: str = "scalar",
    batch_n: int = 1024,
) -> dict:
    """Measure the gateway admit path: drive pre-built requests through
    ``DoOrder``/``DeleteOrder`` (path="scalar") or the SAME seeded flow
    as OrderBatchRequests through the columnar ``DoOrderBatch`` core +
    FrameBatcher (path="columnar"), on an in-process bus under the
    sampler. Repeats the n_orders round (fresh gateway each round) until
    the sampler holds ``min_samples`` stacks or ``max_rounds`` is hit,
    so the stage split is statistically meaningful while the admit
    ns/order itself is a plain wall/N measurement. Columnar rounds are
    ~100x shorter, so callers wanting a tight stage split pass a higher
    max_rounds; the final in-window flush() charges the frame join to
    the admit cost it belongs to."""
    if path not in ("scalar", "columnar"):
        raise ValueError(f"unknown drill path {path!r}")
    columnar = path == "columnar"
    reqs = _drill_requests(n_orders, seed)
    batches = _drill_batches(reqs, batch_n) if columnar else None
    # Warm pb internals, codec, and the admit path outside the window.
    warm, warm_b = _drill_gateway(columnar=columnar)
    if columnar:
        for breq in batches[: max(1, 4096 // batch_n)]:
            warm.DoOrderBatch(breq, None)
        warm_b.close()
    else:
        for req, is_del in reqs[:256]:
            (warm.DeleteOrder if is_del else warm.DoOrder)(req, None)

    sampler = HostSampler(
        hz=hz, keep=DEFAULT_KEEP, mode=mode, all_threads=False
    )
    wall_ns = 0
    done = 0
    rounds = 0
    sampler.start()
    try:
        while rounds < max_rounds and (
            done == 0 or sampler.samples < min_samples
        ):
            gateway, batcher = _drill_gateway(columnar=columnar)
            if columnar:
                do_batch = gateway.DoOrderBatch
                t0 = time.perf_counter_ns()
                for breq in batches:
                    do_batch(breq, None)
                batcher.flush()
                wall_ns += time.perf_counter_ns() - t0
                batcher.close()  # outside the window: thread teardown
            else:
                do_order = gateway.DoOrder
                do_delete = gateway.DeleteOrder
                t0 = time.perf_counter_ns()
                for req, is_del in reqs:
                    if is_del:
                        do_delete(req, None)
                    else:
                        do_order(req, None)
                wall_ns += time.perf_counter_ns() - t0
            done += len(reqs)
            rounds += 1
    finally:
        sampler.stop()

    ns_per_order = wall_ns / max(done, 1)
    join = stage_join(sampler.counts(), n_orders=done, window_ns=wall_ns)
    out = {
        "kind": "gateway_admit_drill",
        "path": path,
        "seed": seed,
        "orders": done,
        "rounds": rounds,
        "wall_s": round(wall_ns / 1e9, 4),
        "admit_ns_per_order": round(ns_per_order, 1),
        "admit_orders_per_sec_per_core": round(1e9 / ns_per_order)
        if ns_per_order > 0
        else None,
        "sampler": {
            "mode": sampler.mode_used,
            "hz": hz,
            "samples": sampler.samples,
            "cpu_s": round(sampler.cpu_s, 4),
            "wall_s": round(sampler.wall_s, 4),
        },
        "coverage_pct": join["coverage_pct"],
        "stages": join["stages"],
        "unattributed": join["unattributed"],
        "collapsed": sampler.collapsed(max_lines=200),
        "note": (
            "host-only admit loop: pre-built OrderRequests -> "
            "OrderGateway (LocalPrePool mark, JSON codec, in-process "
            "MemoryQueue publish); ns/order is wall/N, per-stage rows "
            "distribute that wall by sampled share"
        )
        if not columnar
        else (
            "host-only columnar admit loop: pre-built OrderBatchRequests "
            "-> OrderGateway._apply_columnar (numpy masks, bulk "
            "LocalPrePool mark_frame, GCO4 block encode, FrameBatcher "
            "submit_block, in-process MemoryQueue publish); the final "
            "flush is inside the timing window; ns/order is wall/N, "
            "per-stage rows distribute that wall by sampled share"
        ),
    }
    if columnar:
        out["batch_n"] = batch_n
    return out


# ---------------------------------------------------------------------------
# the host roofline


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def _artifact_value(root: str, name: str, path: tuple):
    try:
        with open(os.path.join(root, name), encoding="utf-8") as fh:
            doc = json.load(fh)
        for key in path:
            doc = doc[key]
        return doc
    except (OSError, KeyError, TypeError, ValueError):
        return None


def host_roofline(drill: dict, root: str | None = None) -> dict:
    """The host-vs-device orders/sec table: the drill's measured gateway
    admit rate next to the committed consumer (BENCH_SERVICE_r05
    headline, orders/sec/core) and device (BENCH_r05, orders/sec)
    numbers — ROADMAP open item 1's ~30x front-door mismatch as one
    committed row set. Missing artifacts degrade to absent rows, never
    an exception."""
    root = root or _repo_root()
    admit = drill.get("admit_orders_per_sec_per_core")
    out: dict = {
        "host_gateway_admit": {
            "orders_per_sec_per_core": admit,
            "source": "measured (gateway_drill, this artifact)",
        },
    }
    consumer = _artifact_value(
        root, "BENCH_SERVICE_r05.json", ("headline", "value")
    )
    if consumer is not None:
        out["host_consumer_drain"] = {
            "orders_per_sec_per_core": consumer,
            "source": "BENCH_SERVICE_r05.json headline (mixed stream)",
        }
        if admit:
            out["front_door_mismatch_consumer_vs_gateway"] = round(
                consumer / admit, 1
            )
    device = _artifact_value(root, "BENCH_r05.json", ("parsed", "value"))
    if device is not None:
        out["device_matching"] = {
            "orders_per_sec": device,
            "source": "BENCH_r05.json (pallas kernel, device bench)",
        }
        if admit:
            out["front_door_mismatch_device_vs_gateway"] = round(
                device / admit, 1
            )
    out["note"] = (
        "the gateway's per-order Python admit loop is the system-wide "
        "bottleneck (ROADMAP open item 1); this table is the measured "
        "before-baseline the columnar front-door rework cites"
    )
    return out


def hostprof_artifact(
    n_orders: int = 30_000,
    hz: float = DRILL_HZ,
    seed: int = 7,
    min_samples: int = 800,
    max_rounds: int = 8,
    artifact: str = "HOSTPROF_r01",
    path: str = "scalar",
    batch_n: int = 1024,
) -> dict:
    """The HOSTPROF_rNN.json payload: the gateway admit drill (per-stage
    ns/order, >= 80% coverage by construction of the stage map) plus the
    host-vs-device roofline table. Defaults reproduce HOSTPROF_r01 (the
    scalar before-baseline); artifact="HOSTPROF_r02", path="columnar"
    (with a much higher max_rounds — columnar rounds are ~100x shorter)
    produces the columnar after-measurement the perf ratchet gates."""
    import platform

    drill = gateway_drill(
        n_orders=n_orders,
        hz=hz,
        seed=seed,
        min_samples=min_samples,
        max_rounds=max_rounds,
        path=path,
        batch_n=batch_n,
    )
    return {
        "artifact": artifact,
        "method": (
            "in-process sampling profiler (obs.hostprof.HostSampler, "
            f"{drill['sampler']['mode']} mode @ {hz} Hz) over a "
            "deterministic gateway admit drill; stage rows join samples "
            "against the tracer stage taxonomy (deepest mapped frame "
            "wins) and distribute measured wall by sampled share"
        ),
        "python": platform.python_version(),
        "drill": drill,
        "roofline": host_roofline(drill),
    }


def bench_host(
    n_orders: int = 16_384, min_samples: int = 256, seed: int = 7
) -> dict:
    """The compact ``"host"`` block bench.py folds into the mixed-stream
    service payload next to ``"analytic"``/``"measured"``: admit
    ns/order + orders/sec/core, per-stage ns/order, sample counts."""
    drill = gateway_drill(
        n_orders=n_orders, min_samples=min_samples, seed=seed
    )
    return {
        "admit_ns_per_order": drill["admit_ns_per_order"],
        "admit_orders_per_sec_per_core": (
            drill["admit_orders_per_sec_per_core"]
        ),
        "coverage_pct": drill["coverage_pct"],
        "sampler_mode": drill["sampler"]["mode"],
        "samples": drill["sampler"]["samples"],
        "stage_ns_per_order": {
            st: row.get("ns_per_order")
            for st, row in drill["stages"].items()
        },
    }


def bench_admit(
    n_orders: int = 16_384,
    seed: int = 7,
    min_samples: int = 64,
    batch_n: int = 1024,
) -> dict:
    """The compact ``"admit"`` block bench.py folds into the mixed-stream
    service payload (and serves under ``--admit``): scalar vs columnar
    admit on the IDENTICAL seeded flow, side by side with the speedup
    ratio — the front-door rework's headline comparison, cheap enough
    for CI."""
    scalar = gateway_drill(
        n_orders=n_orders, seed=seed, min_samples=min_samples,
        max_rounds=2, path="scalar",
    )
    columnar = gateway_drill(
        n_orders=n_orders, seed=seed, min_samples=min_samples,
        max_rounds=24, path="columnar", batch_n=batch_n,
    )

    def _row(d: dict) -> dict:
        return {
            "admit_ns_per_order": d["admit_ns_per_order"],
            "admit_orders_per_sec_per_core": (
                d["admit_orders_per_sec_per_core"]
            ),
            "orders": d["orders"],
            "rounds": d["rounds"],
            "coverage_pct": d["coverage_pct"],
        }

    out = {
        "kind": "admit_bench",
        "seed": seed,
        "batch_n": batch_n,
        "scalar": _row(scalar),
        "columnar": _row(columnar),
    }
    s, c = scalar["admit_ns_per_order"], columnar["admit_ns_per_order"]
    if s and c:
        out["speedup_x"] = round(s / c, 2)
    return out


# ---------------------------------------------------------------------------
# the process singleton


class HostProfiler:
    """The HOSTPROF singleton behind the ops ``/hostprof`` endpoint and
    the ``gome_hostprof_*`` gauges.

    Disabled by default. ``install()`` (service.app, from the
    ``ops.hostprof`` knob) arms a live thread-mode sampler (started and
    stopped with the service) and registers the gauges; ``drill()`` runs
    the deterministic admit drill on demand and keeps the last report
    for the endpoint/gauges. ``note_admit`` is the hot-path hook — the
    gateway calls it per accepted order, so the disabled cost is ONE
    attribute check and zero allocations."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sampler: HostSampler | None = None  # guarded by self._lock (armed ⇔ sampler)
        self._admits: int | None = None  # guarded by self._lock
        self._hz = DEFAULT_HZ  # guarded by self._lock
        self._keep = DEFAULT_KEEP  # guarded by self._lock
        self._last_drill: dict | None = None  # guarded by self._lock

    @property
    def enabled(self) -> bool:
        return self._sampler is not None  # gomelint: disable=GL402

    def install(
        self,
        hz: float = DEFAULT_HZ,
        keep_n: int = DEFAULT_KEEP,
        mode: str = "thread",
        registry=None,
    ) -> "HostProfiler":
        """Arm the live sampler (NOT started — service.app starts it
        with the service so the wall profile covers served traffic only)
        and register the gauges. Thread mode by default: the live
        service's main thread blocks in C calls, where SIGPROF delivery
        stalls (module docstring)."""
        with self._lock:
            if self._sampler is None:
                self._sampler = HostSampler(
                    hz=hz, keep=keep_n, mode=mode, all_threads=True
                )
            if self._admits is None:
                self._admits = 0
            self._hz = hz
            self._keep = keep_n
        self._export(registry or REGISTRY)
        return self

    def disable(self) -> None:
        with self._lock:
            sampler, self._sampler = self._sampler, None
            self._admits = None
            self._last_drill = None
        if sampler is not None:
            sampler.stop()

    def start(self) -> None:
        """Start the live sampler thread (service.app start())."""
        with self._lock:
            sampler = self._sampler
        if sampler is not None:
            sampler.start()

    def stop(self) -> None:
        """Stop the live sampler thread; stays armed (samples keep)."""
        with self._lock:
            sampler = self._sampler
        if sampler is not None:
            sampler.stop()

    # ------------------------------------------------------------------
    # hot path

    def note_admit(self, n: int = 1) -> None:
        """One accepted order (ADD or DEL) left the gateway into the
        pipeline. Disabled: one attribute check, zero allocations."""
        if self._admits is None:  # gomelint: disable=GL402 — lock-free
            return  # fast check; the locked add below re-validates
        with self._lock:
            if self._admits is not None:
                self._admits += n

    # ------------------------------------------------------------------
    # reports

    def drill(
        self,
        n_orders: int = 8192,
        min_samples: int = 128,
        max_rounds: int = 4,
        seed: int = 7,
    ) -> dict:
        """Run the deterministic admit drill now and keep the report for
        the endpoint/gauges. Sub-second of bounded work — ops surface,
        never the serving path."""
        rep = gateway_drill(
            n_orders=n_orders,
            min_samples=min_samples,
            max_rounds=max_rounds,
            seed=seed,
        )
        with self._lock:
            if self._sampler is not None:
                self._last_drill = rep
        return rep

    def last_drill(self) -> dict | None:
        with self._lock:
            return self._last_drill

    def live_report(self) -> dict:
        """Stage join over the LIVE sampler's stacks. Thread mode is a
        wall profile: blocked threads (a consumer waiting on the bus)
        sample at full rate, so stage shares mean wall residency, not
        CPU burn; ns/order rows divide sampled wall by note_admit'd
        orders."""
        with self._lock:
            sampler = self._sampler
            admits = self._admits
        if sampler is None:
            return {"enabled": False}
        wall_ns = sampler.wall_s * 1e9
        join = stage_join(
            sampler.counts(),
            n_orders=admits or None,
            window_ns=wall_ns or None,
        )
        join.update(
            enabled=True,
            mode=sampler.mode_used,
            sampling=sampler._active,
            wall_s=round(sampler.wall_s, 3),
            admits=admits,
        )
        return join

    def collapsed(self) -> str:
        """Collapsed stacks for ``/hostprof?format=collapsed``: the live
        sampler's when it has samples, else the last drill's."""
        with self._lock:
            sampler = self._sampler
            drill = self._last_drill
        if sampler is None:
            return "# hostprof disabled\n"
        if sampler.samples:
            return sampler.collapsed()
        if drill is not None and drill.get("collapsed"):
            return drill["collapsed"]
        return "# hostprof: no samples yet\n"

    def payload(self, run_drill: bool = False) -> dict:
        """The ops ``/hostprof`` JSON body. ``?drill=1`` runs the admit
        drill on demand; drill errors degrade to an ``error`` field,
        never a 500."""
        if not self.enabled:
            return {"enabled": False, "live": None, "drill": None}
        err = None
        if run_drill:
            try:
                self.drill()
            except Exception as exc:  # pragma: no cover - env-specific
                err = f"{type(exc).__name__}: {exc}"
        with self._lock:
            hz, keep = self._hz, self._keep
            admits = self._admits
        out = {
            "enabled": True,
            "hz": hz,
            "keep": keep,
            "admits": admits,
            "live": self.live_report(),
            "drill": self.last_drill(),
        }
        if err:
            out["error"] = err
        return out

    # ------------------------------------------------------------------
    # gauges

    def _samples_total(self) -> int:
        with self._lock:
            sampler = self._sampler
            drill = self._last_drill
        n = sampler.samples if sampler is not None else 0
        if drill is not None:
            n += drill["sampler"]["samples"]
        return n

    def _stage_ns(self, stage: str) -> float:
        """Per-stage ns/order for the gauges: the drill's measured row
        when one exists (CPU-paced, deterministic flow), else the live
        wall-profile row."""
        with self._lock:
            drill = self._last_drill
        src = drill["stages"] if drill is not None else (
            self.live_report().get("stages") or {}
        )
        v = (src.get(stage) or {}).get("ns_per_order")
        return float(v) if v is not None else 0.0

    def _admit_rate(self) -> float:
        """Admit orders/sec/core: the drill's measured number when one
        exists, else orders note_admit'd per second of live admit-stage
        sampled wall."""
        with self._lock:
            drill = self._last_drill
            sampler = self._sampler
            admits = self._admits
        if drill is not None:
            return float(drill["admit_orders_per_sec_per_core"] or 0.0)
        if sampler is None or not admits or not sampler.samples:
            return 0.0
        counts = sampler.counts()
        admit_samples = sum(
            c
            for stack, c in counts.items()
            if classify_stack(stack) in ADMIT_STAGES
        )
        admit_s = (
            admit_samples / sampler.samples
        ) * sampler.wall_s
        return admits / admit_s if admit_s > 0 else 0.0

    def _export(self, reg) -> None:
        reg.callback_gauge(
            "gome_hostprof_samples_total",
            "Host stack samples captured since arm (live sampler + last "
            "drill)",
            lambda: self._samples_total(),
        )
        reg.callback_gauge(
            "gome_hostprof_admit_orders_per_sec_per_core",
            "Achievable gateway admit rate from measured host ns/order "
            "(last drill, else live window)",
            lambda: self._admit_rate(),
        )
        for st in HOST_STAGES:
            reg.callback_gauge(
                "gome_hostprof_stage_ns_per_order",
                "Measured host ns/order per stage (sampled share of the "
                "measured window / orders)",
                lambda s=st: self._stage_ns(s),
                labels={"stage": st},
            )


HOSTPROF = HostProfiler()

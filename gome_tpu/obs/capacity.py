"""Capacity observatory (round 13): coordinated-omission-safe latency.

Every latency number the repo committed before this round was measured
closed-loop: the driver sends an order, waits for it to finish, sends the
next. Under saturation that loop silently slows the arrival process down
to whatever the service can absorb, so queueing delay never shows up in
the percentiles — the classic *coordinated omission* benchmarking sin.
This module is the instrument that fixes it, in three cooperating
pieces:

  * :class:`LogHistogram` — an HDR-style log-bucketed latency histogram
    with a bounded relative error per bucket, a sparse count map, an
    associative :meth:`~LogHistogram.merge`, and a byte-stable
    :meth:`~LogHistogram.to_bytes` / :meth:`~LogHistogram.from_bytes`
    wire form so per-process recorders can be merged losslessly into one
    fleet histogram.
  * :class:`OpenLoopSchedule` — the *intended* arrival clock. An
    open-loop driver derives each order's intended send time from the
    offered rate alone; latency is charged from the intended time, so an
    order delayed in the driver's own send queue still pays for the wait.
  * ladder helpers — :func:`find_knee` (first offered-rate point where
    delivered/offered drops below the floor or the corrected p99 blows
    its budget), :func:`monotone_ladder`, :func:`attribution_check`
    (do the per-stage "where the order spends its time" rows sum to the
    measured e2e mean?), and :func:`saturated_stage`.

``CAPACITY`` is the process-global singleton that arms an ops ``/capacity``
payload + ``gome_capacity_*`` gauges from a committed sweep verdict
(``CAPACITY_r01.json``, schema ``gome-capacity-verdict-v1``) — same
disabled-singleton contract as ``FLEET``/``HOSTPROF``: unarmed it is one
attribute check and serves ``{"enabled": False}``.

The existing ``utils.metrics.Histogram`` (fixed buckets, exposition
format) stays for /metrics; committed latency *claims* migrate here.
Stdlib-only on purpose: ``scripts/capacity.py``, ``bench.py`` and the
fleet drill import this from driver processes that must not pay a jax
import.
"""

from __future__ import annotations

import json
import math
import struct
import threading

__all__ = [
    "LogHistogram",
    "OpenLoopSchedule",
    "CAPACITY",
    "CapacityObservatory",
    "find_knee",
    "monotone_ladder",
    "attribution_check",
    "saturated_stage",
    "load_verdict",
    "SCHEMA",
]

SCHEMA = "gome-capacity-verdict-v1"

_MAGIC = b"GCH1"
_HEADER = struct.Struct("<4sdddQI")  # magic, rel_err, min, max, count, npairs
_PAIR = struct.Struct("<iq")  # bucket index (int32), count (int64)


class LogHistogram:
    """Log-bucketed latency histogram with bounded relative error.

    Bucket boundaries grow geometrically by ``g = (1 + rel_err)**2``;
    a value is reported as the geometric mean of its bucket, so every
    estimate ``e`` of a recorded value ``v`` in ``[min_value, max_value)``
    satisfies ``1/(1+rel_err) < e/v <= (1+rel_err)`` (the property test
    in tests/test_capacity.py pins this). Values below ``min_value``
    land in a single underflow bucket (index 0, estimated at
    ``min_value/2``); values at or above ``max_value`` saturate into the
    top bucket. Counts are a sparse dict so an idle histogram costs a
    few hundred bytes regardless of the dynamic range.

    The entire state is the integer count map — the mean, like the
    percentiles, is derived from bucket estimates (same bounded relative
    error). That makes ``merge`` exactly associative and commutative:
    recording a stream in one process, or a split of the same stream in
    two processes then merging, produce identical state — and identical
    ``to_bytes`` output, which is the cross-process contract the fleet
    sweep relies on.
    """

    __slots__ = (
        "rel_err", "min_value", "max_value",
        "_growth", "_log_growth", "_log_min", "_top_index",
        "_lock", "_counts", "_count",
    )

    def __init__(self, rel_err: float = 0.01,
                 min_value: float = 1e-6, max_value: float = 600.0):
        if not (0.0 < rel_err < 1.0):
            raise ValueError(f"rel_err out of range: {rel_err}")
        if not (0.0 < min_value < max_value):
            raise ValueError(
                f"need 0 < min_value < max_value, got {min_value}, {max_value}"
            )
        self.rel_err = float(rel_err)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self._growth = (1.0 + self.rel_err) ** 2
        self._log_growth = math.log(self._growth)
        self._log_min = math.log(self.min_value)
        # Bucket i >= 1 covers [min*g^(i-1), min*g^i); the top bucket is
        # the one containing max_value — larger values clamp into it.
        self._top_index = self._raw_index(self.max_value)
        self._lock = threading.Lock()
        self._counts: dict[int, int] = {}  # guarded by self._lock
        self._count = 0  # guarded by self._lock

    # -- bucket geometry -------------------------------------------------

    def _raw_index(self, value: float) -> int:
        # floor() on the log ratio, then nudge across float edges so the
        # half-open [lo, hi) contract holds exactly (the relative-error
        # property test walks bucket boundaries directly).
        i = 1 + int(math.floor(
            (math.log(value) - self._log_min) / self._log_growth
        ))
        if i < 1:
            i = 1
        while value < self.min_value * self._growth ** (i - 1):
            i -= 1
        while value >= self.min_value * self._growth ** i:
            i += 1
        return i

    def index(self, value: float) -> int:
        """Bucket index for ``value``: 0 underflow, else 1.._top_index."""
        if value != value or value < 0.0:  # NaN / negative: charge underflow
            return 0
        if value < self.min_value:
            return 0
        i = self._raw_index(value)
        return self._top_index if i > self._top_index else i

    def bucket_bounds(self, index: int) -> tuple[float, float]:
        """[lo, hi) covered by ``index`` (underflow reports [0, min))."""
        if index <= 0:
            return 0.0, self.min_value
        return (
            self.min_value * self._growth ** (index - 1),
            self.min_value * self._growth ** index,
        )

    def bucket_estimate(self, index: int) -> float:
        """Representative value: the geometric mean of the bucket."""
        if index <= 0:
            return self.min_value / 2.0
        lo, hi = self.bucket_bounds(index)
        return math.sqrt(lo * hi)

    # -- recording -------------------------------------------------------

    def record(self, value: float, count: int = 1) -> None:
        if count <= 0:
            return
        i = self.index(value)
        with self._lock:
            self._counts[i] = self._counts.get(i, 0) + count
            self._count += count

    def record_corrected(self, value: float, expected_interval: float) -> None:
        """Record ``value`` plus HDR-style coordinated-omission back-fill.

        When a *closed-loop* driver measures ``value`` but was supposed
        to issue one request every ``expected_interval`` seconds, the
        requests it failed to send while stalled would each have seen a
        progressively smaller wait: synthesize them at value - k*interval
        down to the interval. Open-loop drivers with true intended times
        (OpenLoopSchedule) don't need this — they record the real wait.
        """
        self.record(value)
        if expected_interval <= 0.0:
            return
        missing = value - expected_interval
        while missing >= expected_interval:
            self.record(missing)
            missing -= expected_interval

    # -- read side -------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def mean(self) -> float:
        """Bucket-estimate mean (same bounded relative error as the
        percentiles — sums of per-bucket geometric means, not raw
        values, so the mean survives merge/serialize exactly)."""
        with self._lock:
            items = list(self._counts.items())
            total = self._count
        if not total:
            return 0.0
        return math.fsum(
            c * self.bucket_estimate(i) for i, c in items
        ) / total

    def percentile(self, q: float) -> float:
        return self.percentiles((q,))[0]

    def percentiles(self, qs=(0.5, 0.9, 0.99, 0.999)) -> list[float]:
        """Bucket-estimate quantiles (one lock, one sorted walk)."""
        with self._lock:
            total = self._count
            items = sorted(self._counts.items())
        out = []
        for q in qs:
            if total == 0:
                out.append(0.0)
                continue
            rank = max(1.0, q * total)
            cum = 0
            est = self.bucket_estimate(items[-1][0])
            for idx, c in items:
                cum += c
                if cum >= rank:
                    est = self.bucket_estimate(idx)
                    break
            out.append(est)
        return out

    def summary(self, qs=(0.5, 0.9, 0.99, 0.999)) -> dict:
        ps = self.percentiles(qs)
        d = {"count": self.count, "mean_s": self.mean()}
        for q, p in zip(qs, ps):
            digits = f"{q:g}".split(".")[1]
            if len(digits) == 1:
                digits += "0"  # 0.5 -> p50, 0.999 -> p999
            d[f"p{digits}_s"] = p
        return d

    # -- merge + wire ----------------------------------------------------

    def _same_geometry(self, other: "LogHistogram") -> bool:
        return (
            self.rel_err == other.rel_err
            and self.min_value == other.min_value
            and self.max_value == other.max_value
        )

    def merge(self, other: "LogHistogram") -> None:
        if not self._same_geometry(other):
            raise ValueError(
                "merge across histogram geometries: "
                f"({self.rel_err}, {self.min_value}, {self.max_value}) vs "
                f"({other.rel_err}, {other.min_value}, {other.max_value})"
            )
        with other._lock:
            items = list(other._counts.items())
            n = other._count
        with self._lock:
            for idx, c in items:
                self._counts[idx] = self._counts.get(idx, 0) + c
            self._count += n

    def to_bytes(self) -> bytes:
        """Byte-stable wire form: same recorded state -> same bytes."""
        with self._lock:
            items = sorted(self._counts.items())
            n = self._count
        head = _HEADER.pack(
            _MAGIC, self.rel_err, self.min_value, self.max_value,
            n, len(items),
        )
        return head + b"".join(_PAIR.pack(i, c) for i, c in items)

    @classmethod
    def from_bytes(cls, data: bytes) -> "LogHistogram":
        if len(data) < _HEADER.size:
            raise ValueError(f"short histogram blob: {len(data)} bytes")
        magic, rel_err, mn, mx, n, npairs = _HEADER.unpack_from(data, 0)
        if magic != _MAGIC:
            raise ValueError(f"bad histogram magic: {magic!r}")
        want = _HEADER.size + npairs * _PAIR.size
        if len(data) != want:
            raise ValueError(
                f"histogram blob length {len(data)} != expected {want}"
            )
        h = cls(rel_err=rel_err, min_value=mn, max_value=mx)
        off = _HEADER.size
        counts = {}
        for _ in range(npairs):
            idx, c = _PAIR.unpack_from(data, off)
            off += _PAIR.size
            counts[idx] = c
        if sum(counts.values()) != n:
            raise ValueError("histogram blob count != sum of bucket counts")
        # single-writer: h is private to this frame until returned
        h._counts = counts
        h._count = n
        return h


class OpenLoopSchedule:
    """Intended arrival times for a constant offered rate (open loop).

    Order ``i`` (0-based) is *intended* to arrive at ``t0 + (i+1)/rate``
    regardless of how far behind the driver has fallen — that fixed
    clock is what makes the corrected latency ``completion - intended``
    immune to coordinated omission. ``batch_due(first, n)`` is the send
    deadline for a batch holding orders ``first..first+n-1``: the
    intended time of its *last* order (a batch is modeled as a front-end
    accumulator flushing when its newest order arrives).
    """

    __slots__ = ("rate", "t0", "interval")

    def __init__(self, rate: float, t0: float = 0.0):
        if rate <= 0.0:
            raise ValueError(f"rate must be positive: {rate}")
        self.rate = float(rate)
        self.t0 = float(t0)
        self.interval = 1.0 / self.rate

    def intended(self, i: int) -> float:
        return self.t0 + (i + 1) * self.interval

    def batch_due(self, first: int, n: int) -> float:
        return self.intended(first + n - 1)

    def accumulation_mean(self, n: int) -> float:
        """Mean wait an order spends in an n-order accumulator: for
        uniform spacing the j-th order of the batch waits
        (n-1-j)/rate, averaging (n-1)/(2*rate) exactly."""
        return (n - 1) / (2.0 * self.rate) if n > 1 else 0.0


# -- ladder analysis -----------------------------------------------------


def monotone_ladder(points: list) -> bool:
    """Offered rates strictly increase along the ladder."""
    rates = [p["offered_per_sec"] for p in points]
    return all(b > a for a, b in zip(rates, rates[1:]))


def find_knee(points: list, delivered_floor: float = 0.98,
              p99_budget_s: float | None = None):
    """First ladder point where the service stops keeping up.

    A point is past the knee when delivered/offered < ``delivered_floor``
    or (when a budget is given) the corrected p99 exceeds
    ``p99_budget_s``. Returns ``(index, reason)`` or ``(None, None)``.
    """
    for i, p in enumerate(points):
        offered = p["offered_per_sec"]
        delivered = p["delivered_per_sec"]
        if offered > 0 and delivered / offered < delivered_floor:
            return i, (
                f"delivered/offered {delivered / offered:.4f} "
                f"< {delivered_floor}"
            )
        if p99_budget_s is not None:
            p99 = p.get("corrected", {}).get("p99_s")
            if p99 is not None and p99 > p99_budget_s:
                return i, f"corrected p99 {p99:.4f}s > budget {p99_budget_s}s"
    return None, None


def attribution_check(rows: list, e2e_mean_s: float, tol: float = 0.05) -> dict:
    """Do the per-stage seconds/order rows sum to the measured e2e mean?

    Means add linearly across pipeline stages, so the honest check is
    sum(rows) vs the corrected histogram's mean — not a percentile.
    """
    total = sum(r["seconds_per_order"] for r in rows)
    frac = abs(total - e2e_mean_s) / e2e_mean_s if e2e_mean_s > 0 else 1.0
    return {
        "sum_s": total,
        "e2e_mean_s": e2e_mean_s,
        "frac_err": frac,
        "within_tol": bool(rows) and frac <= tol,
        "tol": tol,
    }


def saturated_stage(rows: list) -> str | None:
    """Name the busiest *server* stage (max utilization; queue rows carry
    utilization=None and never win)."""
    best, best_u = None, -1.0
    for r in rows:
        u = r.get("utilization")
        if u is not None and u > best_u:
            best, best_u = r["stage"], u
    return best


def load_verdict(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r} != {SCHEMA!r}"
        )
    return doc


# -- process-global singleton -------------------------------------------


class CapacityObservatory:
    """Serves the committed capacity verdict as ops payload + gauges.

    Same disabled-singleton contract as FLEET/HOSTPROF: module import
    costs nothing, ``payload()`` unarmed is ``{"enabled": False}``, and
    ``install(verdict)`` arms the ``/capacity`` payload plus the
    ``gome_capacity_*`` callback gauges on the given registry.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._verdict: dict | None = None  # guarded by self._lock

    @property
    def enabled(self) -> bool:
        return self._verdict is not None  # gomelint: disable=GL402 - off-lock fast check, worst case one stale payload

    def install(self, verdict: dict, registry=None) -> None:
        if verdict.get("schema") != SCHEMA:
            raise ValueError(
                f"capacity verdict schema {verdict.get('schema')!r} "
                f"!= {SCHEMA!r}"
            )
        with self._lock:
            self._verdict = verdict
        self._export(registry)

    def disable(self) -> None:
        with self._lock:
            self._verdict = None

    def _knee_point(self) -> dict | None:
        with self._lock:
            v = self._verdict
        if not v:
            return None
        knee = v.get("knee") or {}
        idx = knee.get("index")
        ladder = v.get("ladder") or []
        if idx is None or not (0 <= idx < len(ladder)):
            return None
        return ladder[idx]

    def _gauge(self, key: str) -> float:
        p = self._knee_point()
        if p is None:
            return 0.0
        if key == "offered":
            return float(p.get("offered_per_sec", 0.0))
        if key == "delivered":
            return float(p.get("delivered_per_sec", 0.0))
        if key == "p99":
            return float(p.get("corrected", {}).get("p99_s", 0.0))
        return 0.0

    def _export(self, registry=None) -> None:
        if registry is None:
            from ..utils.metrics import REGISTRY
            registry = REGISTRY
        registry.callback_gauge(
            "gome_capacity_points",
            "load-sweep ladder points in the installed capacity verdict",
            lambda: float(len((self._verdict or {}).get("ladder", []))),  # gomelint: disable=GL402 - gauge read, snapshot semantics
        )
        registry.callback_gauge(
            "gome_capacity_knee_offered_per_sec",
            "offered rate at the detected saturation knee",
            lambda: self._gauge("offered"),
        )
        registry.callback_gauge(
            "gome_capacity_knee_delivered_per_sec",
            "delivered rate at the detected saturation knee",
            lambda: self._gauge("delivered"),
        )
        registry.callback_gauge(
            "gome_capacity_corrected_p99_s_at_knee",
            "coordinated-omission-corrected p99 at the knee",
            lambda: self._gauge("p99"),
        )

    def payload(self) -> dict:
        with self._lock:
            v = self._verdict
        if v is None:
            return {"enabled": False}
        knee = v.get("knee") or {}
        return {
            "enabled": True,
            "schema": v.get("schema"),
            "mode": v.get("mode"),
            "pass": v.get("pass"),
            "points": len(v.get("ladder", [])),
            "knee": knee,
            "checks": v.get("checks", {}),
            "verdict": v,
        }


CAPACITY = CapacityObservatory()

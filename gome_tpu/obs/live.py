"""Live-buffer accounting — tagged ``jax.live_arrays()`` snapshots and a
steady-state leak detector.

The engine's device residency story is an argument, not a measurement:
books are "one stack", donation "reuses buffers", escalations "grow and
replay". ``jax.live_arrays()`` enumerates every device buffer the process
actually holds, so residency becomes data:

  * :func:`live_array_stats` — process-wide count/bytes (after a gc pass:
    dead-but-uncollected pytrees would otherwise read as residency);
  * :func:`pytree_stats` — count/bytes of one subsystem's pytree (the
    engine's book stack, a pending frame's compaction buffers, ...);
  * :class:`LiveBufferMonitor` — named subsystems exported as
    ``gome_hbm_resident_bytes{subsystem=...}`` callback gauges plus the
    process totals (``gome_live_arrays`` / ``gome_live_array_bytes``) —
    scrape-time reads, nothing on the hot path;
  * :func:`leak_report` / :func:`assert_steady_state` — the leak
    detector: at steady state an engine step must not grow the live
    buffer count (escalations and first-seen compiles allocate, so the
    caller settles those first). Asserted in tests/test_soak.py.
"""

from __future__ import annotations

import gc


def live_array_stats(collect: bool = True) -> dict:
    """Process-wide live device-buffer count and bytes. ``collect`` runs
    the gc first so reference cycles holding dead arrays (common in test
    suites) do not read as device residency."""
    import jax

    if collect:
        gc.collect()
    arrs = jax.live_arrays()
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:  # deleted between enumeration and read
            pass
    return {"count": len(arrs), "bytes": total}


def pytree_stats(tree) -> dict:
    """Count/bytes over one pytree's array leaves (host numpy leaves
    count too — a restored-but-not-yet-placed subsystem is still
    residency somewhere)."""
    import jax

    leaves = jax.tree.leaves(tree)
    n = 0
    total = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        n += 1
        total += int(nbytes)
    return {"count": n, "bytes": total}


class LiveBufferMonitor:
    """Named subsystems -> live-buffer gauges.

    ``register(name, fn)`` stores a zero-arg callable returning the
    subsystem's current pytree (called at snapshot/scrape time — the
    engine swaps its book stack on every step, so the monitor must not
    hold a reference). ``export(registry)`` wires scrape-time callback
    gauges; ``snapshot()`` is the /cost JSON form."""

    def __init__(self):
        self._sections: dict[str, object] = {}

    def register(self, name: str, fn) -> "LiveBufferMonitor":
        self._sections[name] = fn
        return self

    def snapshot(self) -> dict:
        out = {"total": live_array_stats()}
        subsystems = {}
        for name, fn in self._sections.items():
            try:
                subsystems[name] = pytree_stats(fn())
            except Exception as exc:  # a dead subsystem must not 500 /cost
                subsystems[name] = {"error": str(exc)}
        out["subsystems"] = subsystems
        return out

    def export(self, registry=None) -> None:
        """Register scrape-time gauges: per-subsystem
        ``gome_hbm_resident_bytes{subsystem=...}`` plus process totals."""
        from ..utils.metrics import REGISTRY

        registry = registry or REGISTRY
        registry.callback_gauge(
            "gome_live_arrays",
            "process-wide live device-buffer count (jax.live_arrays)",
            lambda: live_array_stats(collect=False)["count"],
        )
        registry.callback_gauge(
            "gome_live_array_bytes",
            "process-wide live device-buffer bytes (jax.live_arrays)",
            lambda: live_array_stats(collect=False)["bytes"],
        )
        for name, fn in self._sections.items():
            registry.callback_gauge(
                "gome_hbm_resident_bytes",
                "per-subsystem device-resident bytes",
                (lambda f: lambda: pytree_stats(f())["bytes"])(fn),
                labels={"subsystem": name},
            )


def service_monitor(service) -> LiveBufferMonitor:
    """The standard subsystem tagging for one EngineService/MatchEngine:
    the device book stack (the dominant steady-state residency) — reads
    go through the closure so engine growth/restore is always reflected."""
    mon = LiveBufferMonitor()
    engine = getattr(service, "engine", service)
    batch = getattr(engine, "batch", engine)
    mon.register("engine_books", lambda: batch.books)
    return mon


# -- leak detection --------------------------------------------------------


def leak_report(step_fn, steps: int = 8, settle: int = 2) -> dict:
    """Run ``step_fn`` ``settle`` times (escalations, first-seen compiles,
    and cache warms allocate legitimately), snapshot the live-buffer
    count, then run ``steps`` more and record the count after each. A
    steady-state engine loop must come back to the baseline every step —
    monotonic growth is a leaked device buffer (a retained checkpoint, an
    accumulator that outlived its frame, a cache without a bound).

    Returns {"baseline", "counts", "leaked"}: ``leaked`` = final count
    minus baseline (<= 0 means flat)."""
    for _ in range(settle):
        step_fn()
    baseline = live_array_stats()["count"]
    counts = []
    for _ in range(steps):
        step_fn()
        counts.append(live_array_stats()["count"])
    return {
        "baseline": baseline,
        "counts": counts,
        "leaked": (counts[-1] - baseline) if counts else 0,
    }


def assert_steady_state(
    step_fn, steps: int = 8, settle: int = 2, tolerance: int = 0
) -> dict:
    """leak_report + assertion: raises AssertionError when the loop leaks
    more than ``tolerance`` buffers end to end. Returns the report."""
    report = leak_report(step_fn, steps=steps, settle=settle)
    if report["leaked"] > tolerance:
        raise AssertionError(
            f"live device buffers grew by {report['leaked']} over "
            f"{steps} steady-state steps (baseline {report['baseline']}, "
            f"trajectory {report['counts']}) — leaked buffer(s)"
        )
    return report

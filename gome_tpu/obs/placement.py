"""Placement observatory (round 14): who flows where, and what it costs.

ROADMAP items 1 and 2 name the same disease — naive placement. The
committed artifacts show it from both ends: ``FLEET_r01.json`` measured a
1.56x partition order imbalance under the ``fnv1a % P`` symbol hash, and
``MULTICHIP_r06.json`` measured a D=8 dense shard skew of 3.64 — every
shard pads to the hottest shard's row block, so adding devices *loses*
throughput. Until this round nothing in the tree measured symbol flow,
lane occupancy, or padding waste, so the placement fix would have been a
guess. This module is the measurement substrate, in three pieces:

  * :class:`SpaceSaving` — a deterministic Space-Saving top-K sketch
    (Metwally et al.) over per-symbol order arrivals. Bounded memory
    (at most ``k`` tracked counters per writer), a per-key error bound
    (``count - err <= true <= count``), an *exactly associative and
    commutative* :meth:`~SpaceSaving.merge` (a lossless sparse add — the
    fleet rollup over M members holds at most ``M*k`` counters), and a
    byte-stable :meth:`~SpaceSaving.to_bytes` wire form like
    ``obs.capacity.LogHistogram`` so per-process sketches fold into one
    fleet-wide flow table.
  * :class:`OccupancyLedger` — the dispatch-side account: dispatched vs
    live rows per dense frame, padding rows/bytes, per-shard row blocks,
    plus per-lane EWMA dispatch rates. Fed by ``note_dispatch`` next to
    ``engine.batch._grid_geometry``; paired with the admit-side sketch it
    decomposes observed skew into *lane-placement skew* x *cap-class
    padding* (multiplicative, reconciling against the observed
    rows-per-live-lane within tolerance) plus the fleet-level
    *hash-partition imbalance* row.
  * ``PLACEMENT`` — the process-global singleton with the house
    disabled-contract (TIMELINE/FLEET/CAPACITY/HOSTPROF): unarmed, every
    hook is one attribute check and zero allocations
    (``sys.getallocatedblocks``-pinned in tests/test_placement.py);
    ``install()`` arms the ops ``/placement`` payload + the
    ``gome_placement_*`` gauges, optionally serving a committed what-if
    verdict (``PLACEMENT_r01.json``, schema ``gome-placement-verdict-v1``
    — written by ``scripts/placement_eval.py``).

The sketch and ledger are stdlib-only; numpy is imported lazily inside
armed hook bodies only (the gateway's columnar admit block hands numpy
index arrays straight through).
"""

from __future__ import annotations

import json
import struct
import threading
from pathlib import Path

__all__ = [
    "SpaceSaving",
    "OccupancyLedger",
    "PLACEMENT",
    "PlacementObservatory",
    "load_verdict",
    "SCHEMA",
    "DEFAULT_ROW_BYTES",
]

SCHEMA = "gome-placement-verdict-v1"

_MAGIC = b"GSS1"
_HEADER = struct.Struct("<4sIQI")  # magic, k, total, npairs
_KEYLEN = struct.Struct("<H")  # utf-8 key length
_PAIR = struct.Struct("<qq")  # count, err (int64)

#: Default padding cost per dispatched row: the int32 op-grid cell
#: (3 x int32 index fields + 4 x int32 value fields = 28 B,
#: obs.compile_journal.frame_combo_detail) at the committed MULTICHIP_r06
#: depth t=16. Service boot overrides this with the engine's real
#: dtype x max_t figure.
DEFAULT_ROW_BYTES = 28 * 16


class SpaceSaving:
    """Deterministic Space-Saving heavy-hitter sketch over string keys.

    At most ``k`` counters are tracked. ``note(key, n)`` charges an
    existing counter, claims a free slot, or evicts the deterministic
    minimum (smallest ``(count, key)`` — ties break on the key, so the
    same stream always produces the same state). The evicted counter's
    count seeds the newcomer's count *and* its error bound, giving the
    classic invariants for every tracked key::

        count >= true_count >= count - err        (per-key bound)
        err <= min_tracked_count <= total / k     (global bound)

    and every key whose true count exceeds ``total / k`` is tracked.
    The whole state is the integer counter map — which makes
    :meth:`merge` a *lossless sparse add* (sum count and err per key,
    sum totals): exactly associative and commutative, with identical
    :meth:`to_bytes` output whichever order a fleet folds its members.
    Eviction bounds only the stream-side writer; a rollup over M member
    sketches holds at most ``M * k`` counters — bounded by the fleet
    size, never by the stream. Sum of counts always equals ``total``
    (all stream mass is charged somewhere), which the wire decoder
    checks.

    Same lock discipline as ``obs.capacity.LogHistogram``: one internal
    lock, every public method safe to call from any thread.
    """

    __slots__ = ("k", "_lock", "_counts", "_total")

    def __init__(self, k: int = 64):
        if k <= 0:
            raise ValueError(f"sketch capacity must be positive: {k}")
        self.k = int(k)
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}  # key -> [count, err]; guarded by self._lock
        self._total = 0  # guarded by self._lock

    def note(self, key: str, count: int = 1) -> None:
        """Charge ``count`` arrivals to ``key`` (Space-Saving update).

        The eviction scan is O(k) — k is small (64 by default) and the
        scan runs only on a full sketch meeting a *new* key; a heap
        would trade that for allocation on every update, which the
        armed admit path cares about more.
        """
        if count <= 0:
            return
        with self._lock:
            self._total += count
            c = self._counts.get(key)
            if c is not None:
                c[0] += count
                return
            if len(self._counts) < self.k:
                self._counts[key] = [count, 0]
                return
            counts = self._counts
            victim = min(counts, key=lambda s: (counts[s][0], s))
            floor = counts.pop(victim)[0]
            counts[key] = [floor + count, floor]

    @property
    def total(self) -> int:
        """Total stream mass noted (survives eviction and merge)."""
        with self._lock:
            return self._total

    @property
    def tracked(self) -> int:
        """Counters currently held (<= k per writer; <= M*k merged)."""
        with self._lock:
            return len(self._counts)

    def estimate(self, key: str) -> tuple[int, int] | None:
        """(count, err) for a tracked key, None if untracked."""
        with self._lock:
            c = self._counts.get(key)
            return (c[0], c[1]) if c is not None else None

    def top(self, n: int = 16) -> list[dict]:
        """The heavy-hitter table: up to ``n`` rows sorted by
        (count desc, key) — deterministic, like everything here."""
        with self._lock:
            items = sorted(
                self._counts.items(), key=lambda kv: (-kv[1][0], kv[0])
            )[:n]
            total = self._total
        return [
            {
                "symbol": key,
                "count": c,
                "err": e,
                "share": round(c / total, 6) if total else 0.0,
            }
            for key, (c, e) in items
        ]

    def merge(self, other: "SpaceSaving") -> None:
        """Fold ``other`` in: per-key (count, err) sums + total sum.

        Lossless by design (no truncation back to k), so the operation
        is exactly associative and commutative — the property the fleet
        rollup's byte-stability test pins. Capacity geometry must match,
        like LogHistogram's merge."""
        if self.k != other.k:
            raise ValueError(
                f"merge across sketch capacities: {self.k} vs {other.k}"
            )
        with other._lock:
            items = [(key, c[0], c[1]) for key, c in other._counts.items()]
            n = other._total
        with self._lock:
            for key, c, e in items:
                mine = self._counts.get(key)
                if mine is None:
                    self._counts[key] = [c, e]
                else:
                    mine[0] += c
                    mine[1] += e
            self._total += n

    def to_bytes(self) -> bytes:
        """Byte-stable wire form: same state -> same bytes (keys sorted
        by their utf-8 encoding)."""
        with self._lock:
            items = [
                (key.encode("utf-8"), c[0], c[1])
                for key, c in self._counts.items()
            ]
            total = self._total
        items.sort(key=lambda kv: kv[0])
        head = _HEADER.pack(_MAGIC, self.k, total, len(items))
        parts = [head]
        for kb, c, e in items:
            parts.append(_KEYLEN.pack(len(kb)))
            parts.append(kb)
            parts.append(_PAIR.pack(c, e))
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "SpaceSaving":
        if len(data) < _HEADER.size:
            raise ValueError(f"short sketch blob: {len(data)} bytes")
        magic, k, total, npairs = _HEADER.unpack_from(data, 0)
        if magic != _MAGIC:
            raise ValueError(f"bad sketch magic: {magic!r}")
        sk = cls(k=k)
        off = _HEADER.size
        counts: dict[str, list[int]] = {}
        for _ in range(npairs):
            if off + _KEYLEN.size > len(data):
                raise ValueError("truncated sketch blob (key length)")
            (klen,) = _KEYLEN.unpack_from(data, off)
            off += _KEYLEN.size
            if off + klen + _PAIR.size > len(data):
                raise ValueError("truncated sketch blob (pair)")
            key = data[off:off + klen].decode("utf-8")
            off += klen
            c, e = _PAIR.unpack_from(data, off)
            off += _PAIR.size
            counts[key] = [c, e]
        if off != len(data):
            raise ValueError(
                f"sketch blob length {len(data)} != expected {off}"
            )
        if sum(c[0] for c in counts.values()) != total:
            raise ValueError("sketch blob total != sum of counter counts")
        # single-writer: sk is private to this frame until returned
        sk._counts = counts
        sk._total = total
        return sk


class OccupancyLedger:
    """Running account of what dense dispatch geometry costs.

    Cumulative dispatched/live/padding rows across every dense frame,
    plus the latest dispatch's full geometry (per-shard row blocks when
    the engine runs a mesh). Plain integers, single-writer under the
    owning observatory's lock — no lock of its own on purpose (the
    observatory's ``note_dispatch`` already holds one)."""

    __slots__ = ("frames", "dispatched_rows", "live_rows",
                 "padding_rows", "last")

    def __init__(self) -> None:
        self.frames = 0  # single-writer: PlacementObservatory.note_dispatch under PLACEMENT._lock
        self.dispatched_rows = 0  # single-writer: PlacementObservatory.note_dispatch under PLACEMENT._lock
        self.live_rows = 0  # single-writer: PlacementObservatory.note_dispatch under PLACEMENT._lock
        self.padding_rows = 0  # single-writer: PlacementObservatory.note_dispatch under PLACEMENT._lock
        self.last: dict | None = None  # single-writer: PlacementObservatory.note_dispatch under PLACEMENT._lock

    def note(self, n_rows: int, n_live: int,
             shard_counts=None, r_s: int | None = None) -> None:
        """One dense dispatch: ``n_rows`` rows carrying ``n_live`` live
        lanes; under a mesh, ``shard_counts`` are the per-shard live
        counts and ``r_s`` the uniform per-shard row block."""
        self.frames += 1
        self.dispatched_rows += n_rows
        self.live_rows += n_live
        self.padding_rows += n_rows - n_live
        last: dict = {
            "n_rows": n_rows,
            "live": n_live,
            "rows_per_live_lane": round(n_rows / n_live, 4),
        }
        if shard_counts is not None:
            counts = [int(c) for c in shard_counts]
            d = len(counts)
            mx = max(counts)
            last["devices"] = d
            last["r_s"] = int(r_s) if r_s is not None else None
            last["shard_skew"] = round(mx * d / n_live, 4)
            # Per-shard row blocks: under the uniform-R_s layout every
            # shard dispatches r_s rows; its padding is r_s - live.
            last["row_blocks"] = [
                {"shard": i, "rows": int(r_s or 0), "live": c,
                 "padding": int(r_s or 0) - c}
                for i, c in enumerate(counts)
            ]
        self.last = last

    def as_dict(self, row_bytes: int) -> dict:
        """The payload block; ``row_bytes`` converts padding rows to
        waste bytes at the configured grid depth."""
        disp, live = self.dispatched_rows, self.live_rows
        return {
            "frames": self.frames,
            "dispatched_rows": disp,
            "live_rows": live,
            "padding_rows": self.padding_rows,
            "padding_bytes": self.padding_rows * row_bytes,
            "row_bytes": row_bytes,
            "rows_per_live_lane": round(disp / live, 4) if live else 0.0,
            "last": self.last,
        }


def load_verdict(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r} != {SCHEMA!r}"
        )
    return doc


def default_verdict() -> dict | None:
    """The committed repo-root ``PLACEMENT_r01.json``, or None when the
    artifact is absent or malformed (a service boot must not fail on a
    missing what-if verdict — live telemetry still arms)."""
    try:
        return load_verdict(str(_REPO_ROOT / "PLACEMENT_r01.json"))
    except (OSError, ValueError):
        return None


# -- committed-baseline lookups ------------------------------------------

_REPO_ROOT = Path(__file__).resolve().parents[2]
_baseline_cache: dict[str, dict | None] = {}  # guarded by _baseline_lock
_baseline_lock = threading.Lock()


def _artifact(name: str) -> dict | None:
    """Best-effort read of a committed repo-root artifact (memoized).
    The attribution table cites the committed before-numbers from the
    artifacts themselves — never from prose — so a regenerated artifact
    updates the baseline rows automatically."""
    with _baseline_lock:
        if name in _baseline_cache:
            return _baseline_cache[name]
    doc: dict | None
    try:
        with open(_REPO_ROOT / name, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        doc = None
    with _baseline_lock:
        _baseline_cache[name] = doc
    return doc


def shard_skew_baseline() -> dict | None:
    """MULTICHIP_r06's widest-mesh point (the 3.64-skew disease row)."""
    doc = _artifact("MULTICHIP_r06.json")
    if not doc:
        return None
    points = doc.get("curve") or []
    if not points:
        return None
    p = points[-1]
    return {
        "artifact": "MULTICHIP_r06",
        "devices": p.get("devices"),
        "shard_skew": p.get("shard_skew"),
        "rows_per_live_lane": p.get("rows_per_live_lane"),
    }


def partition_imbalance_baseline() -> dict | None:
    """FLEET_r01's measured partition order imbalance."""
    doc = _artifact("FLEET_r01.json")
    if not doc:
        return None
    imb = (doc.get("table") or {}).get("imbalance") or {}
    return {
        "artifact": "FLEET_r01",
        "max_over_min_orders": imb.get("max_over_min_orders"),
        "orders_per_partition": imb.get("orders_per_partition"),
    }


# -- process-global singleton --------------------------------------------


class PlacementObservatory:
    """Heavy-hitter flow + occupancy accounting behind ``/placement``.

    House disabled-singleton contract (TIMELINE/FLEET/CAPACITY):
    module import costs nothing, every hot-path hook unarmed is one
    attribute check and zero allocations, ``payload()`` unarmed is
    ``{"enabled": False}``. ``install()`` arms the sketch + ledger and
    exports the ``gome_placement_*`` gauges; an optional committed
    what-if verdict (scripts/placement_eval.py) rides the payload."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sketch: SpaceSaving | None = None  # guarded by self._lock (armed ⇔ sketch)
        self._ledger = OccupancyLedger()  # guarded by self._lock
        self._lane_ewma = None  # guarded by self._lock (np.ndarray | None)
        self._alpha = 0.2  # guarded by self._lock
        self._row_bytes = DEFAULT_ROW_BYTES  # guarded by self._lock
        self._partitions = 8  # guarded by self._lock
        self._verdict: dict | None = None  # guarded by self._lock

    @property
    def enabled(self) -> bool:
        return self._sketch is not None  # gomelint: disable=GL402 — off-lock fast check; worst case one stale payload

    # -- lifecycle -------------------------------------------------------

    def install(self, topk: int = 64, ewma_alpha: float = 0.2,
                row_bytes: int = DEFAULT_ROW_BYTES, partitions: int = 8,
                verdict: dict | None = None, registry=None) -> None:
        """Arm the observatory: a fresh ``topk``-deep sketch + ledger,
        per-lane EWMA at ``ewma_alpha``, padding costed at ``row_bytes``
        per row, hash-attribution computed over ``partitions`` what-if
        partitions. ``verdict`` (optional) is a committed
        ``gome-placement-verdict-v1`` document to serve alongside the
        live telemetry."""
        if topk <= 0:
            raise ValueError(f"placement topk must be positive: {topk}")
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError(
                f"placement ewma_alpha must be in (0, 1]: {ewma_alpha}"
            )
        if row_bytes <= 0:
            raise ValueError(
                f"placement row_bytes must be positive: {row_bytes}"
            )
        if partitions <= 0:
            raise ValueError(
                f"placement partitions must be positive: {partitions}"
            )
        if verdict is not None and verdict.get("schema") != SCHEMA:
            raise ValueError(
                f"placement verdict schema {verdict.get('schema')!r} "
                f"!= {SCHEMA!r}"
            )
        with self._lock:
            self._ledger = OccupancyLedger()
            self._lane_ewma = None
            self._alpha = float(ewma_alpha)
            self._row_bytes = int(row_bytes)
            self._partitions = int(partitions)
            self._verdict = verdict
            # Arm LAST: a hook racing install() sees either disabled or
            # a fully-configured observatory, never a half-built one.
            self._sketch = SpaceSaving(topk)
        self._export(registry)

    def disable(self) -> None:
        with self._lock:
            self._sketch = None
            self._verdict = None
            self._ledger = OccupancyLedger()
            self._lane_ewma = None

    # -- hot-path hooks --------------------------------------------------

    def note_admit(self, symbol: str, count: int = 1) -> None:  # gomelint: hotpath
        """Gateway admit hook (scalar paths): one accepted order (or
        cancel) for ``symbol``. Disabled = one attribute check, zero
        allocations."""
        sk = self._sketch  # gomelint: disable=GL402 — lock-free fast check; the sketch's own lock re-validates nothing is torn
        if sk is None:
            return
        sk.note(symbol, count)

    def note_admit_frame(self, symbols, symbol_idx) -> None:  # gomelint: hotpath
        """Columnar admit hook: ``symbols`` is the batch's unique-symbol
        list and ``symbol_idx`` the per-row index column (gateway
        _intern output) — the per-symbol bincount happens HERE, armed
        only, so the disabled gateway pays one attribute check."""
        sk = self._sketch  # gomelint: disable=GL402 — lock-free fast check, same as note_admit
        if sk is None:
            return
        import numpy as np

        counts = np.bincount(
            np.asarray(symbol_idx, dtype=np.int64), minlength=len(symbols)
        )
        for sym, c in zip(symbols, counts.tolist()):
            if c:
                sk.note(sym, c)

    def note_dispatch(self, n_rows: int, live,  # gomelint: hotpath
                      shard_counts=None, r_s: int | None = None) -> None:
        """Dense-dispatch geometry hook (engine.batch._grid_geometry):
        ``live`` is the frame's live-lane id array, ``shard_counts`` /
        ``r_s`` the mesh layout when sharded. Disabled = one attribute
        check, zero allocations; armed it is one ledger update plus one
        vectorized EWMA decay over the lane axis."""
        if self._sketch is None:  # gomelint: disable=GL402 — fast check; the locked re-check below is authoritative
            return
        import numpy as np

        lanes = np.asarray(live)
        n_live = int(lanes.shape[0])
        if n_live == 0:
            return
        hi = int(lanes.max()) + 1
        with self._lock:
            if self._sketch is None:
                return
            self._ledger.note(int(n_rows), n_live,
                              shard_counts=shard_counts, r_s=r_s)
            ew = self._lane_ewma
            if ew is None or ew.shape[0] < hi:
                grown = np.zeros(max(hi, 64), np.float64)
                if ew is not None:
                    grown[: ew.shape[0]] = ew
                self._lane_ewma = ew = grown
            a = self._alpha
            ew *= 1.0 - a
            ew[lanes] += a

    # -- read side -------------------------------------------------------

    def occupancy_probe(self) -> dict:
        """Tiny cumulative-ledger snapshot for the timeline sampler —
        occupancy history rides ``/timeline`` next to RSS and queue
        depth. ``{}`` while disabled (probes must stay cheap)."""
        if self._sketch is None:  # gomelint: disable=GL402 — probe fast check
            return {}
        with self._lock:
            led = self._ledger
            return {
                "frames": led.frames,
                "dispatched_rows": led.dispatched_rows,
                "live_rows": led.live_rows,
                "padding_rows": led.padding_rows,
            }

    def _hash_partition_flows(self, sk: SpaceSaving,
                              partitions: int) -> list[int]:
        """Tracked flow per what-if ``fnv1a % P`` partition — the ONE
        placement policy tree-wide (gome_tpu.fleet.router.partition_of);
        untracked tail mass is excluded (heavy hitters dominate the
        imbalance by construction)."""
        from ..fleet.router import partition_of

        flows = [0] * partitions
        for row in sk.top(sk.k):
            flows[partition_of(row["symbol"], partitions)] += row["count"]
        return flows

    def attribution(self) -> dict:
        """Decompose the latest observed dispatch skew.

        The dense packer's cost is multiplicative:
        ``rows/live = shard_skew * cap_class_padding`` exactly, where
        ``shard_skew = max_shard_live * D / live`` (lane placement — the
        ROADMAP item 2 disease) and ``cap_class_padding = r_s / max``
        (pow2 bucketing + the grow-only floor; on an unsharded engine the
        skew term is 1 and padding carries everything). The components
        are computed *independently* from the recorded geometry and
        reconciled against the observed total within tolerance — a
        failing reconciliation means the ledger and the packer disagree
        about geometry, which is a bug, not a workload. The fleet-level
        ``hash_partition`` row is additive context (a different axis,
        not a factor of the dispatch product). Baselines cite the
        committed artifacts (MULTICHIP_r06, FLEET_r01) read from disk,
        not prose."""
        tol = 0.05
        with self._lock:
            sk = self._sketch
            last = self._ledger.last
            partitions = self._partitions
        if sk is None:
            return {"enabled": False}
        out: dict = {"enabled": True, "tol": tol}
        if last is None:
            out["components"] = []
            out["reconciliation"] = None
        else:
            observed = last["rows_per_live_lane"]
            if "shard_skew" in last:
                counts = [b["live"] for b in last["row_blocks"]]
                mx = max(counts)
                skew = mx * last["devices"] / last["live"]
                padding = (last["r_s"] or mx) / mx
            else:
                skew = 1.0
                padding = last["n_rows"] / last["live"]
            product = skew * padding
            frac = abs(product - observed) / observed if observed else 1.0
            out["observed_rows_per_live_lane"] = observed
            out["components"] = [
                {
                    "component": "lane_placement_skew",
                    "value": round(skew, 4),
                    "baseline": shard_skew_baseline(),
                },
                {
                    "component": "cap_class_padding",
                    "value": round(padding, 4),
                    "baseline": None,
                },
            ]
            out["reconciliation"] = {
                "product": round(product, 4),
                "frac_err": round(frac, 6),
                "within_tol": frac <= tol,
            }
        flows = self._hash_partition_flows(sk, partitions)
        total = sum(flows)
        mean = total / partitions if partitions else 0.0
        out["hash_partition"] = {
            "partitions": partitions,
            "tracked_flow_per_partition": flows,
            "imbalance_max_over_mean": (
                round(max(flows) / mean, 4) if mean else 0.0
            ),
            "baseline": partition_imbalance_baseline(),
        }
        return out

    def payload(self) -> dict:
        """The ``/placement`` wire form: heavy-hitter table + sketch
        wire bytes (the fleet aggregator merges members from these),
        occupancy ledger, hot-lane EWMA table, attribution rows, and
        the installed verdict (if any)."""
        with self._lock:
            sk = self._sketch
            if sk is None:
                return {"enabled": False}
            row_bytes = self._row_bytes
            alpha = self._alpha
            occupancy = self._ledger.as_dict(row_bytes)
            verdict = self._verdict
            ew = self._lane_ewma
            hot_lanes = []
            if ew is not None:
                import numpy as np

                n = min(16, int((ew > 0).sum()))
                if n:
                    order = np.argsort(-ew, kind="stable")[:n]
                    hot_lanes = [
                        {"lane": int(i), "ewma_rate": round(float(ew[i]), 6)}
                        for i in order
                        if ew[i] > 0
                    ]
        top = sk.top(16)
        total = sk.total
        return {
            "enabled": True,
            "admits": total,
            "top": top,
            "topk_share": (
                round(sum(r["count"] for r in top) / total, 6)
                if total else 0.0
            ),
            "sketch": {
                "k": sk.k,
                "tracked": sk.tracked,
                "total": total,
                "bytes_hex": sk.to_bytes().hex(),
            },
            "occupancy": occupancy,
            "lanes": {"ewma_alpha": alpha, "hot": hot_lanes},
            "attribution": self.attribution(),
            "verdict": verdict,
        }

    # -- metrics export --------------------------------------------------

    def _g_topk_share(self) -> float:
        sk = self._sketch  # gomelint: disable=GL402 — gauge read, snapshot semantics
        if sk is None:
            return 0.0
        total = sk.total
        if not total:
            return 0.0
        return sum(r["count"] for r in sk.top(16)) / total

    def _g_rows_per_live(self) -> float:
        with self._lock:
            last = self._ledger.last
        return float(last["rows_per_live_lane"]) if last else 0.0

    def _g_attr(self, component: str) -> float:
        a = self.attribution()
        for row in a.get("components", ()):
            if row["component"] == component:
                return float(row["value"])
        return 0.0

    def _export(self, registry=None) -> None:
        if registry is None:
            from ..utils.metrics import REGISTRY

            registry = REGISTRY
        registry.callback_gauge(
            "gome_placement_admits_total",
            "orders noted by the placement sketch since install",
            lambda: float(self._sketch.total if self._sketch else 0),  # gomelint: disable=GL402 — gauge read, snapshot semantics
        )
        registry.callback_gauge(
            "gome_placement_topk_share",
            "share of admitted flow carried by the top-16 symbols",
            self._g_topk_share,
        )
        registry.callback_gauge(
            "gome_placement_sketch_tracked",
            "symbol counters currently tracked by the placement sketch",
            lambda: float(self._sketch.tracked if self._sketch else 0),  # gomelint: disable=GL402 — gauge read, snapshot semantics
        )
        registry.callback_gauge(
            "gome_placement_rows_per_live_lane",
            "latest dense dispatch's rows per live lane (padding factor)",
            self._g_rows_per_live,
        )
        registry.callback_gauge(
            "gome_placement_attr_lane_skew",
            "attribution: lane-placement skew factor of the latest dispatch",
            lambda: self._g_attr("lane_placement_skew"),
        )
        registry.callback_gauge(
            "gome_placement_attr_padding",
            "attribution: cap-class padding factor of the latest dispatch",
            lambda: self._g_attr("cap_class_padding"),
        )


#: Process-global observatory (disabled until service boot or a test
#: arms it via install() — gated by the ops config's `placement` flag).
PLACEMENT = PlacementObservatory()

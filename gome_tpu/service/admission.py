"""Gateway admission control — end-to-end overload protection (round 12).

The batcher's Backpressure (spill full) only fires once the *bus* is
down; a healthy bus in front of a slow consumer accepts frames forever
while the committed-offset gap — `gome_bus_depth`, the real end-to-end
lag — grows without bound. This controller closes that loop: the
gateway asks `admit()` before marking/emitting, and when consumer lag
crosses the depth ceiling (or the caller's gRPC deadline is already too
tight to survive the queue), the order is shed with the established
RETRYABLE status (code 14) plus a machine-parseable retry-after hint
that scales with overload — clients with utils.resilience back off
instead of hammering a drowning fleet (CoinTossX's flow-control stance:
shed early at the edge, never collapse in the middle).

Depth is sampled through a cached `depth_fn` read: admission sits on the
per-RPC hot path, and the committed-offset gap moves at frame cadence,
not per order — a `cache_s` stale read is indistinguishable from racing
the consumer's next commit.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..utils.metrics import REGISTRY

#: retry-after hints are embedded in the reject message as
#: `retry-after=<seconds>s`; clients parse with RETRY_AFTER_RE (the
#: wire OrderResponse has no header field to carry it — reference shape).
RETRY_AFTER_FMT = "retry-after={:.3f}s"


@dataclass(frozen=True)
class Decision:
    """One admission verdict. `ok` admits; otherwise `reason` is
    "depth" (queue over the ceiling) or "deadline" (caller's remaining
    gRPC deadline cannot survive current lag) and `retry_after_s` is the
    backoff hint for the reject message."""

    ok: bool
    reason: str = ""
    retry_after_s: float = 0.0
    depth: int = 0

    def message(self) -> str:
        hint = RETRY_AFTER_FMT.format(self.retry_after_s)
        if self.reason == "deadline":
            return f"overloaded, deadline too tight ({hint})"
        return f"overloaded, queue depth {self.depth} ({hint})"


class AdmissionController:
    """Depth- and deadline-based load shedding for the order gateway.

    depth_fn        () -> int: consumer lag for the order path — wire
                    `bus.order_queue.depth` (published minus committed,
                    the gap `gome_bus_depth` exports).
    max_depth       admit while depth < max_depth; at/above it new
                    orders are shed retryable. The ceiling bounds
                    worst-case queueing delay: max_depth / drain-rate.
    min_deadline_s  shed when the caller's remaining gRPC deadline is
                    below this — the reply would be DEADLINE_EXCEEDED
                    garbage anyway, so spend zero pipeline work on it.
    retry_after_s   base hint at the ceiling; the hint scales linearly
                    with overshoot (2x ceiling -> 2x hint) and clamps at
                    `retry_after_max_s`, so a deeply backed-up fleet
                    pushes retries further out instead of inviting a
                    synchronized stampede.
    cache_s         depth_fn sample cache window (see module docstring).
    """

    def __init__(
        self,
        depth_fn: Callable[[], int],
        max_depth: int = 16384,
        min_deadline_s: float = 0.0,
        retry_after_s: float = 0.05,
        retry_after_max_s: float = 2.0,
        cache_s: float = 0.005,
        registry=REGISTRY,
    ):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        if retry_after_s <= 0 or retry_after_max_s < retry_after_s:
            raise ValueError(
                "need 0 < retry_after_s <= retry_after_max_s"
            )
        self.depth_fn = depth_fn
        self.max_depth = max_depth
        self.min_deadline_s = min_deadline_s
        self.retry_after_s = retry_after_s
        self.retry_after_max_s = retry_after_max_s
        self.cache_s = cache_s
        self._lock = threading.Lock()
        self._cached_depth = 0  # guarded by self._lock
        self._cached_at = -1.0  # guarded by self._lock
        self._shed_depth = registry.counter(
            "gome_gateway_shed_total",
            "orders shed at admission (by reason)",
            labels={"reason": "depth"},
        )
        self._shed_deadline = registry.counter(
            "gome_gateway_shed_total",
            "orders shed at admission (by reason)",
            labels={"reason": "deadline"},
        )
        registry.callback_gauge(
            "gome_gateway_admission_depth",
            "last consumer-lag sample the admission controller acted on",
            lambda: self._cached_depth,  # gomelint: disable=GL402 — stale read is the design
        )

    def depth(self) -> int:
        """Cached consumer-lag sample (refreshes after cache_s)."""
        now = time.monotonic()
        with self._lock:
            if now - self._cached_at >= self.cache_s:
                self._cached_depth = int(self.depth_fn())
                self._cached_at = now
            return self._cached_depth

    def _hint(self, depth: int) -> float:
        over = depth / self.max_depth if self.max_depth else 1.0
        return min(
            max(self.retry_after_s * over, self.retry_after_s),
            self.retry_after_max_s,
        )

    def admit(
        self, n: int = 1, time_remaining_s: float | None = None
    ) -> Decision:  # gomelint: hotpath
        """Admission verdict for `n` incoming orders. `time_remaining_s`
        is the caller's remaining gRPC deadline (context.time_remaining();
        None = no deadline set)."""
        if (
            time_remaining_s is not None
            and time_remaining_s < self.min_deadline_s
        ):
            self._shed_deadline.inc(n)
            d = self.depth()
            return Decision(
                ok=False, reason="deadline",
                retry_after_s=self._hint(d), depth=d,
            )
        d = self.depth()
        if d + n > self.max_depth:
            self._shed_depth.inc(n)
            return Decision(
                ok=False, reason="depth",
                retry_after_s=self._hint(d + n), depth=d,
            )
        return Decision(ok=True, depth=d)

"""gRPC gateway — the reference's server process (gomengine/main.go:22-64).

Handler behavior parity (main.go:39-64): handlers do NO matching — they
build the internal order, mark the pre-pool (ADD only; main.go:44-45 — DEL
never marks), publish to the "doOrder" queue, and return success
immediately. The response never reflects matching outcome; the pipeline is
fully asynchronous (SURVEY §1 L4).

Differences, deliberate:
  * float→tick scaling is validated here at the edge (the reference scales
    inside the consumer, ordernode.go:76-87, and cannot reject bad input —
    its gateway already returned success);
  * SubscribeMatches streams the matchOrder feed over gRPC (extension; the
    reference's downstream is an AMQP stub, rabbitmq.go:169).
"""

from __future__ import annotations

import dataclasses
from concurrent import futures

import grpc

from ..api import order_pb2 as pb
from ..api.service import add_order_servicer
from ..bus import QueueBus, encode_order
from ..config import Config
from ..fixed import scale
from ..obs.hostprof import HOSTPROF
from ..types import Action, Order, OrderType, Side
from ..utils.logging import get_logger
from ..utils.trace import TRACER

log = get_logger("gateway")

#: Edge-reject status codes on the reference-shaped OrderResponse.code
#: field: 3 = permanent reject (invalid order, gateway shut down — do not
#: retry), RETRYABLE = the pipeline is degraded (bus down + spill full /
#: circuit open); the order was NOT accepted and a retry later should
#: succeed. 14 matches gRPC UNAVAILABLE by convention.
CODE_REJECT = 3
CODE_RETRYABLE = 14


def order_from_request(
    request: pb.OrderRequest, action: Action, accuracy: int
) -> Order:
    """OrderRequest → internal Order (NewOrderNode's role,
    ordernode.go:38-54: stamp action, scale price/volume by 10^accuracy)."""
    return Order(
        uuid=request.uuid,
        oid=request.oid,
        symbol=request.symbol,
        side=Side(request.transaction),
        price=scale(request.price, accuracy),
        volume=scale(request.volume, accuracy),
        action=action,
        order_type=OrderType(request.kind),
    )


class OrderGateway:
    """The Order servicer (main.go:20,39-64)."""

    def __init__(
        self,
        bus: QueueBus,
        accuracy: int,
        mark=None,
        match_feed=None,
        max_volume: int | None = None,
        batcher=None,
        unmark=None,
    ):
        """mark: callable(Order) recording the pre-pool entry — the
        MatchEngine.mark bound method in single-binary mode. match_feed:
        MatchFeed for SubscribeMatches (optional). max_volume: per-order lot
        ceiling enforced at the edge (int32 engines pass LOT_MAX32 so an
        oversized order is rejected with code 3 here, like volume<=0,
        instead of raising inside the consumer batch). batcher: a
        service.batcher.FrameBatcher — accepted orders then leave as
        columnar ORDER frames (size/deadline bounded) instead of one JSON
        document per request; admission/marking semantics are unchanged.
        unmark: callable(Order) undoing a pre-pool mark — used only on the
        shutdown race where the batcher closed between mark and emit, so a
        rejected order never leaves a dangling marker."""
        self._bus = bus
        self._accuracy = accuracy
        self._mark = mark or (lambda order: None)
        self._unmark = unmark or (lambda order: None)
        self._match_feed = match_feed
        self._max_volume = max_volume
        self._batcher = batcher

    def _emit(self, order: Order) -> None:
        if self._batcher is not None:
            self._batcher.submit(order)
        elif order.trace is not None and self._bus.order_queue.supports_headers:
            # Per-order publish: the trace context also rides the AMQP
            # basic-properties headers (survives the broker hop even for
            # opaque bodies; the consumer adopts it when the body carries
            # none).
            self._bus.order_queue.publish(
                encode_order(order), headers={"x-trace": order.trace}
            )
        else:
            self._bus.order_queue.publish(encode_order(order))

    def _begin_trace(self):
        """(trace_id, t_ingress) for a new order journey, or (None, 0.0)
        while tracing is disabled (the zero-overhead path)."""
        tid = TRACER.new_trace()
        return tid, (TRACER.clock() if tid is not None else 0.0)

    def _traced_emit(self, order: Order, tid: str | None, t0: float) -> Order:
        """Close the ingress span, stamp the wire context, and emit under
        an enqueue span. Returns the (possibly re-stamped) order."""
        if tid is None:
            self._emit(order)
            return order
        TRACER.add_span(tid, "ingress", t0, TRACER.clock())
        with TRACER.bind(tid), TRACER.span("enqueue", tid):
            # The hop timestamp is stamped INSIDE the enqueue span: the
            # receiver-side span it seeds (batch_wait / bus_transit)
            # then starts after enqueue began — journeys stay monotone.
            order = dataclasses.replace(
                order, trace=TRACER.context(tid)
            )
            self._emit(order)
        return order

    def _validate_add(self, request: pb.OrderRequest) -> Order:
        """OrderRequest -> admitted ADD Order; raises ValueError with the
        edge-rejection reason (code 3) otherwise."""
        order = order_from_request(request, Action.ADD, self._accuracy)
        if order.volume <= 0:
            raise ValueError("volume must be positive")
        if self._max_volume is not None and order.volume > self._max_volume:
            raise ValueError(
                f"volume {order.volume} exceeds the engine's per-order "
                f"lot ceiling {self._max_volume}"
            )
        if order.order_type is OrderType.LIMIT and order.price <= 0:
            raise ValueError("limit price must be positive")
        return order

    def DoOrder(self, request: pb.OrderRequest, context) -> pb.OrderResponse:
        tid, t0 = self._begin_trace()
        try:
            order = self._validate_add(request)
        except ValueError as e:
            return pb.OrderResponse(code=3, message=f"rejected: {e}")
        self._mark(order)  # pre-pool before queueing (main.go:44-45)
        try:
            self._traced_emit(order, tid, t0)
        except (ConnectionError, OSError) as e:
            # Bus degraded (spill full / circuit open / reconnect budget
            # exhausted): the order was NOT accepted into the pipeline, so
            # the mark must not dangle — and the client hears an explicit
            # RETRYABLE status instead of a gRPC UNKNOWN or a silent drop.
            self._unmark(order)
            return pb.OrderResponse(
                code=CODE_RETRYABLE, message=f"degraded, retry: {e}"
            )
        except RuntimeError as e:
            # Batcher closed mid-shutdown: permanent for this process.
            self._unmark(order)
            return pb.OrderResponse(
                code=CODE_REJECT, message=f"rejected: {e}"
            )
        # main.go:49: unconditional success; matching outcome arrives async.
        HOSTPROF.note_admit()  # disabled: one attribute check, no allocs
        return pb.OrderResponse(code=0, message="order accepted")

    def DeleteOrder(self, request: pb.OrderRequest, context) -> pb.OrderResponse:
        tid, t0 = self._begin_trace()
        try:
            order = order_from_request(request, Action.DEL, self._accuracy)
        except ValueError as e:
            return pb.OrderResponse(code=3, message=f"rejected: {e}")
        # No pre-pool mark (main.go:54-64); the consumer clears it so a
        # still-queued ADD dies (engine.go:88-90, SURVEY §2.3.3). Cancels
        # ride the same batcher so the DEL-after-ADD order is preserved.
        try:
            self._traced_emit(order, tid, t0)
        except (ConnectionError, OSError) as e:
            return pb.OrderResponse(
                code=CODE_RETRYABLE, message=f"degraded, retry: {e}"
            )
        except RuntimeError as e:
            # Batcher closed: reject, don't crash the handler.
            return pb.OrderResponse(
                code=CODE_REJECT, message=f"rejected: {e}"
            )
        HOSTPROF.note_admit()
        return pb.OrderResponse(code=0, message="cancel accepted")

    def _apply_entries(self, entries) -> pb.OrderBatchResponse:
        """Shared core of the amortized-ingest RPCs: apply (request,
        is_cancel) pairs in order — per-entry validation rejects are
        collected (parallel reject_index/rejects arrays), accepted
        entries mark + emit exactly like their unary counterparts. An
        emit failure stops the batch: the response carries CODE_RETRYABLE
        when the bus is degraded (retry the remainder later) or
        CODE_REJECT when the batcher is closed, and `accepted` says how
        many entries made it into the pipeline before the failure
        (at-most-once for the remainder — the client resubmits them)."""
        resp = pb.OrderBatchResponse()
        accepted = 0
        for i, (request, is_cancel) in enumerate(entries):
            tid, t0 = self._begin_trace()  # per-entry order journey
            if is_cancel:
                try:
                    order = order_from_request(
                        request, Action.DEL, self._accuracy
                    )
                except ValueError as e:
                    resp.reject_index.append(i)
                    resp.rejects.add(code=3, message=f"rejected: {e}")
                    continue
                unmark_on_fail = False
            else:
                try:
                    order = self._validate_add(request)
                except ValueError as e:
                    resp.reject_index.append(i)
                    resp.rejects.add(code=3, message=f"rejected: {e}")
                    continue
                self._mark(order)
                unmark_on_fail = True
            try:
                self._traced_emit(order, tid, t0)
            except (RuntimeError, ConnectionError, OSError) as e:
                if unmark_on_fail:
                    self._unmark(order)
                resp.code = (
                    CODE_RETRYABLE
                    if isinstance(e, (ConnectionError, OSError))
                    else CODE_REJECT
                )
                resp.message = f"batch aborted at entry {i}: {e}"
                break
            accepted += 1
        resp.accepted = accepted
        if accepted:
            HOSTPROF.note_admit(accepted)  # one locked add per batch
        return resp

    def DoOrderBatch(
        self, request: pb.OrderBatchRequest, context
    ) -> pb.OrderBatchResponse:
        """Amortized ingest: many reference-shaped OrderRequests in one
        RPC, applied in list order (same-batch ADD->DEL sequencing is
        preserved; `cancel[i]` selects DeleteOrder semantics)."""
        n = len(request.orders)
        if request.cancel and len(request.cancel) != n:
            return pb.OrderBatchResponse(
                code=3,
                message=(
                    f"cancel mask length {len(request.cancel)} != "
                    f"orders length {n}"
                ),
            )
        cancels = request.cancel or (False,) * n
        return self._apply_entries(zip(request.orders, cancels))

    def DoOrderStream(
        self, request_iterator, context
    ) -> pb.OrderBatchResponse:
        """Client-streaming ingest: ADD semantics per message (cancels go
        through DeleteOrder / DoOrderBatch); one summary response when
        the client half-closes."""
        return self._apply_entries(
            (request, False) for request in request_iterator
        )

    def SubscribeMatches(self, request: pb.SubscribeRequest, context):
        if self._match_feed is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED, "no match feed attached"
            )
        yield from self._match_feed.subscribe(context)


def serve_gateway(
    gateway: OrderGateway, config: Config, max_workers: int = 16
) -> grpc.Server:
    """Build + start the gRPC server (main.go:28-36 / grpc.go:24-39's
    listener-from-config). Returns the started server; caller owns
    shutdown."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    add_order_servicer(server, gateway)
    # Server reflection, like the reference (main.go:33) — grpcurl works.
    from ..api.reflection import add_reflection_servicer

    add_reflection_servicer(server)
    addr = f"{config.grpc.host}:{config.grpc.port}"
    bound = server.add_insecure_port(addr)
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC listener on {addr}")
    # Port-0 callers (tests, the fleet drill's subprocess workers) need
    # the OS-assigned port; grpc.Server has no accessor for it.
    server.bound_port = bound
    server.start()
    log.info("gateway serving on %s:%d", config.grpc.host, bound)
    return server

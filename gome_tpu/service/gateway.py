"""gRPC gateway — the reference's server process (gomengine/main.go:22-64).

Handler behavior parity (main.go:39-64): handlers do NO matching — they
build the internal order, mark the pre-pool (ADD only; main.go:44-45 — DEL
never marks), publish to the "doOrder" queue, and return success
immediately. The response never reflects matching outcome; the pipeline is
fully asynchronous (SURVEY §1 L4).

Differences, deliberate:
  * float→tick scaling is validated here at the edge (the reference scales
    inside the consumer, ordernode.go:76-87, and cannot reject bad input —
    its gateway already returned success);
  * SubscribeMatches streams the matchOrder feed over gRPC (extension; the
    reference's downstream is an AMQP stub, rabbitmq.go:169).
"""

from __future__ import annotations

import dataclasses
import operator
from concurrent import futures

import grpc
import numpy as np

from ..api import order_pb2 as pb
from ..api.service import add_order_servicer
from ..bus import QueueBus, encode_order
from ..bus.colwire import encode_order_block, encode_order_frame_blocks
from ..config import Config
from ..fixed import scale
from ..obs.hostprof import HOSTPROF
from ..obs.placement import PLACEMENT
from ..types import Action, Order, OrderType, Side
from ..utils.faults import FAULTS
from ..utils.logging import get_logger
from ..utils.trace import TRACER

log = get_logger("gateway")

#: Edge-reject status codes on the reference-shaped OrderResponse.code
#: field: 3 = permanent reject (invalid order, gateway shut down — do not
#: retry), RETRYABLE = the pipeline is degraded (bus down + spill full /
#: circuit open); the order was NOT accepted and a retry later should
#: succeed. 14 matches gRPC UNAVAILABLE by convention.
CODE_REJECT = 3
CODE_RETRYABLE = 14


def _time_remaining(context) -> float | None:
    """Caller's remaining gRPC deadline in seconds, or None when no
    deadline was set (or the test harness passed a bare context)."""
    if context is None:
        return None
    tr = getattr(context, "time_remaining", None)
    if not callable(tr):
        return None
    remaining = tr()
    # grpc returns a huge sentinel (~year-scale) when no deadline is set.
    if remaining is None or remaining > 1e8:
        return None
    return remaining


def order_from_request(
    request: pb.OrderRequest, action: Action, accuracy: int
) -> Order:
    """OrderRequest → internal Order (NewOrderNode's role,
    ordernode.go:38-54: stamp action, scale price/volume by 10^accuracy)."""
    return Order(
        uuid=request.uuid,
        oid=request.oid,
        symbol=request.symbol,
        side=Side(request.transaction),
        price=scale(request.price, accuracy),
        volume=scale(request.volume, accuracy),
        action=action,
        order_type=OrderType(request.kind),
    )


#: Above this magnitude a float64 has an ulp >= 0.5, so ``rint(x * 10^a)``
#: can land on the wrong integer and the vectorized scale result is no
#: longer provably equal to fixed.scale's Decimal result. Rows whose scaled
#: value reaches this bound are re-run through the scalar path.
_SAFE_SCALED = float(1 << 51)

#: DoOrderStream applies columnar admission in chunks of this many
#: messages, so reject indices/abort entry numbers stay absolute while the
#: working set (proto list + numpy columns) stays cache-sized.
STREAM_CHUNK = 4096

#: C-level field pulls for the columnar extraction passes: map(attrgetter)
#: keeps the per-row loop out of Python bytecode entirely (~25% cheaper
#: than a genexpr/listcomp at gateway batch sizes).
_GET_TRANSACTION = operator.attrgetter("transaction")
_GET_KIND = operator.attrgetter("kind")
_GET_PRICE = operator.attrgetter("price")
_GET_VOLUME = operator.attrgetter("volume")
_GET_SYMBOL = operator.attrgetter("symbol")
_GET_UUID = operator.attrgetter("uuid")
_GET_OID = operator.attrgetter("oid")


def _vector_scale(values: np.ndarray, accuracy: int):
    """Vectorized fixed.scale: float column -> (int64 ticks, exact mask,
    suspect mask).

    ``exact[i]`` guarantees the scalar path would admit the value and
    produce the same integer: within ``|x * 10^a| < 2**51`` the tick grid
    is coarser than the float64 ulp, so at most one integer ``j`` satisfies
    ``float(j / 10^a) == x`` — and then ``repr(x)`` has <= ``accuracy``
    fractional digits, which is exactly fixed.scale's acceptance test.
    ``suspect[i]`` marks rows outside that provable range (huge/non-finite
    scaled values); the caller re-runs those through fixed.scale itself.
    Rows that are neither exact nor suspect are definite scalar-path
    rejects ("more than {a} decimal places").
    """
    p = 10.0 ** accuracy
    with np.errstate(invalid="ignore", over="ignore"):
        scaled = values * p
        safe = np.isfinite(scaled) & (np.abs(scaled) < _SAFE_SCALED)
        ticks = np.rint(np.where(safe, scaled, 0.0))
        exact = safe & ((ticks / p) == values)
    return ticks.astype(np.int64), exact, ~safe


def _intern(strings: list):
    """Column of python strings -> (first-occurrence unique list, uint32
    index array) — the dictionary-encoding step of the GCO4 wire columns,
    done once per batch instead of once per order. A dict pass beats
    np.unique here (no U-dtype copy, no sort) at gateway batch sizes."""
    table: dict = {}
    setd = table.setdefault
    idx = [setd(s, len(table)) for s in strings]
    return list(table), np.asarray(idx, np.uint32)


def orders_from_columns(cols: dict):
    """Materialize internal Orders from a columnar admit block — the
    scalar-pool fallback when no bulk marker is wired, and the parity
    harness tests use it to compare paths row for row."""
    symbols = cols["symbols"]
    uuids = cols["uuids"]
    sym_idx = np.asarray(cols["symbol_idx"]).tolist()
    uuid_idx = np.asarray(cols["uuid_idx"]).tolist()
    oids = np.asarray(cols["oids"]).tolist()
    action = np.asarray(cols["action"]).tolist()
    side = np.asarray(cols["side"]).tolist()
    kind = np.asarray(cols["kind"]).tolist()
    price = np.asarray(cols["price"]).tolist()
    volume = np.asarray(cols["volume"]).tolist()
    return [
        Order(
            uuid=uuids[uuid_idx[i]],
            oid=oids[i].decode(),
            symbol=symbols[sym_idx[i]],
            side=Side(side[i]),
            price=price[i],
            volume=volume[i],
            action=Action(action[i]),
            order_type=OrderType(kind[i]),
        )
        for i in range(int(cols["n"]))
    ]


class OrderGateway:
    """The Order servicer (main.go:20,39-64)."""

    def __init__(
        self,
        bus: QueueBus,
        accuracy: int,
        mark=None,
        match_feed=None,
        max_volume: int | None = None,
        batcher=None,
        unmark=None,
        mark_frame=None,
        unmark_frame=None,
        columnar: bool = True,
        admission=None,
    ):
        """mark: callable(Order) recording the pre-pool entry — the
        MatchEngine.mark bound method in single-binary mode. match_feed:
        MatchFeed for SubscribeMatches (optional). max_volume: per-order lot
        ceiling enforced at the edge (int32 engines pass LOT_MAX32 so an
        oversized order is rejected with code 3 here, like volume<=0,
        instead of raising inside the consumer batch). batcher: a
        service.batcher.FrameBatcher — accepted orders then leave as
        columnar ORDER frames (size/deadline bounded) instead of one JSON
        document per request; admission/marking semantics are unchanged.
        unmark: callable(Order) undoing a pre-pool mark — used only on the
        shutdown race where the batcher closed between mark and emit, so a
        rejected order never leaves a dangling marker. mark_frame /
        unmark_frame: callables taking a decoded-ORDER-frame cols dict and
        bulk-(un)marking its ADD rows (MatchEngine.mark_frame /
        unmark_frame in single-binary mode) — the columnar admit path's
        batched pre-pool marker; when absent the columnar path falls back
        to per-order mark/unmark over materialized Orders. columnar: admit
        DoOrderBatch/DoOrderStream traffic through the array-native core
        (False pins the per-entry scalar loop, e.g. for parity tests).
        admission: a service.admission.AdmissionController — handlers
        consult it BEFORE marking/emitting; a shed returns the retryable
        status (code 14) with a retry-after hint, so backed-up consumers
        push backpressure all the way to the client."""
        self._bus = bus
        self._accuracy = accuracy
        self._mark = mark or (lambda order: None)
        self._unmark = unmark or (lambda order: None)
        self._mark_frame = mark_frame
        self._unmark_frame = unmark_frame
        self._columnar = columnar
        self._match_feed = match_feed
        self._max_volume = max_volume
        self._batcher = batcher
        self._admission = admission

    def _emit(self, order: Order) -> None:
        # Fault point "gateway.emit": exit = gateway-kill, call-handler
        # raising ConnectionError = bus-disconnect — both exercised by
        # scripts/fleet_chaos.py against the real degraded paths below.
        FAULTS.fire("gateway.emit")
        if self._batcher is not None:
            self._batcher.submit(order)
        elif order.trace is not None and self._bus.order_queue.supports_headers:
            # Per-order publish: the trace context also rides the AMQP
            # basic-properties headers (survives the broker hop even for
            # opaque bodies; the consumer adopts it when the body carries
            # none).
            self._bus.order_queue.publish(
                encode_order(order), headers={"x-trace": order.trace}
            )
        else:
            self._bus.order_queue.publish(encode_order(order))

    def _begin_trace(self):
        """(trace_id, t_ingress) for a new order journey, or (None, 0.0)
        while tracing is disabled (the zero-overhead path)."""
        tid = TRACER.new_trace()
        return tid, (TRACER.clock() if tid is not None else 0.0)

    def _traced_emit(self, order: Order, tid: str | None, t0: float) -> Order:
        """Close the ingress span, stamp the wire context, and emit under
        an enqueue span. Returns the (possibly re-stamped) order."""
        if tid is None:
            self._emit(order)
            return order
        TRACER.add_span(tid, "ingress", t0, TRACER.clock())
        with TRACER.bind(tid), TRACER.span("enqueue", tid):
            # The hop timestamp is stamped INSIDE the enqueue span: the
            # receiver-side span it seeds (batch_wait / bus_transit)
            # then starts after enqueue began — journeys stay monotone.
            order = dataclasses.replace(
                order, trace=TRACER.context(tid)
            )
            self._emit(order)
        return order

    def _validate_add(self, request: pb.OrderRequest) -> Order:
        """OrderRequest -> admitted ADD Order; raises ValueError with the
        edge-rejection reason (code 3) otherwise."""
        order = order_from_request(request, Action.ADD, self._accuracy)
        if order.volume <= 0:
            raise ValueError("volume must be positive")
        if self._max_volume is not None and order.volume > self._max_volume:
            raise ValueError(
                f"volume {order.volume} exceeds the engine's per-order "
                f"lot ceiling {self._max_volume}"
            )
        if order.order_type is OrderType.LIMIT and order.price <= 0:
            raise ValueError("limit price must be positive")
        return order

    def DoOrder(self, request: pb.OrderRequest, context) -> pb.OrderResponse:
        if self._admission is not None:
            d = self._admission.admit(1, _time_remaining(context))
            if not d.ok:
                return pb.OrderResponse(
                    code=CODE_RETRYABLE, message=d.message()
                )
        tid, t0 = self._begin_trace()
        try:
            order = self._validate_add(request)
        except ValueError as e:
            return pb.OrderResponse(code=3, message=f"rejected: {e}")
        self._mark(order)  # pre-pool before queueing (main.go:44-45)
        try:
            self._traced_emit(order, tid, t0)
        except (ConnectionError, OSError) as e:
            # Bus degraded (spill full / circuit open / reconnect budget
            # exhausted): the order was NOT accepted into the pipeline, so
            # the mark must not dangle — and the client hears an explicit
            # RETRYABLE status instead of a gRPC UNKNOWN or a silent drop.
            self._unmark(order)
            return pb.OrderResponse(
                code=CODE_RETRYABLE, message=f"degraded, retry: {e}"
            )
        except RuntimeError as e:
            # Batcher closed mid-shutdown: permanent for this process.
            self._unmark(order)
            return pb.OrderResponse(
                code=CODE_REJECT, message=f"rejected: {e}"
            )
        # main.go:49: unconditional success; matching outcome arrives async.
        HOSTPROF.note_admit()  # disabled: one attribute check, no allocs
        PLACEMENT.note_admit(order.symbol)  # same disabled contract
        return pb.OrderResponse(code=0, message="order accepted")

    def DeleteOrder(self, request: pb.OrderRequest, context) -> pb.OrderResponse:
        if self._admission is not None:
            d = self._admission.admit(1, _time_remaining(context))
            if not d.ok:
                return pb.OrderResponse(
                    code=CODE_RETRYABLE, message=d.message()
                )
        tid, t0 = self._begin_trace()
        try:
            order = order_from_request(request, Action.DEL, self._accuracy)
        except ValueError as e:
            return pb.OrderResponse(code=3, message=f"rejected: {e}")
        # No pre-pool mark (main.go:54-64); the consumer clears it so a
        # still-queued ADD dies (engine.go:88-90, SURVEY §2.3.3). Cancels
        # ride the same batcher so the DEL-after-ADD order is preserved.
        try:
            self._traced_emit(order, tid, t0)
        except (ConnectionError, OSError) as e:
            return pb.OrderResponse(
                code=CODE_RETRYABLE, message=f"degraded, retry: {e}"
            )
        except RuntimeError as e:
            # Batcher closed: reject, don't crash the handler.
            return pb.OrderResponse(
                code=CODE_REJECT, message=f"rejected: {e}"
            )
        HOSTPROF.note_admit()
        PLACEMENT.note_admit(order.symbol)  # cancels are symbol flow too
        return pb.OrderResponse(code=0, message="cancel accepted")

    def _apply_entries(self, entries) -> pb.OrderBatchResponse:
        """Shared core of the amortized-ingest RPCs: apply (request,
        is_cancel) pairs in order — per-entry validation rejects are
        collected (parallel reject_index/rejects arrays), accepted
        entries mark + emit exactly like their unary counterparts. An
        emit failure stops the batch: the response carries CODE_RETRYABLE
        when the bus is degraded (retry the remainder later) or
        CODE_REJECT when the batcher is closed, and `accepted` says how
        many entries made it into the pipeline before the failure
        (at-most-once for the remainder — the client resubmits them)."""
        resp = pb.OrderBatchResponse()
        accepted = 0
        for i, (request, is_cancel) in enumerate(entries):
            tid, t0 = self._begin_trace()  # per-entry order journey
            if is_cancel:
                try:
                    order = order_from_request(
                        request, Action.DEL, self._accuracy
                    )
                except ValueError as e:
                    resp.reject_index.append(i)
                    resp.rejects.add(code=3, message=f"rejected: {e}")
                    continue
                unmark_on_fail = False
            else:
                try:
                    order = self._validate_add(request)
                except ValueError as e:
                    resp.reject_index.append(i)
                    resp.rejects.add(code=3, message=f"rejected: {e}")
                    continue
                self._mark(order)
                unmark_on_fail = True
            try:
                self._traced_emit(order, tid, t0)
            except (RuntimeError, ConnectionError, OSError) as e:
                if unmark_on_fail:
                    self._unmark(order)
                resp.code = (
                    CODE_RETRYABLE
                    if isinstance(e, (ConnectionError, OSError))
                    else CODE_REJECT
                )
                resp.message = f"batch aborted at entry {i}: {e}"
                break
            accepted += 1
            PLACEMENT.note_admit(order.symbol)  # disabled: one attr check
        resp.accepted = accepted
        if accepted:
            HOSTPROF.note_admit(accepted)  # one locked add per batch
        return resp

    # -- columnar admit core (round 11) ----------------------------------
    #
    # The scalar loop above costs ~13us/order on the host profile, ~84% of
    # it per-order python (order_build + per-order JSON encode + per-order
    # queue put, HOSTPROF_r01). The columnar core touches each proto field
    # exactly once into numpy columns, validates with array masks, interns
    # symbols/uuids once per batch, bulk-marks the pre-pool, and hands the
    # batcher one GCO4 wire block — zero per-order python on the accept
    # path. Per-row semantics (reject codes, messages, precedence, pool
    # contents, decoded frame rows) are identical to the scalar loop:
    # every row the masks cannot *prove* accepted-with-identical-ticks is
    # re-run through the scalar validators, so reject messages come from
    # the same code and float->tick edge cases cannot diverge.

    def _recheck_rows(
        self, reqs, cancel, flagged, ok, price, volume, resp, base
    ):
        """Re-run flagged rows through the scalar validators: definite
        rejects get their byte-identical per-row status here; suspect rows
        (scale overflow range) are patched with fixed.scale's authoritative
        ticks or rejected. Rare path — flagged rows are malformed input or
        >2**51-tick magnitudes."""
        for i in np.nonzero(flagged)[0].tolist():
            try:
                if cancel[i]:
                    order = order_from_request(
                        reqs[i], Action.DEL, self._accuracy
                    )
                else:
                    order = self._validate_add(reqs[i])
                if (
                    abs(order.price) >= 1 << 63
                    or abs(order.volume) >= 1 << 63
                ):
                    # The scalar path admits arbitrary-precision ticks and
                    # would only crash later at struct.pack in the encoder;
                    # the columnar wire is honest about its i64 columns and
                    # rejects at the edge (MIGRATION.md round 11).
                    raise ValueError(
                        "scaled value exceeds the 64-bit wire range"
                    )
                price[i] = order.price
                volume[i] = order.volume
                ok[i] = True
            except ValueError as e:
                ok[i] = False
                resp.reject_index.append(base + i)
                resp.rejects.add(code=3, message=f"rejected: {e}")
        return ok

    def _mark_cols(self, cols: dict) -> None:
        if self._mark_frame is not None:
            self._mark_frame(cols)
            return
        for order in orders_from_columns(cols):
            if order.action is Action.ADD:
                self._mark(order)

    def _unmark_cols(self, cols: dict) -> None:
        if self._unmark_frame is not None:
            self._unmark_frame(cols)
            return
        for order in orders_from_columns(cols):
            if order.action is Action.ADD:
                self._unmark(order)

    def _emit_cols(self, cols: dict, m: int) -> None:  # gomelint: hotpath
        FAULTS.fire("gateway.emit")  # same point as the scalar funnel
        block = encode_order_block(
            m,
            cols["action"],
            cols["side"],
            cols["kind"],
            cols["price"],
            cols["volume"],
            cols["symbols"],
            cols["symbol_idx"],
            cols["uuids"],
            cols["uuid_idx"],
            cols["oids"],
        )
        if self._batcher is not None:
            self._batcher.submit_block(block, m)
        else:
            self._bus.order_queue.publish(
                encode_order_frame_blocks([block])
            )

    def _apply_columnar(
        self, reqs: list, cancel: np.ndarray, resp, base: int = 0
    ) -> int:  # gomelint: hotpath
        """Array-native admission of one batch: validates + interns +
        marks + emits the accepted rows as ONE wire block, appending
        per-row rejects to resp. Returns accepted count. Emission is
        all-or-nothing per block: on emit failure every mark is undone,
        zero rows are accepted, and resp carries the scalar loop's abort
        code/message anchored at the block's first accepted entry."""
        n = len(reqs)
        if n == 0:
            return 0
        # One pass over the cached proto wrappers per numeric field —
        # the caller materialized the repeated field ONCE (upb builds a
        # fresh wrapper per iteration, so repeated passes over the proto
        # itself would triple the extraction cost). Field access is the
        # irreducible protobuf cost.
        trans = np.fromiter(map(_GET_TRANSACTION, reqs), np.int64, n)
        kind = np.fromiter(map(_GET_KIND, reqs), np.int64, n)
        price_f = np.fromiter(map(_GET_PRICE, reqs), np.float64, n)
        vol_f = np.fromiter(map(_GET_VOLUME, reqs), np.float64, n)
        price, price_ok, price_sus = _vector_scale(price_f, self._accuracy)
        volume, vol_ok, vol_sus = _vector_scale(vol_f, self._accuracy)
        ok = (
            (trans >= 0) & (trans <= 1)
            & (kind >= 0) & (kind <= 1)
            & price_ok & vol_ok
        )
        add_ok = volume > 0
        if self._max_volume is not None:
            add_ok &= volume <= self._max_volume
        # MARKET adds skip the price check, like _validate_add.
        add_ok &= (kind != 0) | (price > 0)
        ok &= cancel | add_ok  # cancels skip the ADD-only checks
        flagged = ~ok | price_sus | vol_sus
        if flagged.any():
            ok = self._recheck_rows(
                reqs, cancel, flagged, ok, price, volume, resp, base
            )
        m = int(ok.sum())
        if m == 0:
            return 0
        if m == n:
            keep = None
            sym_src = list(map(_GET_SYMBOL, reqs))
            uid_src = list(map(_GET_UUID, reqs))
            oid_src = list(map(_GET_OID, reqs))
            sel = slice(None)
        else:
            keep = np.nonzero(ok)[0]
            rows = list(map(reqs.__getitem__, keep.tolist()))
            sym_src = list(map(_GET_SYMBOL, rows))
            uid_src = list(map(_GET_UUID, rows))
            oid_src = list(map(_GET_OID, rows))
            sel = keep
        symbols, symbol_idx = _intern(sym_src)
        uuids, uuid_idx = _intern(uid_src)
        try:
            oids = np.asarray(oid_src, dtype="S")
        except UnicodeEncodeError:
            oids = np.asarray([s.encode() for s in oid_src])
        if oids.dtype.itemsize == 0:  # all-empty oid column
            oids = oids.astype("S1")
        cols = {
            "n": m,
            "action": np.where(
                cancel[sel], np.uint8(Action.DEL), np.uint8(Action.ADD)
            ),
            "side": trans[sel].astype(np.uint8),
            "kind": kind[sel].astype(np.uint8),
            "price": price[sel],
            "volume": volume[sel],
            "symbols": symbols,
            "symbol_idx": symbol_idx,
            "uuids": uuids,
            "uuid_idx": uuid_idx,
            "oids": oids,
        }
        self._mark_cols(cols)  # pre-pool before queueing (main.go:44-45)
        try:
            self._emit_cols(cols, m)
        except (RuntimeError, ConnectionError, OSError) as e:
            self._unmark_cols(cols)
            resp.code = (
                CODE_RETRYABLE
                if isinstance(e, (ConnectionError, OSError))
                else CODE_REJECT
            )
            first = base if keep is None else base + int(keep[0])
            resp.message = f"batch aborted at entry {first}: {e}"
            return 0
        HOSTPROF.note_admit(m)  # one locked add per block
        # Symbol-flow sketch (obs.placement): the armed hook bincounts
        # the already-interned columns; disabled it is one attr check.
        PLACEMENT.note_admit_frame(cols["symbols"], cols["symbol_idx"])
        return m

    def DoOrderBatch(
        self, request: pb.OrderBatchRequest, context
    ) -> pb.OrderBatchResponse:
        """Amortized ingest: many reference-shaped OrderRequests in one
        RPC, applied in list order (same-batch ADD->DEL sequencing is
        preserved; `cancel[i]` selects DeleteOrder semantics)."""
        n = len(request.orders)
        if request.cancel and len(request.cancel) != n:
            return pb.OrderBatchResponse(
                code=3,
                message=(
                    f"cancel mask length {len(request.cancel)} != "
                    f"orders length {n}"
                ),
            )
        if self._admission is not None and n:
            # One verdict for the whole batch (all-or-nothing shed:
            # accepted=0, the client resubmits after the hint — the same
            # remainder contract as a batch abort at entry 0).
            d = self._admission.admit(n, _time_remaining(context))
            if not d.ok:
                return pb.OrderBatchResponse(
                    code=CODE_RETRYABLE, message=d.message()
                )
        if self._columnar and not TRACER.enabled and n:
            # Array-native core; per-order trace journeys need the scalar
            # loop (each entry gets its own trace id + wire context).
            resp = pb.OrderBatchResponse()
            if request.cancel:
                cancel = np.fromiter(request.cancel, np.bool_, n)
            else:
                cancel = np.zeros(n, np.bool_)
            resp.accepted = self._apply_columnar(
                list(request.orders), cancel, resp
            )
            return resp
        cancels = request.cancel or (False,) * n
        return self._apply_entries(zip(request.orders, cancels))

    def DoOrderStream(
        self, request_iterator, context
    ) -> pb.OrderBatchResponse:
        """Client-streaming ingest: ADD semantics per message (cancels go
        through DeleteOrder / DoOrderBatch); one summary response when
        the client half-closes."""
        if not (self._columnar and not TRACER.enabled):
            return self._apply_entries(
                (request, False) for request in request_iterator
            )
        # Columnar in STREAM_CHUNK windows: rejects stay per-row with
        # absolute indices; an emit failure aborts the stream with
        # accepted = rows admitted by earlier chunks (the scalar loop's
        # at-most-once remainder contract, at chunk granularity).
        resp = pb.OrderBatchResponse()
        accepted = 0
        base = 0
        chunk: list = []
        for request in request_iterator:
            chunk.append(request)
            if len(chunk) >= STREAM_CHUNK:
                if not self._admit_stream_chunk(resp, len(chunk), context):
                    resp.accepted = accepted
                    return resp
                accepted += self._apply_columnar(
                    chunk, np.zeros(len(chunk), np.bool_), resp, base=base
                )
                if resp.code:
                    resp.accepted = accepted
                    return resp
                base += len(chunk)
                chunk = []
        if chunk:
            if not self._admit_stream_chunk(resp, len(chunk), context):
                resp.accepted = accepted
                return resp
            accepted += self._apply_columnar(
                chunk, np.zeros(len(chunk), np.bool_), resp, base=base
            )
        resp.accepted = accepted
        return resp

    def _admit_stream_chunk(self, resp, n: int, context) -> bool:
        """Admission verdict per stream chunk — a shed aborts the stream
        with the retryable status and accepted = rows admitted by the
        chunks that made it (the established remainder contract)."""
        if self._admission is None:
            return True
        d = self._admission.admit(n, _time_remaining(context))
        if d.ok:
            return True
        resp.code = CODE_RETRYABLE
        resp.message = d.message()
        return False

    def SubscribeMatches(self, request: pb.SubscribeRequest, context):
        if self._match_feed is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED, "no match feed attached"
            )
        yield from self._match_feed.subscribe(context)


def serve_gateway(
    gateway: OrderGateway, config: Config, max_workers: int = 16
) -> grpc.Server:
    """Build + start the gRPC server (main.go:28-36 / grpc.go:24-39's
    listener-from-config). Returns the started server; caller owns
    shutdown."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    add_order_servicer(server, gateway)
    # Server reflection, like the reference (main.go:33) — grpcurl works.
    from ..api.reflection import add_reflection_servicer

    add_reflection_servicer(server)
    addr = f"{config.grpc.host}:{config.grpc.port}"
    bound = server.add_insecure_port(addr)
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC listener on {addr}")
    # Port-0 callers (tests, the fleet drill's subprocess workers) need
    # the OS-assigned port; grpc.Server has no accessor for it.
    server.bound_port = bound
    server.start()
    log.info("gateway serving on %s:%d", config.grpc.host, bound)
    return server

"""Match-event feed — the reference's consume_match_order process
(consume_match_order.go:7-10 → rabbitmq.go:132-177): drains the
"matchOrder" queue, logs each MatchResult (rabbitmq.go:162-171), and — where
the reference leaves a "your code..." stub (rabbitmq.go:169) — fans events
out to in-process subscribers (the gateway's SubscribeMatches stream).
"""

from __future__ import annotations

import queue
import threading

from ..api import order_pb2 as pb
from ..bus import QueueBus, decode_match_result
from ..fixed import unscale
from ..types import MatchResult, OrderSnapshot
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("matchfeed")

_dupes_total = REGISTRY.counter(
    "gome_matchfeed_dupes_total",
    "duplicate matchfeed seqs observed (suppressed before fan-out)",
)
_gaps_total = REGISTRY.counter(
    "gome_matchfeed_gaps_total",
    "missing matchfeed seqs observed (events lost upstream)",
)


class SeqTracker:
    """Subscriber-side exactly-once guard over matchfeed seq numbers.

    ``observe(seq)`` returns False for an already-seen seq (the caller
    suppresses the event) and True otherwise, counting dupes and gaps as
    it goes. The baseline is the FIRST observed seq: a subscriber
    attaching mid-stream must not count everything before its attach
    point as a gap. Pass ``first_seq`` to anchor the stream start instead
    (e.g. 0 for a full-stream audit of a queue read from offset 0).

    A duplicate only rewinds, never re-counts: seqs at or below the
    high-water mark are dupes; anything above it contributes
    ``seq - last - 1`` gaps. Unstamped events (seq None) pass through
    untracked — mixed legacy streams stay deliverable.
    """

    def __init__(self, first_seq: int | None = None):
        # single-writer (all counters): the observe() caller — one
        # delivery thread per tracker (the matchfeed fan-out loop, or the
        # chaos verdict's replay walk). state() readers tolerate
        # staleness; ints rebind atomically under the GIL.
        self.last_seq: int | None = (  # single-writer: observe() caller
            None if first_seq is None else first_seq - 1
        )
        self.dupes = 0  # single-writer: observe() caller
        self.gaps = 0  # single-writer: observe() caller
        self.observed = 0  # single-writer: observe() caller

    def observe(self, seq: int) -> bool:
        self.observed += 1
        last = self.last_seq
        if last is None:
            self.last_seq = seq
            return True
        if seq <= last:
            self.dupes += 1
            _dupes_total.inc()
            return False
        if seq > last + 1:
            self.gaps += seq - last - 1
            _gaps_total.inc(seq - last - 1)
        self.last_seq = seq
        return True

    def state(self) -> dict:
        return {
            "last_seq": self.last_seq,
            "observed": self.observed,
            "dupes": self.dupes,
            "gaps": self.gaps,
        }


def snapshot_to_pb(s: OrderSnapshot) -> pb.OrderSnapshot:
    # Wire doubles carry the reference's observable values: the scaled
    # float64 (SURVEY §2.2 — events serialize post-scaling nodes).
    return pb.OrderSnapshot(
        uuid=s.uuid,
        oid=s.oid,
        symbol=s.symbol,
        transaction=int(s.side),
        price=unscale(s.price),
        volume=unscale(s.volume),
    )


def match_result_to_pb(mr: MatchResult) -> pb.MatchEvent:
    return pb.MatchEvent(
        node=snapshot_to_pb(mr.node),
        match_node=snapshot_to_pb(mr.match_node),
        match_volume=float(mr.match_volume),
    )


class MatchFeed:
    def __init__(self, bus: QueueBus, log_events: bool = True):
        self.bus = bus
        self.log_events = log_events
        self._subs: list[queue.Queue] = []  # guarded by self._lock
        self._lock = threading.Lock()
        self._life = threading.Lock()  # serializes start()/stop()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # guarded by self._life
        self.events_seen = 0  # single-writer: the feed thread (run_once)
        # Exactly-once guard: dupes (same event re-delivered by the
        # at-least-once replay window) are suppressed before fan-out, so
        # subscribers see each seq at most once; gaps are counted loudly
        # (a gap after recovery is a durability bug, never expected).
        self.seq = SeqTracker()
        self.suppressed = 0  # single-writer: the feed thread (run_once)

    def run_once(self) -> int:
        msgs = self.bus.match_queue.poll_batch(256, 0.002)
        if not msgs:
            return 0
        from ..bus.colwire import decode_event_frame, is_frame

        with self._lock:
            subs = list(self._subs)
        for m in msgs:
            if is_frame(m.body):
                # Binary EVENT frame (bus.colwire): one message = a whole
                # batch of MatchResults.
                results = decode_event_frame(m.body).to_results()
            else:
                results = [decode_match_result(m.body)]
            for mr in results:
                if mr.seq is not None and not self.seq.observe(mr.seq):
                    self.suppressed += 1
                    continue
                self.events_seen += 1
                if self.log_events:
                    # rabbitmq.go:170's util.Info.Printf of the result
                    log.info(
                        "match %s: taker=%s maker=%s qty=%d",
                        "CANCEL" if mr.is_cancel else "FILL",
                        mr.node.oid,
                        mr.match_node.oid,
                        mr.match_volume,
                    )
                ev = match_result_to_pb(mr)
                for q in subs:
                    q.put(ev)
        self.bus.match_queue.commit(msgs[-1].offset + 1)
        return len(msgs)

    def drain(self) -> int:
        total = 0
        while self.bus.match_queue.committed() < self.bus.match_queue.end_offset():
            total += self.run_once()
        return total

    def seq_state(self) -> dict:
        """Exactly-once state for /durability."""
        return {**self.seq.state(), "suppressed": self.suppressed}

    def subscribe(self, context=None):
        """Generator of pb.MatchEvent for one subscriber (gateway streaming
        handler). Ends when the gRPC context goes inactive or the feed
        stops."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._subs.append(q)
        try:
            while not self._stop.is_set():
                if context is not None and not context.is_active():
                    return
                try:
                    yield q.get(timeout=0.1)
                except queue.Empty:
                    continue
        finally:
            with self._lock:
                self._subs.remove(q)

    # -- background loop -----------------------------------------------------
    def start(self) -> None:
        # Serialized with stop() under _life: the watchdog restarts a
        # dead feed from ITS thread while an operator (or service
        # shutdown) may be starting/stopping it from another — without
        # the lock two start() calls can both pass the None check and
        # spawn two fan-out loops (double delivery, lost joins).
        with self._life:
            if self._thread is not None:
                raise RuntimeError("feed already started")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="match-feed", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        from ..utils.resilience import backoff_delays
        from .consumer import FAULT_BACKOFF

        delays = None  # backoff across consecutive failures (dead bus)
        while not self._stop.is_set():
            try:
                self.run_once()
                delays = None
            except Exception:
                log.exception("match feed batch failed")
                if delays is None:
                    delays = backoff_delays(FAULT_BACKOFF)
                self._stop.wait(next(delays, FAULT_BACKOFF.max_s))

    def stop(self) -> None:
        # The feed loop never takes _life, so joining under it cannot
        # deadlock; concurrent stop()s serialize harmlessly.
        with self._life:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(timeout=10)
                self._thread = None

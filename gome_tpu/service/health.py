"""Failure detection & supervision (SURVEY §5.3 — the reference has only a
recover() in main and log.Fatalf on MQ errors; crash model: lose in-flight
messages, keep Redis book state).

This framework's stronger model: the consumer/feed loops already survive
per-batch exceptions (service/consumer.py), durability comes from
persist+file-bus replay, and this module adds the missing observability and
supervision:

  HealthMonitor — point-in-time health snapshot: thread liveness,
                  heartbeat age, queue lags, engine capacity pressure,
                  and per-connection resilience state (breaker state,
                  reconnect/retry counts, time degraded — every
                  utils.resilience.Supervised in the process) plus the
                  gateway's degraded-mode spill (service.batcher).
  Watchdog      — periodic checks with a restart policy for dead loops
                  (bounded restarts — persistent crash loops surface
                  instead of flapping forever).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("health")

_restarts = REGISTRY.counter(
    "gome_loop_restarts_total", "consumer/feed loops restarted by watchdog"
)


@dataclasses.dataclass
class Health:
    healthy: bool
    consumer_alive: bool
    feed_alive: bool
    heartbeat_age_s: float
    order_lag: int  # unconsumed messages in doOrder
    match_lag: int  # undelivered messages in matchOrder
    lane_pressure: float  # provisioned-lane utilization [0, 1]
    detail: dict

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class HealthMonitor:
    def __init__(self, service, stall_after_s: float = 30.0):
        """service: EngineService. stall_after_s: heartbeat age beyond which
        a *running* consumer thread counts as stalled (wedged device call,
        deadlock) — the failure mode liveness alone misses."""
        self.service = service
        self.stall_after_s = stall_after_s
        self._beat = time.monotonic()  # single-writer: heartbeat() — the consumer loop

    def heartbeat(self) -> None:
        self._beat = time.monotonic()

    def check(self) -> Health:
        svc = self.service
        consumer_thread = svc.consumer._thread
        feed_thread = svc.feed._thread
        consumer_alive = bool(consumer_thread and consumer_thread.is_alive())
        feed_alive = bool(feed_thread and feed_thread.is_alive())
        oq = svc.bus.order_queue
        mq = svc.bus.match_queue
        order_lag = oq.end_offset() - oq.committed()
        match_lag = mq.end_offset() - mq.committed()
        batch = svc.engine.batch
        lane_pressure = len(batch.symbols) / max(batch.max_slots, 1)
        age = time.monotonic() - self._beat
        stalled = consumer_alive and order_lag > 0 and age > self.stall_after_s
        healthy = consumer_alive and feed_alive and not stalled
        from ..utils.resilience import resilience_snapshot

        connections = resilience_snapshot()
        degraded = any(c["breaker"] != "closed" for c in connections.values())
        gateway = {}
        batcher = getattr(svc.gateway, "_batcher", None)
        if batcher is not None:
            gateway = batcher.stats()
            degraded = degraded or gateway.get("degraded", False)
        return Health(
            healthy=healthy,
            consumer_alive=consumer_alive,
            feed_alive=feed_alive,
            heartbeat_age_s=age,
            order_lag=order_lag,
            match_lag=match_lag,
            lane_pressure=lane_pressure,
            detail={
                "stalled": stalled,
                "orders_processed": batch.stats.orders,
                "cap_escalations": batch.stats.cap_escalations,
                "device_calls": batch.stats.device_calls,
                # Transport degradation is NOT unhealthy (matching keeps
                # running; durability covers the gap) but operators need
                # to see it: supervised-connection + spill state.
                "degraded": degraded,
                "connections": connections,
                "gateway": gateway,
            },
        )


class Watchdog:
    """Periodically checks health and restarts dead loops. Crash-looping
    components get max_restarts attempts within window_s, then the watchdog
    stops restarting and marks the service unhealthy (a supervisor above —
    systemd/k8s — takes over, with durability guaranteeing replay)."""

    def __init__(
        self,
        service,
        monitor: HealthMonitor | None = None,
        interval_s: float = 1.0,
        max_restarts: int = 5,
        window_s: float = 60.0,
    ):
        self.service = service
        self.monitor = monitor or HealthMonitor(service)
        self.interval_s = interval_s
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._restart_times: list[float] = []  # single-writer: the watchdog thread (check_once)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # single-writer: start()/stop() caller
        self.gave_up = False  # single-writer: the watchdog thread (check_once)

    def check_once(self) -> Health:
        h = self.monitor.check()
        if not h.consumer_alive and self.service.consumer._thread is not None:
            self._restart("consumer", self.service.consumer)
        if not h.feed_alive and self.service.feed._thread is not None:
            self._restart("feed", self.service.feed)
        return h

    def _restart(self, name: str, component) -> None:
        now = time.monotonic()
        self._restart_times = [
            t for t in self._restart_times if now - t < self.window_s
        ]
        if len(self._restart_times) >= self.max_restarts:
            if not self.gave_up:
                log.error(
                    "%s crash-looping (%d restarts in %.0fs); giving up — "
                    "escalate to the process supervisor",
                    name, len(self._restart_times), self.window_s,
                )
                self.gave_up = True
            return
        log.warning("restarting dead %s loop", name)
        self._restart_times.append(now)
        _restarts.inc()
        component.stop()
        component.start()

    # -- background loop -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("watchdog already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="watchdog", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:
                log.exception("health check failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

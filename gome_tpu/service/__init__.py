"""Service layer: the gRPC gateway, the order consumer, and the match-event
feed — the reference's three processes (gomengine/main.go,
consume_new_order.go, consume_match_order.go) as composable components that
run in one binary (default) or separately against a shared `file` bus."""

from .gateway import OrderGateway, serve_gateway
from .consumer import OrderConsumer
from .matchfeed import MatchFeed
from .app import EngineService

__all__ = [
    "OrderGateway",
    "serve_gateway",
    "OrderConsumer",
    "MatchFeed",
    "EngineService",
]

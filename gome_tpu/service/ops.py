"""Operator HTTP endpoint: /metrics (Prometheus text format from
utils.metrics.REGISTRY), /healthz (service.health.HealthMonitor JSON),
/trace (the order-lifecycle flight recorder as Chrome trace-event JSON —
load the dump in chrome://tracing or https://ui.perfetto.dev), /cost
(device-level attribution JSON: the compile journal, live-buffer
residency, and the XLA cost model incl. the donation-effectiveness
report — gome_tpu.obs), and /timeline (the host-side steady-state
sampler's bounded series — gome_tpu.obs.timeline).

The reference has no observability surface at all (SURVEY §5.5 — logging
only); this is the cheap operator-facing extension the TPU service ships:
one stdlib ThreadingHTTPServer, no dependencies, curl-able:

    curl localhost:9109/metrics
    curl localhost:9109/healthz     # 200 healthy / 503 unhealthy
    curl localhost:9109/trace > trace.json   # open in Perfetto
    curl localhost:9109/cost        # compiles + HBM + per-entry cost
    curl localhost:9109/timeline    # RSS/rusage/live-buffer time series
    curl localhost:9109/profile     # measured roofline (capture on demand)
    curl localhost:9109/hostprof    # host-CPU stage attribution (?drill=1
                                    # runs the admit drill; ?format=collapsed
                                    # dumps flamegraph-ready stacks)
    curl localhost:9109/durability  # snapshot cadence, recovery state,
                                    # matchfeed exactly-once tracker,
                                    # fault-injection report
    curl localhost:9109/fleet       # merged N-process view (obs.fleet):
                                    # per-member health, summed counters,
                                    # fleet-wide seq audit
    curl localhost:9109/capacity    # installed capacity-sweep verdict
                                    # (obs.capacity): offered-rate ladder,
                                    # knee, corrected percentiles,
                                    # bottleneck attribution
    curl localhost:9109/placement   # symbol-flow heavy hitters, lane/shard
                                    # occupancy ledger, skew attribution,
                                    # and the committed what-if placement
                                    # verdict (obs.placement)

Enabled by an `ops:` section in config.yaml (port, host) or by
constructing OpsServer directly around any EngineService.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("ops")


class OpsServer:
    """HTTP server exposing /metrics and /healthz for one EngineService.

    start() binds and serves on a daemon thread; port 0 picks a free port
    (the bound port is in `self.port`)."""

    def __init__(self, service=None, host: str = "127.0.0.1", port: int = 0,
                 registry=REGISTRY, tracer=None):
        from ..utils.trace import TRACER

        self.service = service
        self.host = host
        self.port = port  # single-writer: start() caller (rebound to the bound port)
        self.registry = registry
        self.tracer = tracer or TRACER  # /trace reads its flight recorder
        self._httpd: ThreadingHTTPServer | None = None  # single-writer: start()/stop() caller
        self._thread: threading.Thread | None = None  # single-writer: start()/stop() caller
        self.monitor = None
        self.live_monitor = None
        if service is not None:
            from .health import HealthMonitor

            self.monitor = HealthMonitor(service)
            from ..obs.live import service_monitor

            # Tagged live-buffer residency for /cost and the
            # gome_hbm_resident_bytes{subsystem=...} gauges — all
            # scrape-time reads, nothing on the hot path.
            self.live_monitor = service_monitor(service)
            self.live_monitor.export(self.registry)

    def cost_payload(self) -> dict:
        """The /cost JSON document: compile journal (gome_tpu.obs.
        compile_journal.JOURNAL), live-buffer residency, and the
        memoized XLA cost model + donation-effectiveness report. The
        cost model compiles tiny canonical-geometry executables on first
        read (memoized process-wide); a backend without cost_analysis
        degrades to null fields rather than a 500."""
        from ..obs.compile_journal import JOURNAL
        from ..obs.live import LiveBufferMonitor

        payload: dict = {"compile_journal": JOURNAL.as_dict()}
        mon = self.live_monitor or LiveBufferMonitor()
        payload["live_buffers"] = mon.snapshot()
        try:
            from ..obs import costmodel

            dtype = "int32"
            svc = self.service
            if svc is not None:
                import numpy as np

                engine = getattr(svc, "engine", None)
                if engine is not None:
                    dtype = np.dtype(engine.config.dtype).name
            payload["cost_model"] = {
                "dtype": dtype,
                "entries": costmodel.entry_report(dtype),
                "donation": costmodel.donation_report(dtype),
            }
        except Exception as exc:  # never 500 the whole surface
            log.exception("cost model unavailable")
            payload["cost_model"] = {"error": str(exc)}
        return payload

    def timeline_payload(self) -> dict:
        """The /timeline JSON document: the process-global timeline
        sampler's bounded series (gome_tpu.obs.timeline.TIMELINE —
        {"enabled", "interval_s", "samples": [...]}; empty but valid
        while the sampler is disabled)."""
        from ..obs.timeline import TIMELINE

        return TIMELINE.as_dict()

    def profile_payload(self, refresh: bool = False) -> dict:
        """The /profile JSON document: the measured roofline
        (gome_tpu.obs.profiler.PROFILER) — per-entry device time,
        achieved GFLOP/s / GB/s, efficiency vs the analytic ceiling,
        the Perfetto artifact path, and the per-shard dispatch
        telemetry. Armed with no capture yet (or ``?refresh=1``) this
        captures on demand — seconds of bounded work on the handler
        thread, never the dispatch path; disabled it returns
        ``{"enabled": false}``."""
        from ..obs.profiler import PROFILER

        dtype = "int32"
        svc = self.service
        if svc is not None:
            import numpy as np

            engine = getattr(svc, "engine", None)
            if engine is not None:
                dtype = np.dtype(engine.config.dtype).name
        return PROFILER.payload(dtype=dtype, refresh=refresh)

    def durability_payload(self) -> dict:
        """The /durability JSON document: the crash-consistency surface in
        one read — Persister state (snapshot cadence, last restore,
        recovery timing), queue offsets (published / committed per
        queue), the matchfeed exactly-once tracker, and the fault-
        injection registry's report (plan + hit counts; `enabled: false`
        outside chaos runs). Every field is a scrape-time read."""
        from ..utils.faults import FAULTS

        svc = self.service
        payload: dict = {"faults": FAULTS.report()}
        persist = getattr(svc, "persist", None)
        payload["persist"] = (
            persist.probe() if persist is not None else None
        )
        feed = getattr(svc, "feed", None)
        payload["matchfeed"] = (
            feed.seq_state()
            if feed is not None and hasattr(feed, "seq_state")
            else None
        )
        consumer = getattr(svc, "consumer", None)
        if consumer is not None:
            payload["consumer"] = {
                "match_seq": getattr(consumer, "match_seq", None),
            }
        bus = getattr(svc, "bus", None)
        queues = {}
        for qname in ("order_queue", "match_queue"):
            q = getattr(bus, qname, None)
            if q is None or not hasattr(q, "end_offset"):
                continue
            try:
                queues[qname] = {
                    "end": q.end_offset(),
                    "committed": q.committed(),
                }
            except Exception:  # a dead backend must not 500 the payload
                queues[qname] = {"error": "unreadable"}
        payload["queues"] = queues
        return payload

    def fleet_payload(self) -> dict:
        """The /fleet JSON document: the fleet aggregator's merged view
        (gome_tpu.obs.fleet.FLEET) — per-member health + degraded
        rollup, the merged metric exposition with per-family totals,
        the fleet-wide matchfeed seq audit, and member timeline tails.
        ``{"enabled": false}`` while no member map is installed."""
        from ..obs.fleet import FLEET

        return FLEET.payload()

    def capacity_payload(self) -> dict:
        """The /capacity JSON document: the installed load-sweep verdict
        (gome_tpu.obs.capacity.CAPACITY) — the offered-rate ladder with
        corrected (coordinated-omission-safe) percentiles, the detected
        saturation knee, and the per-stage bottleneck attribution table.
        ``{"enabled": false}`` while no verdict is installed."""
        from ..obs.capacity import CAPACITY

        return CAPACITY.payload()

    def placement_payload(self) -> dict:
        """The /placement JSON document: the placement observatory
        (gome_tpu.obs.placement.PLACEMENT) — the heavy-hitter symbol
        table + mergeable sketch bytes, the dispatch occupancy ledger
        (rows, padding, per-shard blocks), the hot-lane EWMA table, the
        skew attribution rows against the committed baselines, and the
        installed what-if placement verdict (scripts/placement_eval.py).
        ``{"enabled": false}`` while disarmed."""
        from ..obs.placement import PLACEMENT

        return PLACEMENT.payload()

    def hostprof_payload(self, run_drill: bool = False) -> dict:
        """The /hostprof JSON document: the host-CPU sampling profiler
        (gome_tpu.obs.hostprof.HOSTPROF) — the live wall-profile stage
        join plus the last admit-drill report (measured per-stage
        gateway ns/order and achievable orders/sec/core). ``?drill=1``
        runs the deterministic admit drill on demand — sub-second of
        bounded work on the handler thread, never the serving path;
        disabled it returns ``{"enabled": false}``."""
        from ..obs.hostprof import HOSTPROF

        return HOSTPROF.payload(run_drill=run_drill)

    def start(self) -> "OpsServer":
        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route into our logger
                log.debug("http %s", fmt % args)

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = ops.registry.render().encode()
                        self._send(
                            200, body, "text/plain; version=0.0.4"
                        )
                    elif self.path.split("?")[0] == "/healthz":
                        if ops.monitor is None:
                            self._send(
                                200, b'{"healthy": true, "detail": '
                                b'"no service attached"}\n',
                                "application/json",
                            )
                            return
                        health = ops.monitor.check()
                        body = (
                            json.dumps(health.as_dict(), default=str) + "\n"
                        ).encode()
                        self._send(
                            200 if health.healthy else 503, body,
                            "application/json",
                        )
                    elif self.path.split("?")[0] == "/cost":
                        body = json.dumps(
                            ops.cost_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/timeline":
                        body = json.dumps(
                            ops.timeline_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/profile":
                        refresh = "refresh=1" in (
                            self.path.split("?", 1)[1:] or [""]
                        )[0]
                        body = json.dumps(
                            ops.profile_payload(refresh=refresh),
                            default=str,
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/hostprof":
                        query = (self.path.split("?", 1)[1:] or [""])[0]
                        if "format=collapsed" in query:
                            from ..obs.hostprof import HOSTPROF

                            self._send(
                                200, HOSTPROF.collapsed().encode(),
                                "text/plain",
                            )
                            return
                        body = json.dumps(
                            ops.hostprof_payload(
                                run_drill="drill=1" in query
                            ),
                            default=str,
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/durability":
                        body = json.dumps(
                            ops.durability_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/fleet":
                        body = json.dumps(
                            ops.fleet_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/capacity":
                        body = json.dumps(
                            ops.capacity_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/placement":
                        body = json.dumps(
                            ops.placement_payload(), default=str
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path.split("?")[0] == "/trace":
                        query = (self.path.split("?", 1)[1:] or [""])[0]
                        rec = ops.tracer.recorder
                        if "format=journeys" in query:
                            # The fleet aggregator's stitch feed: raw
                            # journeys (open ones included — a gateway
                            # process never completes its half) instead
                            # of the Chrome-trace render.
                            dump = (
                                rec.export()
                                if rec is not None
                                else {"pid": None, "journeys": []}
                            )
                        else:
                            dump = (
                                rec.chrome_trace()
                                if rec is not None
                                else {"traceEvents": []}
                            )
                        body = json.dumps(dump).encode()
                        self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception:  # never kill the handler thread
                    log.exception("ops endpoint error")
                    try:
                        self._send(500, b"internal error\n", "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ops-http", daemon=True
        )
        self._thread.start()
        log.info("ops endpoint up on %s:%d (/metrics, /healthz, /trace, "
                 "/cost, /timeline, /profile, /hostprof, /durability, "
                 "/fleet, /capacity, /placement)",
                 self.host, self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

"""The gateway->frame batching bridge: per-request gRPC traffic becomes
columnar ORDER frames.

The reference's gateway publishes one JSON document per request
(main.go:46-48 via engine.go:35-44); at frame-consumer rates that wire
costs more than matching. This bridge is the production answer to "who
aggregates requests into frames": the gRPC handlers submit accepted
orders here (after marking the pre-pool, exactly like their per-order
publish), and the bridge flushes one binary ORDER frame (bus.colwire) to
the doOrder queue when either

  * `max_n` orders accumulated (throughput bound), or
  * `max_wait_s` elapsed since the oldest buffered order (latency bound —
    this IS the batching latency cost, and it is configurable: a frame
    closes at most max_wait_s after the order that opened it).

Arrival order is preserved (one lock-guarded buffer; the flusher swaps
the whole buffer out under the lock), so the per-symbol FIFO invariant
(SURVEY §5.2) holds through the bridge. Consumers need no changes: the
order consumer already sniffs frames vs JSON per message, so a deployment
can switch the gateway to the bridge mid-stream.

Degraded mode (bus unavailable): a frame whose publish fails with a
ConnectionError — the supervised bus client raises one when its backoff
budget is exhausted or its circuit is open — is SPILLED to a bounded
in-memory deque instead of being lost or blocking handlers forever. The
deadline thread keeps retrying the spill FIFO (spilled frames always go
out before younger ones, preserving order); once `spill_max_frames` is
reached, submit() raises Backpressure and the gateway rejects with a
RETRYABLE status — bounded buffering with explicit backpressure, never
unbounded growth and never silent drops. Spill depth and time-in-degraded
are exported through utils.metrics (scrape-time callback gauges), and
service/health.py folds them into /healthz."""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from ..bus.colwire import encode_order_frame_blocks, encode_orders
from ..types import Order
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from ..utils.trace import TRACER, decode_context, encode_context

log = get_logger("batcher")

_rejects = REGISTRY.counter(
    "gome_gateway_retryable_rejects_total",
    "orders rejected retryable because the degraded-mode spill was full",
)
_spilled = REGISTRY.counter(
    "gome_gateway_spilled_frames_total",
    "ORDER frames diverted to the in-memory spill on publish failure",
)


class Backpressure(ConnectionError):
    """The degraded-mode spill is full: the order was NOT accepted and the
    client should retry later (gateway maps this to a retryable reject).
    Subclasses ConnectionError so generic bus-fault handling applies."""


class FrameBatcher:
    """Order accumulator flushing ORDER frames to a queue.

    submit() is thread-safe (gRPC handler threads call it concurrently);
    flushes happen on the submitting thread when the size bound trips, or
    on the background deadline thread for the latency bound. close()
    flushes the remainder and stops the deadline thread."""

    def __init__(
        self,
        queue,
        max_n: int = 4096,
        max_wait_s: float = 0.002,
        spill_max_frames: int = 64,
        retry_interval_s: float = 0.05,
        min_n: int | None = None,
        depth_fn=None,
        depth_low: int = 256,
        depth_high: int = 8192,
        resize_interval_s: float = 0.05,
    ):
        """min_n + depth_fn arm ADAPTIVE frame sizing (round 12): the
        size bound interpolates between min_n (consumer lag <= depth_low
        — queues shallow, close frames early for latency) and max_n
        (lag >= depth_high — backed up, amortize hard for throughput).
        depth_fn is the consumer-lag read (bus.order_queue.depth); it is
        sampled at most every resize_interval_s, off the per-submit hot
        path. Omit either and the bound is the fixed max_n of rounds
        <= 11. The latency bound (max_wait_s) is never adapted — it is
        the explicit worst-case promise."""
        if max_n < 1:
            raise ValueError("max_n must be >= 1")
        if spill_max_frames < 1:
            raise ValueError("spill_max_frames must be >= 1")
        self.queue = queue
        self.max_n = max_n
        self.max_wait_s = max_wait_s
        self.spill_max_frames = spill_max_frames
        self.retry_interval_s = retry_interval_s
        if min_n is not None and depth_fn is not None:
            if not (1 <= min_n <= max_n):
                raise ValueError("need 1 <= min_n <= max_n")
            if not (0 <= depth_low < depth_high):
                raise ValueError("need 0 <= depth_low < depth_high")
            self._adaptive = True
        else:
            self._adaptive = False
        self.min_n = min_n if self._adaptive else max_n
        self._depth_fn = depth_fn
        self.depth_low = depth_low
        self.depth_high = depth_high
        self.resize_interval_s = resize_interval_s
        self._eff_n = max_n if not self._adaptive else min_n  # guarded by self._lock
        self._eff_at = -1.0  # guarded by self._lock
        # Mixed buffer: scalar handlers append Order objects, the columnar
        # admit core appends pre-encoded wire BLOCKS (bytes) via
        # submit_block — flushing walks contiguous runs so arrival order
        # is preserved across both producers without re-decoding blocks.
        self._buf: list[Order | bytes] = []  # guarded by self._lock
        # _buf_n is the buffered ORDER count (a bytes block counts its
        # n orders, an Order counts 1), kept incrementally because
        # len(_buf) undercounts once blocks land.
        self._buf_n = 0  # guarded by self._lock
        self._spill: deque[bytes] = deque()  # guarded by self._lock
        self._degraded_since: float | None = None  # guarded by self._lock
        self.degraded_seconds_total = 0.0  # guarded by self._lock
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop_event = threading.Event()
        self._stop = False  # guarded by self._lock (see close())
        self._oldest: float | None = None  # guarded by self._lock
        # Scrape-time callbacks run on the ops HTTP thread WITHOUT the
        # lock on purpose: _flush_locked holds it across a bus publish,
        # and a scrape must never stall behind (or deadlock against) a
        # slow broker. len() and a float read are single bytecode ops
        # under the GIL — a torn gauge is impossible, merely stale.
        REGISTRY.callback_gauge(
            "gome_gateway_spill_depth",
            "degraded-mode spill depth (ORDER frames awaiting the bus)",
            lambda: len(self._spill),  # gomelint: disable=GL402 — see above
        )
        REGISTRY.callback_gauge(
            "gome_gateway_buffered_orders",
            "orders buffered in the batcher awaiting a frame flush "
            "(the batching-bridge queue depth)",
            lambda: self._buf_n,  # gomelint: disable=GL402 — see above
        )
        REGISTRY.callback_gauge(
            "gome_gateway_frame_target",
            "current effective frame-size bound (adaptive sizing; equals "
            "max_n when the adaptive bridge is not armed)",
            lambda: self._eff_n,  # gomelint: disable=GL402 — see above
        )
        REGISTRY.callback_gauge(
            "gome_gateway_degraded_seconds",
            "seconds the gateway has been in degraded mode (0 healthy)",
            lambda: (
                time.monotonic() - self._degraded_since  # gomelint: disable=GL402
                if self._degraded_since is not None  # gomelint: disable=GL402
                else 0.0
            ),
        )
        self._thread = threading.Thread(
            target=self._deadline_loop, name="frame-batcher", daemon=True
        )
        self._thread.start()

    # -- degraded-mode state (callers: gateway handlers, health) -----------
    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded_since is not None

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            degraded_s = (
                now - self._degraded_since
                if self._degraded_since is not None
                else 0.0
            )
            return dict(
                degraded=self._degraded_since is not None,
                degraded_s=degraded_s,
                degraded_seconds_total=self.degraded_seconds_total
                + degraded_s,
                spill_depth=len(self._spill),
                spill_max_frames=self.spill_max_frames,
                buffered=self._buf_n,
                effective_max_n=self._eff_n,
                adaptive=self._adaptive,
            )

    def effective_max_n(self) -> int:
        """Current frame-size bound; recomputes the adaptive target when
        the sample window expired (public for tests/ops introspection)."""
        with self._lock:
            return self._effective_locked()

    def _effective_locked(self) -> int:  # gomelint: hotpath
        """Frame-size bound under self._lock. Adaptive mode linearly
        interpolates min_n..max_n over the depth_low..depth_high lag
        band, sampling depth_fn at most every resize_interval_s; the
        result is always clamped to [min_n, max_n] even against a
        misbehaving depth_fn (negative / NaN-ish readings)."""
        if not self._adaptive:
            return self.max_n
        now = time.monotonic()
        if now - self._eff_at >= self.resize_interval_s:
            self._eff_at = now
            try:
                depth = int(self._depth_fn())
            except Exception:
                # A broken lag probe must never stall admission; fall
                # back to the throughput-safe bound.
                depth = self.depth_high
            frac = (depth - self.depth_low) / (
                self.depth_high - self.depth_low
            )
            frac = min(max(frac, 0.0), 1.0)
            eff = round(self.min_n + frac * (self.max_n - self.min_n))
            self._eff_n = min(max(eff, self.min_n), self.max_n)
        return self._eff_n

    def submit(self, order: Order) -> None:  # gomelint: hotpath
        """Buffer one accepted order; flush if the size bound tripped.

        The encode+publish happens UNDER the lock: a swapped-out batch
        published outside it could be overtaken by the next batch (a
        descheduled flusher), inverting price-time priority across
        frames. Holding the lock serializes frames in arrival order; the
        cost is submitters briefly blocking behind one frame encode
        (~1 ms at 4K orders), which is the batching backpressure.

        Raises RuntimeError after close(): the deadline thread is gone,
        so a buffered order below max_n would be stranded forever — a
        late gRPC handler must fail loudly, not accept-and-drop. Raises
        Backpressure while the degraded-mode spill is full: bounded
        buffering means at some depth new orders must be refused
        (retryable) rather than silently queued to infinity."""
        with self._lock:
            if self._stop:
                raise RuntimeError(
                    "FrameBatcher is closed; order not accepted"
                )
            if len(self._spill) >= self.spill_max_frames:
                _rejects.inc()
                raise Backpressure(
                    f"bus degraded: spill full "
                    f"({self.spill_max_frames} frames); retry later"
                )
            if not self._buf:
                self._oldest = time.monotonic()
                self._wake.set()
            self._buf.append(order)
            self._buf_n += 1
            if self._buf_n >= self._effective_locked():
                self._flush_locked()

    def submit_block(self, block: bytes, n: int) -> None:  # gomelint: hotpath
        """Buffer one pre-encoded ORDER wire block of `n` accepted orders
        (the columnar admit core's output, bus.colwire.encode_order_block);
        flush if the size bound tripped. Same closed/backpressure contract
        as submit() — a refused block means NONE of its orders were
        accepted (the gateway unmarks and rejects the whole batch)."""
        with self._lock:
            if self._stop:
                raise RuntimeError(
                    "FrameBatcher is closed; order not accepted"
                )
            if len(self._spill) >= self.spill_max_frames:
                _rejects.inc(n)
                raise Backpressure(
                    f"bus degraded: spill full "
                    f"({self.spill_max_frames} frames); retry later"
                )
            if not self._buf:
                self._oldest = time.monotonic()
                self._wake.set()
            self._buf.append(block)
            self._buf_n += n
            if self._buf_n >= self._effective_locked():
                self._flush_locked()

    def flush(self) -> int:
        """Flush whatever is buffered now; returns the count flushed into
        a frame (the frame may land in the spill if the bus is down)."""
        with self._lock:
            return self._flush_locked()

    def _encode_order_run(self, orders: list[Order]) -> bytes:
        if TRACER.enabled:
            orders = self._close_batch_wait(orders)
        return encode_orders(orders)

    def _flush_locked(self) -> int:  # gomelint: hotpath
        batch, n = self._swap_locked()
        if batch:
            # Split into contiguous runs so arrival order survives mixed
            # producers: an Order run becomes one GCO2/GCO3 frame (pure
            # scalar traffic stays byte-identical to the pre-columnar
            # wire), a block run becomes ONE GCO4 frame with no
            # decode/re-encode round-trip — the columnar path's whole
            # point (HOSTPROF_r01: the JSON round-trip was ~45% of admit
            # CPU).
            orders: list[Order] = []
            blocks: list[bytes] = []
            for item in batch:
                if isinstance(item, bytes):
                    if orders:
                        self._spill.append(self._encode_order_run(orders))
                        orders = []
                    blocks.append(item)
                else:
                    if blocks:
                        self._spill.append(
                            encode_order_frame_blocks(blocks)
                        )
                        blocks = []
                    orders.append(item)
            if orders:
                self._spill.append(self._encode_order_run(orders))
            if blocks:
                self._spill.append(encode_order_frame_blocks(blocks))
        self._drain_spill_locked()
        return n

    @staticmethod
    def _close_batch_wait(batch: list[Order]) -> list[Order]:
        """Order-lifecycle tracing: each traced order's context carries
        the gateway's enqueue timestamp — close its batch_wait span
        (submit -> frame close) and re-stamp the context with the flush
        time so the consumer's bus_transit span starts here. Runs only
        while the tracer is armed; untraced orders pass through
        untouched."""
        now = TRACER.clock()
        out = []
        for o in batch:
            if o.trace is not None:
                tid, t0 = decode_context(o.trace)
                TRACER.add_span(tid, "batch_wait", t0, now)
                o = dataclasses.replace(
                    o, trace=encode_context(tid, now)
                )
            out.append(o)
        return out

    def _drain_spill_locked(self) -> None:
        """Publish spilled frames FIFO (oldest first — frame order on the
        wire is arrival order even across an outage). A publish fault
        enters/extends degraded mode and leaves the remainder for the
        deadline thread's next retry tick."""
        while self._spill:
            try:
                self.queue.publish(self._spill[0])
            except (ConnectionError, OSError) as e:
                if self._degraded_since is None:
                    self._degraded_since = time.monotonic()
                    _spilled.inc(len(self._spill))
                    log.warning(
                        "bus publish failed (%s): degraded mode, "
                        "%d frame(s) spilled", e, len(self._spill),
                    )
                else:
                    _spilled.inc(1)
                return
            self._spill.popleft()
        if self._degraded_since is not None:
            self.degraded_seconds_total += (
                time.monotonic() - self._degraded_since
            )
            self._degraded_since = None
            log.info("bus recovered: degraded mode over, spill drained")

    def _swap_locked(self):
        batch, self._buf = self._buf, []
        n, self._buf_n = self._buf_n, 0
        self._oldest = None
        return batch, n

    def _deadline_loop(self) -> None:  # gomelint: hotpath
        while True:
            with self._lock:
                spilled = bool(self._spill)
            if not spilled:
                self._wake.wait()
            # gomelint: disable=GL402 — benign stale read: a bool load is
            # one bytecode under the GIL; a missed True is caught on the
            # next wake, and close() sets _wake after _stop.
            if self._stop:  # gomelint: disable=GL402
                return
            with self._lock:
                oldest = self._oldest
                if oldest is None and not self._spill:
                    self._wake.clear()
                    continue
            if oldest is not None:
                delay = oldest + self.max_wait_s - time.monotonic()
            else:
                # Degraded with an empty buffer: the spill is the only
                # pending work — retry it on its own cadence.
                delay = self.retry_interval_s
            if delay > 0:
                # Interruptible: close() sets the stop event, so a large
                # max_wait_s never pins the thread (or close's join).
                if self._stop_event.wait(delay):
                    return
            with self._lock:
                # Flush only if the head is still overdue (a size-bound
                # flush may have raced and restarted the window).
                if (
                    self._oldest is not None
                    and time.monotonic() >= self._oldest + self.max_wait_s
                ):
                    self._flush_locked()
                elif self._spill:
                    self._drain_spill_locked()
                if self._oldest is None and not self._spill:
                    self._wake.clear()

    def close(self) -> None:
        """Flush the remainder and stop the deadline thread.

        _stop is set UNDER the buffer lock: any submit that already
        passed its closed-check has appended before we get the lock, so
        the final flush below catches it — no order can slip between the
        check and the flush and be stranded."""
        with self._lock:
            self._stop = True
        self._stop_event.set()
        self._wake.set()
        self._thread.join(timeout=5)
        self.flush()
        with self._lock:
            if self._spill:
                # Bounded loss, loudly: the process is exiting with the
                # bus still down. The spill was never acknowledged past
                # the gateway's accept, and at-least-once clients retry.
                log.error(
                    "closing with %d undelivered spilled frame(s) — "
                    "bus still down", len(self._spill),
                )

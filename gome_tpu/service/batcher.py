"""The gateway->frame batching bridge: per-request gRPC traffic becomes
columnar ORDER frames.

The reference's gateway publishes one JSON document per request
(main.go:46-48 via engine.go:35-44); at frame-consumer rates that wire
costs more than matching. This bridge is the production answer to "who
aggregates requests into frames": the gRPC handlers submit accepted
orders here (after marking the pre-pool, exactly like their per-order
publish), and the bridge flushes one binary ORDER frame (bus.colwire) to
the doOrder queue when either

  * `max_n` orders accumulated (throughput bound), or
  * `max_wait_s` elapsed since the oldest buffered order (latency bound —
    this IS the batching latency cost, and it is configurable: a frame
    closes at most max_wait_s after the order that opened it).

Arrival order is preserved (one lock-guarded buffer; the flusher swaps
the whole buffer out under the lock), so the per-symbol FIFO invariant
(SURVEY §5.2) holds through the bridge. Consumers need no changes: the
order consumer already sniffs frames vs JSON per message, so a deployment
can switch the gateway to the bridge mid-stream.
"""

from __future__ import annotations

import threading

from ..bus.colwire import encode_orders
from ..types import Order


class FrameBatcher:
    """Order accumulator flushing ORDER frames to a queue.

    submit() is thread-safe (gRPC handler threads call it concurrently);
    flushes happen on the submitting thread when the size bound trips, or
    on the background deadline thread for the latency bound. close()
    flushes the remainder and stops the deadline thread."""

    def __init__(self, queue, max_n: int = 4096, max_wait_s: float = 0.002):
        if max_n < 1:
            raise ValueError("max_n must be >= 1")
        self.queue = queue
        self.max_n = max_n
        self.max_wait_s = max_wait_s
        self._buf: list[Order] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop_event = threading.Event()
        self._stop = False
        self._oldest: float | None = None  # monotonic time of buffer head
        self._thread = threading.Thread(
            target=self._deadline_loop, name="frame-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, order: Order) -> None:
        """Buffer one accepted order; flush if the size bound tripped.

        The encode+publish happens UNDER the lock: a swapped-out batch
        published outside it could be overtaken by the next batch (a
        descheduled flusher), inverting price-time priority across
        frames. Holding the lock serializes frames in arrival order; the
        cost is submitters briefly blocking behind one frame encode
        (~1 ms at 4K orders), which is the batching backpressure.

        Raises RuntimeError after close(): the deadline thread is gone,
        so a buffered order below max_n would be stranded forever — a
        late gRPC handler must fail loudly, not accept-and-drop."""
        with self._lock:
            if self._stop:
                raise RuntimeError(
                    "FrameBatcher is closed; order not accepted"
                )
            if not self._buf:
                import time

                self._oldest = time.monotonic()
                self._wake.set()
            self._buf.append(order)
            if len(self._buf) >= self.max_n:
                self._flush_locked()

    def flush(self) -> int:
        """Flush whatever is buffered now; returns the count flushed."""
        with self._lock:
            return self._flush_locked()

    def _flush_locked(self) -> int:
        batch = self._swap_locked()
        if batch:
            self.queue.publish(encode_orders(batch))
        return len(batch)

    def _swap_locked(self) -> list[Order]:
        batch, self._buf = self._buf, []
        self._oldest = None
        return batch

    def _deadline_loop(self) -> None:
        import time

        while True:
            self._wake.wait()
            if self._stop:
                return
            with self._lock:
                oldest = self._oldest
                if oldest is None:
                    self._wake.clear()
                    continue
            delay = oldest + self.max_wait_s - time.monotonic()
            if delay > 0:
                # Interruptible: close() sets the stop event, so a large
                # max_wait_s never pins the thread (or close's join).
                if self._stop_event.wait(delay):
                    return
            with self._lock:
                # Flush only if the head is still overdue (a size-bound
                # flush may have raced and restarted the window).
                if (
                    self._oldest is not None
                    and time.monotonic() >= self._oldest + self.max_wait_s
                ):
                    self._flush_locked()
                if self._oldest is None:
                    self._wake.clear()

    def close(self) -> None:
        """Flush the remainder and stop the deadline thread.

        _stop is set UNDER the buffer lock: any submit that already
        passed its closed-check has appended before we get the lock, so
        the final flush below catches it — no order can slip between the
        check and the flush and be stranded."""
        with self._lock:
            self._stop = True
        self._stop_event.set()
        self._wake.set()
        self._thread.join(timeout=5)
        self.flush()

"""Order consumer — the reference's consume_new_order process
(consume_new_order.go:7-10 → rabbitmq.go:86-130) with the micro-batching the
TPU engine needs.

The reference drains one message at a time and runs the full match path per
order (rabbitmq.go:116-125). Here the loop polls a micro-batch (N orders or
T µs, whichever first — SURVEY §7 hard part (e)), feeds it to the batched
device engine in arrival order (same-symbol order preserved by lane packing,
batch.py), publishes every resulting MatchResult to the "matchOrder" queue
(engine.go:154-158's role), and only then commits the consumed offset —
at-least-once where the reference is at-most-once (auto-ack,
rabbitmq.go:102; SURVEY §2.3.6).
"""

from __future__ import annotations

import threading

from ..bus import QueueBus, decode_orders_batch
from ..engine.orchestrator import MatchEngine
from ..utils.faults import FAULTS
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from ..utils.resilience import BackoffPolicy, backoff_delays
from ..utils.trace import TRACER, decode_context
from ..utils.tracing import annotate

log = get_logger("consumer")

_orders_total = REGISTRY.counter(
    "gome_orders_consumed_total", "orders drained from the doOrder queue"
)
_events_total = REGISTRY.counter(
    "gome_match_events_total", "MatchResult events published"
)
_batch_size = REGISTRY.histogram(
    "gome_batch_size", "orders per device micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
)
_batch_latency = REGISTRY.histogram(
    "gome_batch_seconds", "wall time per micro-batch (decode+match+publish)"
)
_throughput = REGISTRY.gauge(
    "gome_orders_per_second", "EWMA matching throughput"
)
_poisoned = REGISTRY.counter(
    "gome_poison_orders_total",
    "orders dead-lettered by the poison-batch policy",
)
_step_failures = REGISTRY.counter(
    "gome_consumer_step_failures_total",
    "consumer steps that raised (bus fault, device error, poison batch)",
)

#: Backoff between consecutive FAILED consumer/feed steps: a dead bus must
#: not busy-spin the loop (each failed poll would otherwise burn a core
#: re-raising the same ConnectionError); a transient fault retries almost
#: immediately. Reset on the first successful step.
FAULT_BACKOFF = BackoffPolicy(
    base_s=0.01, max_s=1.0, max_retries=1_000_000, budget_s=float("inf")
)


class OrderConsumer:
    def __init__(
        self,
        engine: MatchEngine,
        bus: QueueBus,
        batch_n: int = 256,
        batch_wait_s: float = 0.002,
        on_batch=None,
        poison_threshold: int = 3,
        match_wire: str = "json",
        pipeline_depth: int = 0,
    ):
        """match_wire: "json" publishes one reference-shape JSON document
        per event (rabbitmq.go wire parity); "frame" publishes one binary
        EVENT frame per batch (bus.colwire) — the high-throughput internal
        transport (the feed decodes both).

        pipeline_depth > 0 enables cross-frame pipelining for ORDER-frame
        traffic (engine.pipeline.FramePipeline): up to that many frames
        stay in flight on the device while the host packs the next, and a
        frame's offset commits only once ITS events published. Requires a
        MatchEngine (admit_frame); JSON messages still process
        synchronously (the pipeline drains first, preserving order)."""
        if match_wire not in ("json", "frame"):
            raise ValueError(f"match_wire must be json|frame, got {match_wire}")
        if pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0")
        if pipeline_depth > 0 and not hasattr(engine, "admit_frame"):
            raise ValueError(
                "pipeline_depth requires a MatchEngine (admit_frame); the "
                f"given engine {type(engine).__name__} has no frame pipeline"
            )
        self.engine = engine
        self.bus = bus
        self.match_wire = match_wire
        self.batch_n = batch_n
        self.batch_wait_s = batch_wait_s
        self.pipeline_depth = pipeline_depth
        # single-writer: the consuming thread — the _loop thread once
        # start()ed, or the sync run_once()/drain()/pump() caller; the
        # two modes never run concurrently (start() is the boundary).
        self._pipe = None  # single-writer: the consuming thread (lazy FramePipeline)
        # Persist-hook counts deferred to the next pipeline-empty boundary
        # (on_batch must only observe consistent cuts; see _emit_resolved).
        self._hook_orders = 0  # single-writer: the consuming thread
        self._hook_events = 0  # single-writer: the consuming thread
        self.on_batch = on_batch  # callback(n_orders, n_events): persist hook
        # Poison-batch policy: a deterministic per-batch error (e.g. a lane
        # CapacityError) would otherwise replay the same uncommitted offset
        # forever and halt matching engine-wide. After `poison_threshold`
        # consecutive failures at the SAME committed offset, the batch is
        # replayed order-by-order and the offending orders dead-lettered
        # (logged + counted) so the stream advances.
        self.poison_threshold = poison_threshold
        self._fail_offset = -1  # single-writer: the consuming thread
        self._fail_count = 0  # single-writer: the consuming thread
        # Order-lifecycle tracing: in-flight frames' journey ids keyed by
        # queue offset (pipelined mode publishes/completes at resolve
        # time, which can be several steps after the feed).
        self._pipe_tids: dict[int, list] = {}
        # Matchfeed sequence numbers (ISSUE 11 exactly-once): match_seq is
        # the next seq to stamp — monotonic per book epoch, advanced by
        # _publish. _seq_committed is its value at the last durable
        # order-queue commit; a failed step rolls match_seq back to it so
        # the at-least-once replay regenerates IDENTICAL seqs (duplicates
        # carry the same seq and are suppressed by SeqTracker downstream).
        self.match_seq = 0  # single-writer: the consuming thread
        self._seq_committed = 0  # single-writer: the consuming thread
        self._last_step_failed = False  # single-writer: the consuming thread
        self._stop = threading.Event()
        self._life = threading.Lock()  # serializes start()/stop()
        self._thread: threading.Thread | None = None  # guarded by self._life

    def reset_seq(self, seq: int) -> None:
        """Recovery hook (persist.Persister.restore_latest): rebase the
        matchfeed seq to the restored cut's manifest value. WAL replay
        then regenerates the truncated match tail with the same seqs it
        had pre-crash."""
        # gomelint: disable=GL704 — happens-before, not a second writer:
        # restore_latest() runs during EngineService.start() BEFORE
        # consumer.start() spawns the loop (app.py orders them), and the
        # chaos/recovery drills call it on a stopped consumer.
        self.match_seq = seq  # gomelint: disable=GL704
        self._seq_committed = seq  # gomelint: disable=GL704

    def _consume_traces(self, cols: dict, headers) -> list:
        """Order-lifecycle tracing, receipt side: pop the GCO3 trace
        column off a decoded ORDER frame (the engine never sees it — its
        admission filters would desync it from the kept rows), close each
        traced order's bus_transit span from the context's carried
        publish timestamp, and return the journey ids for batch-scoped
        attribution. A headers-only context (AMQP x-trace on an opaque
        body) traces the whole message. [] while tracing is off — the
        column is still popped so tracing-off consumers interop with
        tracing-on producers."""
        raw = cols.pop("trace", None)
        tr = TRACER
        if not tr.enabled:
            return []
        t_rx = tr.clock()
        tids = []
        if raw is not None:
            for ctx in raw.tolist():
                if not ctx:
                    continue
                tid, t_pub = decode_context(ctx.decode())
                tr.add_span(tid, "bus_transit", t_pub or t_rx, t_rx)
                tids.append(tid)
        elif headers and headers.get("x-trace"):
            tid, t_pub = decode_context(headers["x-trace"])
            tr.add_span(tid, "bus_transit", t_pub or t_rx, t_rx)
            tids.append(tid)
        return tids

    def _json_traces(self, orders, msgs) -> list:
        """bus_transit spans for a decoded JSON run: context from the
        order body (codec Trace field), falling back to the message's
        AMQP x-trace header (one order per JSON message)."""
        tr = TRACER
        if not tr.enabled:
            return []
        t_rx = tr.clock()
        tids = []
        for o, m in zip(orders, msgs):
            ctx = o.trace
            if ctx is None and m.headers:
                ctx = m.headers.get("x-trace")
            if not ctx:
                continue
            tid, t_pub = decode_context(ctx)
            tr.add_span(tid, "bus_transit", t_pub or t_rx, t_rx)
            tids.append(tid)
        return tids

    def _publish(self, batch) -> None:
        # Frame publishing needs real EventBatch columns; the sharded
        # facade's compatibility wrapper (router._ResultsBatch) publishes
        # reference JSON instead. Every event is stamped with the next
        # matchfeed seq (GCE2 header / JSON "Seq" / AMQP x-seq);
        # match_seq only advances once the publish SUCCEEDED, so a failed
        # publish replays with the same seqs.
        seq0 = self.match_seq
        n = len(batch)
        if self.match_wire == "frame" and hasattr(batch, "columns"):
            from ..bus.colwire import encode_event_frame

            if n:
                mq = self.bus.match_queue
                frame = encode_event_frame(batch, seq0=seq0)
                if mq.supports_headers:
                    # Alongside PR 2's x-trace: stringified per AMQP
                    # header conventions (bus/amqp.py).
                    mq.publish(frame, headers={"x-seq": str(seq0)})
                else:
                    mq.publish(frame)
        else:
            # one write+fsync for the whole batch on the native backend
            self.bus.match_queue.publish_batch(batch.to_json_lines(seq0=seq0))
        self.match_seq = seq0 + n

    def run_once(self) -> int:  # gomelint: hotpath
        """Drain one micro-batch; returns the number of orders processed."""
        if self.pipeline_depth > 0:
            return self._run_once_pipelined()
        msgs = self.bus.order_queue.poll_batch(self.batch_n, self.batch_wait_s)
        if not msgs:
            return 0
        from ..bus.colwire import decode_order_frame, is_frame

        n_orders = n_events = 0
        done_tids: list = []
        with _batch_latency.time() as timer:
            # Split the poll into runs: contiguous JSON messages decode as
            # one batch (native codec); a binary ORDER frame (colwire) IS
            # a batch and takes the zero-per-order-Python frame path. Both
            # producers can share the queue (migration story).
            i = 0
            while i < len(msgs):
                FAULTS.fire("consumer.frame")
                if is_frame(msgs[i].body):
                    with annotate("engine_process_frame"):
                        cols = decode_order_frame(msgs[i].body)
                        tids = self._consume_traces(cols, msgs[i].headers)
                        with TRACER.batch(tids):
                            batch = self.engine.process_frame(cols)
                        count = int(cols["n"])
                    with annotate("publish_events"), TRACER.batch(tids), \
                            TRACER.span("publish"):
                        self._publish(batch)
                    done_tids += tids
                    n_orders += count
                    n_events += len(batch)
                    i += 1
                else:
                    i, n_o, n_e, tids = self._process_json_run(msgs, i)
                    done_tids += tids
                    n_orders += n_o
                    n_events += n_e
            # Commit only after results are published: a crash between
            # processing and commit replays the batch (at-least-once;
            # recovery dedup lives in gome_tpu.persist's replay logic).
            FAULTS.fire("consumer.commit")
            self.bus.order_queue.commit(msgs[-1].offset + 1)
            self._seq_committed = self.match_seq
        for tid in done_tids:  # journeys are complete once committed
            TRACER.complete(tid)
        _orders_total.inc(n_orders)
        _events_total.inc(n_events)
        _batch_size.observe(n_orders)
        if timer.elapsed > 0:
            inst = n_orders / timer.elapsed
            _throughput.set(0.8 * _throughput.value() + 0.2 * inst)
        if self.on_batch is not None:
            self.on_batch(n_orders, n_events)
        return n_orders

    def _process_json_run(self, msgs, i: int) -> tuple[int, int, int, list]:
        """Decode + process + publish one contiguous run of JSON messages
        starting at msgs[i]; returns (j, n_orders, n_events, trace_ids)
        with j the first index past the run. The CALLER commits — commit
        policy differs between the synchronous and pipelined paths — and
        completes the returned journeys. Columnar path end to end: events
        stay as numpy columns from decode through wire serialization; no
        per-event Python objects on the hot path."""
        from ..bus.colwire import is_frame

        j = i
        while j < len(msgs) and not is_frame(msgs[j].body):
            j += 1
        with annotate("decode_orders"):
            orders = decode_orders_batch([m.body for m in msgs[i:j]])
        tids = self._json_traces(orders, msgs[i:j])
        with annotate("engine_process"), TRACER.batch(tids):
            batch = self.engine.process_columnar(orders)
        with annotate("publish_events"), TRACER.batch(tids), \
                TRACER.span("publish"):
            self._publish(batch)
        return j, len(orders), len(batch), tids

    def _emit_resolved(self, token, batch) -> int:
        """Publish one resolved frame's events and commit ITS offset —
        frames resolve in FIFO order, so commits stay monotonic. The
        persist hook (on_batch) is NOT called here: with frames in flight
        the books are AHEAD of the committed offset, so a snapshot taken
        now would double-apply the in-flight span on recovery; the counts
        accumulate and the hook fires at the next pipeline-empty boundary
        (a consistent cut)."""
        offset, n = token
        tids = self._pipe_tids.pop(offset, None) or []
        with annotate("publish_events"), TRACER.batch(tids), \
                TRACER.span("publish"):
            self._publish(batch)
        FAULTS.fire("consumer.commit")
        self.bus.order_queue.commit(offset + 1)
        self._seq_committed = self.match_seq
        self._account(n, len(batch))
        for tid in tids:
            TRACER.complete(tid)
        return n

    def _account(self, n_orders: int, n_events: int) -> None:
        """Bookkeeping for one processed-and-committed unit in pipelined
        mode: metrics now, persist hook deferred to the next consistent
        cut."""
        _orders_total.inc(n_orders)
        _events_total.inc(n_events)
        _batch_size.observe(n_orders)
        self._hook_orders += n_orders
        self._hook_events += n_events

    def _run_once_pipelined(self) -> int:
        """One consumer step with cross-frame pipelining: ORDER frames are
        SUBMITTED to the device (host pack only) and a frame's offset
        commits when it RESOLVES (fetch + decode) and its events publish —
        up to pipeline_depth frames stay in flight, so frame k+1's host
        work overlaps frame k's device execution + fetch. Non-frame (JSON)
        runs drain the pipeline first (one frame at a time — a publish
        failure loses at most one frame's events), then batch-decode as in
        run_once. Any failure aborts the in-flight span (books rewound,
        pre-pool marks restored) and re-raises — the at-least-once replay
        from the uncommitted offset re-feeds it."""
        from ..bus.colwire import decode_order_frame, is_frame
        from ..engine.pipeline import FramePipeline

        q = self.bus.order_queue
        if self._pipe is None:
            self._pipe = FramePipeline(self.engine, depth=self.pipeline_depth)
        pipe = self._pipe
        n_orders = 0
        try:
            if len(pipe) == 0:
                msgs = q.poll_batch(self.batch_n, self.batch_wait_s)
                if not msgs:
                    return 0
            else:
                # Read cursor: committed offset + one message per in-flight
                # frame (only whole ORDER-frame messages stay in flight).
                msgs = q.read_from(q.committed() + len(pipe), self.batch_n)
            with _batch_latency.time() as timer:
                if not msgs:
                    # Queue idle: make progress on the in-flight span.
                    out = pipe.step()
                    if out is not None:
                        n_orders += self._emit_resolved(*out)
                i = 0
                while i < len(msgs):
                    FAULTS.fire("consumer.frame")
                    m = msgs[i]
                    if is_frame(m.body):
                        cols = decode_order_frame(m.body)
                        tids = self._consume_traces(cols, m.headers)
                        if tids:
                            self._pipe_tids[m.offset] = tids
                        with annotate("pipeline_feed"), TRACER.batch(tids):
                            resolved = pipe.feed(
                                cols, token=(m.offset, int(cols["n"]))
                            )
                        for token, batch in resolved:
                            n_orders += self._emit_resolved(token, batch)
                        i += 1
                    else:
                        while True:  # drain in-flight, emit-as-resolved
                            out = pipe.step()
                            if out is None:
                                break
                            n_orders += self._emit_resolved(*out)
                        j, n_o, n_e, jtids = self._process_json_run(msgs, i)
                        q.commit(msgs[j - 1].offset + 1)
                        self._seq_committed = self.match_seq
                        n_orders += n_o
                        self._account(n_o, n_e)
                        for tid in jtids:
                            TRACER.complete(tid)
                        i = j
        except Exception:
            # feed/resolve already restored their own frames' state; abort
            # rewinds whatever is STILL in flight (a failed queue READ
            # included — frames must never outlive a poison-policy
            # quarantine) so the replay from the committed offset sees a
            # consistent engine.
            pipe.abort()
            # The replay re-feeds the aborted frames and re-records their
            # journeys' consumer-side spans; stale id->offset entries
            # would mis-attribute the replay's publishes.
            self._pipe_tids.clear()
            raise
        if n_orders and timer.elapsed > 0:
            inst = n_orders / timer.elapsed
            _throughput.set(0.8 * _throughput.value() + 0.2 * inst)
        if (
            len(pipe) == 0
            and self.on_batch is not None
            and (self._hook_orders or self._hook_events)
        ):
            # Consistent cut: books correspond exactly to the committed
            # offset only when nothing is in flight — the persist hook
            # (snapshot cadence) must only observe such states.
            self.on_batch(self._hook_orders, self._hook_events)
            self._hook_orders = self._hook_events = 0
        return n_orders

    def drain(self) -> int:
        """Process until the order queue is empty (tests, recovery replay)."""
        total = 0
        while self.bus.order_queue.committed() < self.bus.order_queue.end_offset():
            total += self.run_once()
        return total

    # -- background loop -----------------------------------------------------
    def start(self) -> None:
        # Serialized with stop() under _life: the watchdog restarts a
        # dead consumer from ITS thread while service shutdown (or an
        # operator) may be stopping it from another — without the lock
        # two start() calls can both pass the None check and spawn two
        # consumer loops (doubled batches, lost joins).
        with self._life:
            if self._thread is not None:
                raise RuntimeError("consumer already started")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="order-consumer", daemon=True
            )
            self._thread.start()

    # gomelint: hotpath
    def _loop(self) -> None:
        # Consecutive failures back off (decorrelated jitter) instead of
        # busy-spinning against a dead dependency; any success resets.
        delays = None
        while not self._stop.is_set():
            self.step_with_policy()
            if self._last_step_failed:
                if delays is None:
                    delays = backoff_delays(FAULT_BACKOFF)
                self._stop.wait(next(delays, FAULT_BACKOFF.max_s))
            else:
                delays = None

    def step_with_policy(self) -> int:
        """One consumer step with the poison-batch policy applied. Returns
        orders processed (0 on a failed or empty step). Never raises — the
        consumer thread must survive any failure (the reference panics
        instead; a transient bus outage must not kill matching)."""
        self._last_step_failed = False
        try:
            n = self.run_once()
            self._fail_count = 0
            return n
        except Exception:  # keep consuming; reference panics instead
            # Seq rollback to the last durable commit: the replay from the
            # uncommitted offset re-publishes with IDENTICAL seqs, so any
            # double-delivery is detectable (and suppressed) downstream.
            self.match_seq = self._seq_committed
            self._last_step_failed = True
            _step_failures.inc()
            log.exception("order batch failed")
            try:
                offset = self.bus.order_queue.committed()
                if offset == self._fail_offset:
                    self._fail_count += 1
                else:
                    self._fail_offset, self._fail_count = offset, 1
                if self._fail_count >= self.poison_threshold:
                    self._fail_count = 0
                    # Quarantine replays order-by-order from the committed
                    # offset: anything still in flight in the pipeline
                    # would be double-applied — abort it first (books
                    # rewound, marks restored).
                    if self._pipe is not None:
                        self._pipe.abort()
                        self._pipe_tids.clear()
                    return self.quarantine_once()
            except Exception:
                log.exception("poison-batch policy step failed; will retry")
            return 0

    def quarantine_once(self) -> int:
        """Replay the head batch isolating poison ORDERS by bisection:
        a failing chunk splits in half (FIFO preserved) until the failing
        singleton is found, which is dead-lettered (logged + counted in
        gome_poison_orders_total, its pre-pool mark cleared) — the stream
        advances past it while every healthy order in the same message
        (a 256K-order frame included) still matches and publishes.

        A publish failure is NOT a poison order: the quarantine pass stops
        without committing that offset (standard at-least-once replay — the
        same window run_once has between processing and commit), so no
        events are ever dead-lettered because the match queue hiccuped."""
        msgs = self.bus.order_queue.poll_batch(self.batch_n, 0)
        processed = 0
        from ..bus import decode_message_orders

        for m in msgs:
            try:
                orders = decode_message_orders(m.body)
            except Exception:
                # Undecodable message: nothing to salvage.
                _poisoned.inc(1)
                log.exception(
                    "dead-lettering undecodable message at offset %d",
                    m.offset,
                )
                self.bus.order_queue.commit(m.offset + 1)
                self._seq_committed = self.match_seq
                continue
            ok, n_ok = self._bisect_apply(orders)
            if not ok:
                return processed  # publish hiccup: leave offset for replay
            self.bus.order_queue.commit(m.offset + 1)
            self._seq_committed = self.match_seq
            processed += n_ok
            _orders_total.inc(n_ok)
            if self.on_batch is not None:
                self.on_batch(n_ok, 0)
        return processed

    def _bisect_apply(self, orders) -> tuple[bool, int]:
        """Process `orders` in FIFO order, bisecting around failures until
        poison singletons are isolated and dead-lettered. Returns
        (publish_ok, orders_processed); publish_ok=False means the match
        queue failed and the caller must not commit (engine work already
        applied rides the at-least-once replay window)."""
        if not orders:
            return True, 0
        try:
            batch = self.engine.process_columnar(orders)
        except Exception:
            if len(orders) == 1:
                order = orders[0]
                try:  # confirm determinism: transient faults retry clean
                    batch = self.engine.process_columnar(orders)
                except Exception:
                    _poisoned.inc(1)
                    log.exception(
                        "dead-lettering poison order oid=%s symbol=%s",
                        order.oid, order.symbol,
                    )
                    # The failed call restored its consumed pre-pool mark;
                    # a dead-lettered ADD will never be replayed, so the
                    # mark must not linger (it would persist into
                    # snapshots as a live queued ADD).
                    unmark = getattr(self.engine, "unmark", None)
                    if unmark is not None:
                        unmark(order)
                    return True, 0
            else:
                mid = len(orders) // 2
                ok, a = self._bisect_apply(orders[:mid])
                if not ok:
                    return False, a
                ok, b = self._bisect_apply(orders[mid:])
                return ok, a + b
        try:
            self._publish(batch)
        except Exception:
            log.exception(
                "publish failed during quarantine; leaving offset for replay"
            )
            return False, 0
        _events_total.inc(len(batch))
        return True, len(orders)

    def stop(self) -> None:
        # The consumer loop never takes _life, so joining under it cannot
        # deadlock; concurrent stop()s serialize harmlessly.
        with self._life:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(timeout=10)
                self._thread = None

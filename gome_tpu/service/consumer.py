"""Order consumer — the reference's consume_new_order process
(consume_new_order.go:7-10 → rabbitmq.go:86-130) with the micro-batching the
TPU engine needs.

The reference drains one message at a time and runs the full match path per
order (rabbitmq.go:116-125). Here the loop polls a micro-batch (N orders or
T µs, whichever first — SURVEY §7 hard part (e)), feeds it to the batched
device engine in arrival order (same-symbol order preserved by lane packing,
batch.py), publishes every resulting MatchResult to the "matchOrder" queue
(engine.go:154-158's role), and only then commits the consumed offset —
at-least-once where the reference is at-most-once (auto-ack,
rabbitmq.go:102; SURVEY §2.3.6).
"""

from __future__ import annotations

import threading

from ..bus import QueueBus, decode_orders_batch
from ..engine.orchestrator import MatchEngine
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from ..utils.tracing import annotate

log = get_logger("consumer")

_orders_total = REGISTRY.counter(
    "gome_orders_consumed_total", "orders drained from the doOrder queue"
)
_events_total = REGISTRY.counter(
    "gome_match_events_total", "MatchResult events published"
)
_batch_size = REGISTRY.histogram(
    "gome_batch_size", "orders per device micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
)
_batch_latency = REGISTRY.histogram(
    "gome_batch_seconds", "wall time per micro-batch (decode+match+publish)"
)
_throughput = REGISTRY.gauge(
    "gome_orders_per_second", "EWMA matching throughput"
)
_poisoned = REGISTRY.counter(
    "gome_poison_orders_total",
    "orders dead-lettered by the poison-batch policy",
)


class OrderConsumer:
    def __init__(
        self,
        engine: MatchEngine,
        bus: QueueBus,
        batch_n: int = 256,
        batch_wait_s: float = 0.002,
        on_batch=None,
        poison_threshold: int = 3,
        match_wire: str = "json",
        pipeline_depth: int = 0,
    ):
        """match_wire: "json" publishes one reference-shape JSON document
        per event (rabbitmq.go wire parity); "frame" publishes one binary
        EVENT frame per batch (bus.colwire) — the high-throughput internal
        transport (the feed decodes both).

        pipeline_depth > 0 enables cross-frame pipelining for ORDER-frame
        traffic (engine.pipeline.FramePipeline): up to that many frames
        stay in flight on the device while the host packs the next, and a
        frame's offset commits only once ITS events published. Requires a
        MatchEngine (admit_frame); JSON messages still process
        synchronously (the pipeline drains first, preserving order)."""
        if match_wire not in ("json", "frame"):
            raise ValueError(f"match_wire must be json|frame, got {match_wire}")
        self.engine = engine
        self.bus = bus
        self.match_wire = match_wire
        self.batch_n = batch_n
        self.batch_wait_s = batch_wait_s
        self.on_batch = on_batch  # callback(n_orders, n_events): persist hook
        # Poison-batch policy: a deterministic per-batch error (e.g. a lane
        # CapacityError) would otherwise replay the same uncommitted offset
        # forever and halt matching engine-wide. After `poison_threshold`
        # consecutive failures at the SAME committed offset, the batch is
        # replayed order-by-order and the offending orders dead-lettered
        # (logged + counted) so the stream advances.
        self.poison_threshold = poison_threshold
        self._fail_offset = -1
        self._fail_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _publish(self, batch) -> None:
        # Frame publishing needs real EventBatch columns; the sharded
        # facade's compatibility wrapper (router._ResultsBatch) publishes
        # reference JSON instead.
        if self.match_wire == "frame" and hasattr(batch, "columns"):
            from ..bus.colwire import encode_event_frame

            if len(batch):
                self.bus.match_queue.publish(encode_event_frame(batch))
        else:
            # one write+fsync for the whole batch on the native backend
            self.bus.match_queue.publish_batch(batch.to_json_lines())

    def run_once(self) -> int:
        """Drain one micro-batch; returns the number of orders processed."""
        msgs = self.bus.order_queue.poll_batch(self.batch_n, self.batch_wait_s)
        if not msgs:
            return 0
        from ..bus.colwire import decode_order_frame, is_frame

        n_orders = n_events = 0
        with _batch_latency.time() as timer:
            # Split the poll into runs: contiguous JSON messages decode as
            # one batch (native codec); a binary ORDER frame (colwire) IS
            # a batch and takes the zero-per-order-Python frame path. Both
            # producers can share the queue (migration story).
            i = 0
            while i < len(msgs):
                if is_frame(msgs[i].body):
                    with annotate("engine_process_frame"):
                        cols = decode_order_frame(msgs[i].body)
                        batch = self.engine.process_frame(cols)
                        count = int(cols["n"])
                    i += 1
                else:
                    j = i
                    while j < len(msgs) and not is_frame(msgs[j].body):
                        j += 1
                    with annotate("decode_orders"):
                        orders = decode_orders_batch(
                            [m.body for m in msgs[i:j]]
                        )
                    with annotate("engine_process"):
                        # Columnar path end to end: events stay as numpy
                        # columns from decode through wire serialization;
                        # no per-event Python objects on the hot path.
                        batch = self.engine.process_columnar(orders)
                    count = len(orders)
                    i = j
                with annotate("publish_events"):
                    self._publish(batch)
                n_orders += count
                n_events += len(batch)
            # Commit only after results are published: a crash between
            # processing and commit replays the batch (at-least-once;
            # recovery dedup lives in gome_tpu.persist's replay logic).
            self.bus.order_queue.commit(msgs[-1].offset + 1)
        _orders_total.inc(n_orders)
        _events_total.inc(n_events)
        _batch_size.observe(n_orders)
        if timer.elapsed > 0:
            inst = n_orders / timer.elapsed
            _throughput.set(0.8 * _throughput.value() + 0.2 * inst)
        if self.on_batch is not None:
            self.on_batch(n_orders, n_events)
        return n_orders

    def drain(self) -> int:
        """Process until the order queue is empty (tests, recovery replay)."""
        total = 0
        while self.bus.order_queue.committed() < self.bus.order_queue.end_offset():
            total += self.run_once()
        return total

    # -- background loop -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("consumer already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="order-consumer", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.step_with_policy()

    def step_with_policy(self) -> int:
        """One consumer step with the poison-batch policy applied. Returns
        orders processed (0 on a failed or empty step). Never raises — the
        consumer thread must survive any failure (the reference panics
        instead; a transient bus outage must not kill matching)."""
        try:
            n = self.run_once()
            self._fail_count = 0
            return n
        except Exception:  # keep consuming; reference panics instead
            log.exception("order batch failed")
            try:
                offset = self.bus.order_queue.committed()
                if offset == self._fail_offset:
                    self._fail_count += 1
                else:
                    self._fail_offset, self._fail_count = offset, 1
                if self._fail_count >= self.poison_threshold:
                    self._fail_count = 0
                    return self.quarantine_once()
            except Exception:
                log.exception("poison-batch policy step failed; will retry")
            return 0

    def quarantine_once(self) -> int:
        """Replay the head batch isolating poison ORDERS by bisection:
        a failing chunk splits in half (FIFO preserved) until the failing
        singleton is found, which is dead-lettered (logged + counted in
        gome_poison_orders_total, its pre-pool mark cleared) — the stream
        advances past it while every healthy order in the same message
        (a 256K-order frame included) still matches and publishes.

        A publish failure is NOT a poison order: the quarantine pass stops
        without committing that offset (standard at-least-once replay — the
        same window run_once has between processing and commit), so no
        events are ever dead-lettered because the match queue hiccuped."""
        msgs = self.bus.order_queue.poll_batch(self.batch_n, 0)
        processed = 0
        from ..bus.colwire import decode_order_frame, is_frame

        for m in msgs:
            try:
                if is_frame(m.body):
                    from ..engine.frames import orders_from_frame

                    orders = orders_from_frame(decode_order_frame(m.body))
                else:
                    orders = decode_orders_batch([m.body])
            except Exception:
                # Undecodable message: nothing to salvage.
                _poisoned.inc(1)
                log.exception(
                    "dead-lettering undecodable message at offset %d",
                    m.offset,
                )
                self.bus.order_queue.commit(m.offset + 1)
                continue
            ok, n_ok = self._bisect_apply(orders)
            if not ok:
                return processed  # publish hiccup: leave offset for replay
            self.bus.order_queue.commit(m.offset + 1)
            processed += n_ok
            _orders_total.inc(n_ok)
            if self.on_batch is not None:
                self.on_batch(n_ok, 0)
        return processed

    def _bisect_apply(self, orders) -> tuple[bool, int]:
        """Process `orders` in FIFO order, bisecting around failures until
        poison singletons are isolated and dead-lettered. Returns
        (publish_ok, orders_processed); publish_ok=False means the match
        queue failed and the caller must not commit (engine work already
        applied rides the at-least-once replay window)."""
        if not orders:
            return True, 0
        try:
            batch = self.engine.process_columnar(orders)
        except Exception:
            if len(orders) == 1:
                order = orders[0]
                try:  # confirm determinism: transient faults retry clean
                    batch = self.engine.process_columnar(orders)
                except Exception:
                    _poisoned.inc(1)
                    log.exception(
                        "dead-lettering poison order oid=%s symbol=%s",
                        order.oid, order.symbol,
                    )
                    # The failed call restored its consumed pre-pool mark;
                    # a dead-lettered ADD will never be replayed, so the
                    # mark must not linger (it would persist into
                    # snapshots as a live queued ADD).
                    unmark = getattr(self.engine, "unmark", None)
                    if unmark is not None:
                        unmark(order)
                    return True, 0
            else:
                mid = len(orders) // 2
                ok, a = self._bisect_apply(orders[:mid])
                if not ok:
                    return False, a
                ok, b = self._bisect_apply(orders[mid:])
                return ok, a + b
        try:
            self._publish(batch)
        except Exception:
            log.exception(
                "publish failed during quarantine; leaving offset for replay"
            )
            return False, 0
        _events_total.inc(len(batch))
        return True, len(orders)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

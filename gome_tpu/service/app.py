"""EngineService — the whole stack assembled from one Config.

The reference runs three processes wired by external RabbitMQ/Redis
(README.md run instructions; SURVEY §1): gRPC server, order consumer, match
consumer. Here the default deployment is one binary hosting all three
components around the in-process (or file) bus; the same components can
also run in separate processes against a shared `file`/`amqp` bus — a
`redis:` config section then puts the pre-pool markers in a
Redis-compatible store (the built-in RESP client + engine.prepool.
RespPrePool; persist/respserver.py is a standalone stand-in server), which
is exactly the reference's own trade (nodepool.go:14-28) and gives the
split topology reference race semantics (tested in
tests/test_multiprocess.py::test_three_process_prepool_reference_topology).
"""

from __future__ import annotations

import os

from ..bus import make_bus
from ..config import Config
from ..engine.orchestrator import MatchEngine
from ..utils.logging import configure as configure_logging, get_logger
from .consumer import OrderConsumer
from .gateway import OrderGateway, serve_gateway
from .matchfeed import MatchFeed

log = get_logger("app")


class EngineService:
    def __init__(self, config: Config | None = None, persist=None):
        self.config = config or Config()
        configure_logging()
        if self.config.faults.enabled:
            # Arm the deterministic fault-injection registry (utils.faults)
            # BEFORE the bus exists so boot-time injection points (torn
            # sidecar reads, first appends) are covered. Chaos/test
            # tooling only; without a `faults:` section FAULTS stays a
            # zero-allocation no-op.
            from ..utils.faults import FAULTS

            FAULTS.install(self.config.faults.fault_plan())
            log.warning(
                "fault injection ARMED (seed=%d, %d specs) — chaos/test "
                "mode, never production",
                self.config.faults.seed,
                len(self.config.faults.fault_plan().faults),
            )
        self.bus = make_bus(self.config.bus)
        from ..bus.base import export_queue_metrics

        # Per-queue depth/lag gauges (gome_bus_depth{queue=...}): scrape-
        # time reads of local queue state, registered for both queues on
        # every backend — the per-partition fan-in telemetry obs.fleet
        # aggregates.
        export_queue_metrics(self.bus.order_queue)
        export_queue_metrics(self.bus.match_queue)
        e = self.config.engine
        mesh = None
        if e.mesh_devices:
            from ..parallel import make_mesh

            mesh = make_mesh(e.mesh_devices)
        self.engine = MatchEngine(
            config=e.book_config(),
            n_slots=e.n_slots,
            max_t=e.max_t,
            auto_grow=e.auto_grow,
            kernel=e.kernel,
            mesh=mesh,
        )
        if self.config.store.enabled:
            # A `redis:` config section puts the pre-pool markers in the
            # (Redis-compatible) store under the reference's exact schema —
            # split gateway/consumer processes then share marker state the
            # way the reference's three processes do (nodepool.go:14-28).
            # Like the amqp bus backend, an unreachable store must not stop
            # the engine from booting (the reference config.yaml names
            # local Redis/RabbitMQ that may not exist in this environment):
            # warn loudly and keep the in-process pool.
            from ..engine.prepool import RespPrePool
            from ..persist.resp import RespError, SupervisedRespClient

            st = self.config.store
            try:
                # Supervised client: a store restart mid-traffic reconnects
                # under backoff + breaker and replays the session
                # (utils.resilience) instead of killing the marker path.
                client = SupervisedRespClient(
                    st.host, st.port, password=st.password or None,
                    name="resp:store",
                )
                # Validate the session up front (a reachable-but-unusable
                # store, e.g. NOAUTH, must fall back at boot — not fail
                # on the first hot-path HSET).
                client.ping()
                self.engine.pre_pool = RespPrePool(client)
            except (OSError, RespError) as exc:
                log.warning(
                    "redis store %s:%d unusable (%s): pre-pool markers "
                    "stay IN-PROCESS — split gateway/consumer deployments "
                    "need the store up",
                    st.host, st.port, exc,
                )
        self.persist = persist  # gome_tpu.persist.Persister or None
        on_batch = persist.on_batch if persist is not None else None
        self.feed = MatchFeed(self.bus)
        self.consumer = OrderConsumer(
            self.engine,
            self.bus,
            batch_n=e.max_t * max(1, e.n_slots // 8),
            on_batch=on_batch,
            match_wire=self.config.bus.match_wire,
            pipeline_depth=e.pipeline_depth,
        )
        if persist is not None:
            # The consumer rides along so snapshots carry the matchfeed
            # seq at the cut and restore rebases it (exactly-once across
            # restarts); the durability gauges read from the Persister at
            # scrape time.
            persist.attach(self.engine, self.bus, consumer=self.consumer)
            persist.export_metrics()
        from ..engine.step import LOT_MAX32

        self.admission = None
        if self.config.admission.enabled:
            # End-to-end overload protection (round 12): the gateway
            # sheds retryable once order-queue consumer lag crosses the
            # configured ceiling — backpressure reaches the client
            # instead of piling into the bus.
            from .admission import AdmissionController

            a = self.config.admission
            self.admission = AdmissionController(
                self.bus.order_queue.depth,
                max_depth=a.max_depth,
                min_deadline_s=a.min_deadline_s,
                retry_after_s=a.retry_after_s,
                retry_after_max_s=a.retry_after_max_s,
                cache_s=a.cache_s,
            )
        self.gateway = OrderGateway(
            self.bus,
            accuracy=e.accuracy,
            mark=self.engine.mark,
            unmark=self.engine.unmark,
            mark_frame=self.engine.mark_frame,
            unmark_frame=self.engine.unmark_frame,
            match_feed=self.feed,
            max_volume=LOT_MAX32 if e.dtype == "int32" else None,
            admission=self.admission,
        )
        self._server = None
        self.ops = None
        if self.config.ops.enabled:
            from .ops import OpsServer

            if self.config.ops.trace:
                # Arm the order-lifecycle tracer (utils.trace): trace ids
                # at the gateway, per-stage histograms in /metrics, and
                # the flight recorder behind the ops /trace endpoint.
                from ..utils.trace import TRACER, FlightRecorder

                TRACER.install(
                    FlightRecorder(
                        keep_n=self.config.ops.trace_keep,
                        slow_threshold_s=self.config.ops.slow_ms / 1e3,
                    )
                )
            if self.config.ops.cost:
                # Arm the compile journal (gome_tpu.obs): first-seen
                # frame-dispatch combos land in gome_compile_seconds
                # metrics and the ops /cost endpoint.
                from ..obs.compile_journal import JOURNAL

                JOURNAL.install(keep_n=self.config.ops.cost_keep)
            if self.config.ops.timeline:
                # Arm the host-side timeline sampler (gome_tpu.obs.
                # timeline): RSS/rusage/live-buffer/compile/queue series
                # behind the ops /timeline endpoint and gome_timeline_*
                # gauges. The periodic thread runs only while the
                # service is start()ed; sample() also works on demand.
                from ..obs.timeline import TIMELINE, service_timeline

                TIMELINE.install(
                    interval_s=self.config.ops.timeline_interval_s,
                    keep_n=self.config.ops.timeline_keep,
                )
                service_timeline(self)
            if self.config.ops.profile:
                # Arm the measured-roofline profiler (gome_tpu.obs.
                # profiler): per-shard dispatch telemetry on the dense
                # mesh path, bounded jax.profiler captures behind the
                # ops /profile endpoint, gome_profile_* gauges.
                from ..obs.profiler import PROFILER

                PROFILER.install(keep_n=self.config.ops.profile_keep)
            if self.config.ops.hostprof:
                # Arm the host-CPU sampling profiler (gome_tpu.obs.
                # hostprof): gateway note_admit hook live, thread-mode
                # wall sampler behind the ops /hostprof endpoint and the
                # gome_hostprof_* gauges. The sampler thread runs only
                # while the service is start()ed.
                from ..obs.hostprof import HOSTPROF

                HOSTPROF.install(
                    hz=self.config.ops.hostprof_hz,
                    keep_n=self.config.ops.hostprof_keep,
                )
            if self.config.ops.placement:
                # Arm the placement observatory (gome_tpu.obs.placement):
                # gateway admit hooks feed the heavy-hitter symbol
                # sketch, the dense-dispatch hook keeps the occupancy
                # ledger, and the /placement endpoint serves the skew
                # attribution + the committed what-if verdict when one
                # is checked in next to the package.
                import numpy as np

                from ..engine.book import DeviceOp, GRID_I32_FIELDS
                from ..obs import placement as _placement

                itemsize = np.dtype(e.dtype).itemsize
                n_i32 = len(GRID_I32_FIELDS)
                n_val = len(DeviceOp._fields) - n_i32
                _placement.PLACEMENT.install(
                    topk=self.config.ops.placement_topk,
                    ewma_alpha=self.config.ops.placement_alpha,
                    row_bytes=(n_i32 * 4 + n_val * itemsize) * e.max_t,
                    partitions=self.config.ops.placement_partitions,
                    verdict=_placement.default_verdict(),
                )
            if self.config.fleet.enabled:
                # Arm the fleet aggregator (gome_tpu.obs.fleet): this
                # process polls the listed members' ops endpoints and
                # serves the merged view under its own /fleet. The
                # polling thread runs only while the service is
                # start()ed.
                from ..obs.fleet import FLEET

                FLEET.install(
                    self.config.fleet.member_map(),
                    interval_s=self.config.fleet.interval_s,
                    timeout_s=self.config.fleet.timeout_s,
                )
            self.ops = OpsServer(
                self, host=self.config.ops.host, port=self.config.ops.port
            )
        if os.environ.get("GOME_RACECHECK") == "1":
            # Arm the dynamic lockset race detector (analysis.racecheck)
            # over the service's cross-thread hotspots — the CI race
            # drill's hook. Local import behind the env check: a normal
            # boot neither imports nor pays for it.
            from ..analysis.racecheck import maybe_arm

            maybe_arm(self)

    def start(self):
        """Start gRPC server + consumer + feed threads (+ the ops HTTP
        endpoint when configured); returns self."""
        if self.persist is not None:
            self.persist.restore_latest()
        self._server = serve_gateway(self.gateway, self.config)
        self.consumer.start()
        self.feed.start()
        if self.ops is not None:
            self.ops.start()
            if self.config.ops.timeline:
                from ..obs.timeline import TIMELINE

                TIMELINE.start()
            if self.config.ops.hostprof:
                from ..obs.hostprof import HOSTPROF

                HOSTPROF.start()
            if self.config.fleet.enabled:
                from ..obs.fleet import FLEET

                FLEET.start()
        return self

    def stop(self):
        if self._server is not None:
            self._server.stop(grace=2).wait()
            self._server = None
        self.consumer.stop()
        self.feed.stop()
        if self.ops is not None:
            self.ops.stop()
            if self.config.ops.timeline:
                from ..obs.timeline import TIMELINE

                TIMELINE.stop()
            if self.config.ops.hostprof:
                from ..obs.hostprof import HOSTPROF

                HOSTPROF.stop()
            if self.config.fleet.enabled:
                from ..obs.fleet import FLEET

                FLEET.stop()

    def wait(self):
        if self._server is not None:
            self._server.wait_for_termination()

    # -- synchronous conveniences (tests, embedded use) ----------------------
    def pump(self) -> int:
        """Drain order queue then match queue once, synchronously (no
        threads). Returns orders processed."""
        n = self.consumer.drain()
        self.feed.drain()
        return n


def main(argv=None):
    """CLI entry: `python -m gome_tpu.service.app [config.yaml]` — the
    single-binary replacement for the reference's three `go run` processes
    (README.md:11-15)."""
    import sys

    from ..config import load_config

    argv = sys.argv[1:] if argv is None else argv
    config = load_config(argv[0] if argv else None)
    persist = None
    if config.persist.enabled:
        from ..persist import Persister

        persist = Persister(config.persist)
    svc = EngineService(config, persist=persist).start()
    log.info("engine service up (grpc %s:%d)", config.grpc.host, config.grpc.port)
    try:
        svc.wait()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()

"""GL5xx transfer-hygiene: host↔device traffic on annotated hot paths.

The engine's throughput story is "book state lives on device; the host
ships one batched grid down and one batched fetch up per frame". A single
`.item()` on a per-order value, an implicit `bool()` on a jax array in a
conditional, or a `device_put` inside the packing loop silently
reintroduces the per-order round trip the whole design deletes — JAX-LOB
(arXiv:2308.13289) and CoinTossX (arXiv:2102.10925) both report
end-to-end throughput gated by exactly these leaks, not kernel FLOPs.

These rules run OUTSIDE jit, on the host functions reachable from a
``# gomelint: hotpath`` seed (analysis.callgraph); inside traced code the
same idioms are GL1xx's domain. The rules:

  GL501  blocking scalar fetch: ``.item()``/``.tolist()``/``float()``/
         ``int()``/``complex()`` on a device value (one device→host sync
         each — per order, that is the Redis round trip again)
  GL502  host materialization: a ``np.*`` call on a device value
         (``np.asarray``/``np.array``/any ufunc syncs via ``__array__``)
  GL503  implicit bool sync: ``if``/``while``/``assert``/ternary/
         ``bool()``/iteration on a device value (truthiness forces a
         blocking fetch of the whole predicate)
  GL504  ``block_until_ready()`` inside a loop (serializes the device
         pipeline per iteration; drain once per batch instead)
  GL505  host→device transfer (``jax.device_put``/``jnp.asarray``/
         ``jnp.array`` of a host value) inside a loop (per-iteration
         upload; hoist or batch the transfer)

Device-taint model (documented limits — a linter, not an interpreter):

  * values returned by jit/pallas-wrapped functions are DEVICE; the bit
    propagates interprocedurally (a helper whose ``return`` is device
    makes its callers' results device), through arithmetic, subscripts,
    attribute access, tuple unpacking, and ``jax.tree.*`` maps;
  * ``jnp.*`` calls and ``jax.device_put`` produce DEVICE values;
  * ``jax.device_get(x)`` and ``np.asarray(x)`` produce HOST values (the
    latter still flags GL502 when x was device — it is the sanctioned
    fetch only via device_get, which batches and is loggable);
  * ``.shape``/``.dtype``/``len()`` and friends are metadata — they
    de-taint (reading an aval never syncs);
  * parameters, ``self`` attributes, and unresolved calls are UNKNOWN
    (untainted): the pass under-reports rather than spamming — the grep
    surface for what it can miss is the ``# gomelint: hotpath`` seeds.

GL504/GL505 are *lexically* loop-scoped within one function; a transfer
in a helper called from a loop is only caught if the helper itself loops.
"""

from __future__ import annotations

import ast

from . import callgraph
from .core import Finding, register_project_checker, register_rules
from .trace_safety import _STATIC_ATTRS, _dotted

register_rules({
    "GL501": "blocking scalar fetch (.item()/float()/int()) of a device "
             "value on a hot path",
    "GL502": "numpy materialization of a device value on a hot path",
    "GL503": "implicit bool() sync on a device value on a hot path",
    "GL504": "block_until_ready() inside a loop on a hot path",
    "GL505": "host->device transfer inside a loop on a hot path",
})

_SCALAR_CASTS = {"float", "int", "complex"}
_DETAINT_CALLS = {"len", "isinstance", "type", "id", "repr", "str", "hash",
                  "bool"}
_HOST_PRODUCERS = {"device_get"}  # leaf names under jax.*
_TRANSFER_LEAVES = {"device_put", "asarray", "array"}


class _FnFacts:
    __slots__ = ("returns_device",)

    def __init__(self):
        self.returns_device = False


class _Scan(ast.NodeVisitor):
    """One function body's device-taint scan. emit=False runs are the
    returns-device fixpoint; emit=True runs report findings (hot
    functions only)."""

    def __init__(self, checker: "_Checker", fn: callgraph.FuncNode,
                 emit: bool):
        self.c = checker
        self.fn = fn
        self.emit = emit
        self.taint: dict[str, bool] = {}
        self.loop_depth = 0
        self.returns_device = False
        self.findings: list[Finding] = []

    # -- expression taint --------------------------------------------------
    def t(self, node: ast.AST | None) -> bool:
        if node is None:
            return False
        method = getattr(self, f"_t_{type(node).__name__}", None)
        if method is not None:
            return method(node)
        out = False
        for child in ast.iter_child_nodes(node):
            out = self.t(child) or out
        return out

    def _t_Name(self, node):
        return self.taint.get(node.id, False)

    def _t_Constant(self, node):
        return False

    def _t_Lambda(self, node):
        return False

    def _t_Attribute(self, node):
        if node.attr in _STATIC_ATTRS:
            self.t(node.value)
            return False
        return self.t(node.value)

    def _t_Subscript(self, node):
        return self.t(node.value) or self.t(node.slice)

    def _t_IfExp(self, node):
        if self.t(node.test):
            self._report("GL503", node,
                         "ternary condition on a device value (blocking "
                         "truthiness fetch)")
        return self.t(node.body) or self.t(node.orelse)

    def _t_Compare(self, node):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False  # identity tests never materialize
        out = self.t(node.left)
        for cmp_ in node.comparators:
            out = self.t(cmp_) or out
        return out

    def _t_BoolOp(self, node):
        # `x and y` forces bool(x): same sync as an `if`.
        for v in node.values[:-1]:
            if self.t(v):
                self._report("GL503", v,
                             "and/or on a device value (forces bool())")
        return any(self.t(v) for v in node.values)

    def _t_Call(self, node):
        fname = _dotted(node.func) or ""
        leaf = fname.rsplit(".", 1)[-1]
        root = fname.split(".", 1)[0]
        arg_dev = any(self.t(a) for a in node.args) | any(
            self.t(k.value) for k in node.keywords
        )

        # receiver-method syncs
        if isinstance(node.func, ast.Attribute):
            recv = self.t(node.func.value)
            if node.func.attr in ("item", "tolist") and recv:
                self._report(
                    "GL501", node,
                    f".{node.func.attr}() is a blocking device->host "
                    "scalar fetch — batch it through one device_get",
                )
                return False
            if node.func.attr == "block_until_ready":
                if self.loop_depth and self.fn.hot and not self.fn.jitted:
                    self._report(
                        "GL504", node,
                        "block_until_ready() inside a loop serializes the "
                        "device pipeline per iteration — drain once per "
                        "batch/frame",
                    )
                return recv or arg_dev

        if fname in _SCALAR_CASTS:
            if arg_dev:
                self._report(
                    "GL501", node,
                    f"{fname}() on a device value is a blocking scalar "
                    "fetch — device_get the batch once instead",
                )
            return False
        if fname == "bool":
            if arg_dev:
                self._report("GL503", node,
                             "bool() on a device value is a blocking sync")
            return False
        if fname in _DETAINT_CALLS:
            return False

        if root in ("np", "numpy"):
            if arg_dev:
                self._report(
                    "GL502", node,
                    f"{fname}() materializes a device value on the host "
                    "(implicit __array__ sync) — fetch via jax.device_get "
                    "at the batch boundary",
                )
            return False

        if root in ("jnp", "jax"):
            if leaf in _HOST_PRODUCERS:
                return False  # device_get: the sanctioned batched fetch
            if leaf in _TRANSFER_LEAVES and (root == "jnp"
                                             or leaf == "device_put"):
                if self.loop_depth and not arg_dev and self.fn.hot \
                        and not self.fn.jitted:
                    self._report(
                        "GL505", node,
                        f"{fname}() inside a loop uploads host data to the "
                        "device per iteration — hoist or batch the "
                        "transfer",
                    )
                return True
            if root == "jnp" or fname.startswith("jax.numpy"):
                return True  # jnp.* produce device arrays
            # jax.tree.map and friends: taint follows the arguments
            return arg_dev

        # calls into project functions: device iff the target returns device
        out = False
        for target in self._resolve(node):
            if target.jitted or self.c.facts[target].returns_device:
                out = True
        # a method call on a device receiver stays device (`outs.sum()`,
        # `books._replace(...)`, `.at[...].set(...)`)
        if isinstance(node.func, ast.Attribute):
            out = out or self.t(node.func.value)
        return out

    def _resolve(self, node: ast.Call) -> list[callgraph.FuncNode]:
        func = node.func
        if isinstance(func, ast.Name):
            return self.c.graph.resolve_name(func.id, self.fn)
        if isinstance(func, ast.Attribute):
            return self.c.graph.resolve_method(func.attr, self.fn)
        return []

    # -- statements --------------------------------------------------------
    def _assign(self, target, taint: bool) -> None:
        if isinstance(target, ast.Name):
            self.taint[target.id] = taint
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, taint)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, taint)

    def visit_Assign(self, node):
        t = self.t(node.value)
        for target in node.targets:
            self._assign(target, t)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._assign(node.target, self.t(node.value))

    def visit_AugAssign(self, node):
        t = self.t(node.value)
        if isinstance(node.target, ast.Name):
            self.taint[node.target.id] = (
                self.taint.get(node.target.id, False) or t
            )

    def visit_If(self, node):
        if self.t(node.test):
            self._report("GL503", node.test,
                         "`if` on a device value blocks on the predicate "
                         "fetch — fetch the batch once, branch on numpy")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.t(node.test):
            self._report("GL503", node.test,
                         "`while` on a device value syncs per iteration")
        self.loop_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.loop_depth -= 1

    def visit_Assert(self, node):
        if self.t(node.test):
            self._report("GL503", node.test,
                         "`assert` on a device value is a blocking sync "
                         "(and python -O strips it)")
        self.generic_visit(node)

    def visit_For(self, node):
        it_dev = self.t(node.iter)
        if it_dev:
            self._report(
                "GL503", node.iter,
                "`for` over a device value fetches one element per "
                "iteration — device_get once and iterate the numpy copy",
            )
        self._assign(node.target, it_dev)
        self.loop_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.loop_depth -= 1

    def visit_Return(self, node):
        if node.value is not None and self.t(node.value):
            self.returns_device = True

    def visit_With(self, node):
        for item in node.items:
            self.t(item.context_expr)
            if item.optional_vars is not None:
                self._assign(item.optional_vars, False)
        for stmt in node.body:
            self.visit(stmt)

    def _comp(self, node):
        for gen in node.generators:
            self._assign(gen.target, self.t(gen.iter))
            for cond in gen.ifs:
                self.t(cond)

    def _t_ListComp(self, node):
        self._comp(node)
        return self.t(node.elt)

    def _t_SetComp(self, node):
        self._comp(node)
        return self.t(node.elt)

    def _t_GeneratorExp(self, node):
        self._comp(node)
        return self.t(node.elt)

    def _t_DictComp(self, node):
        self._comp(node)
        return self.t(node.key) or self.t(node.value)

    def visit_Expr(self, node):
        self.t(node.value)

    def visit_FunctionDef(self, node):
        pass  # nested scopes are their own FuncNodes

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.t(child)
            else:
                self.visit(child)

    def run(self) -> "_Scan":
        node = self.fn.node
        if isinstance(node, ast.Lambda):
            self.returns_device = self.t(node.body)
            return self
        for stmt in node.body:
            self.visit(stmt)
        return self

    def _report(self, rule: str, node: ast.AST, msg: str) -> None:
        if not (self.emit and self.fn.hot and not self.fn.jitted):
            return
        self.findings.append(Finding(
            rule, self.fn.module.path, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0),
            f"{msg} [hot path: {self.fn.qualname}]",
        ))


class _Checker:
    def __init__(self, project):
        self.graph = callgraph.build(project)
        self.facts: dict[callgraph.FuncNode, _FnFacts] = {
            fn: _FnFacts() for fn in self.graph.funcs
        }

    def run(self) -> list[Finding]:
        # fixpoint: which functions return device values
        for _ in range(8):
            changed = False
            for fn in self.graph.funcs:
                rd = _Scan(self, fn, emit=False).run().returns_device
                if rd and not self.facts[fn].returns_device:
                    self.facts[fn].returns_device = True
                    changed = True
            if not changed:
                break
        findings: list[Finding] = []
        for fn in self.graph.hot_functions():
            findings.extend(_Scan(self, fn, emit=True).run().findings)
        return findings


def check(project) -> list[Finding]:
    return _Checker(project).run()


register_project_checker("GL5", check)

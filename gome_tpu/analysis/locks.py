"""GL4xx lock-discipline: annotation-driven shared-state race detection.

The service layer is deliberately multi-threaded — gRPC handler threads
feed the FrameBatcher, AMQP reader threads append arrivals, the
supervised-reconnect path swaps connections, background deadline/consume
loops mutate cursors — and each class documents its sharing contract with
one lock (or two, like SupervisedAmqpQueue's `_state`/`_io` split). This
checker makes that contract *machine-checked*: declare an attribute's
guard once, and every other touch of it must hold the declared lock.

Declaration (a trailing comment on any `self.<attr> = ...` line, usually
in `__init__`):

    self._buf = []          # guarded by self._lock
    self._committed = 0     # guarded by self._state

Enforcement — any load/store of a declared attribute in the class must be
lexically inside one of:

  * a `with self.<declared lock>:` block (Condition objects count — they
    are locks with waiters);
  * a method whose name ends in `_locked` (the codebase's caller-holds-
    the-lock convention: `_flush_locked`, `_reconnect_locked`, ...), which
    asserts the DECLARED lock of each attribute it touches is held;
  * a method annotated `# holds: self._lock` on (or immediately above)
    its `def` line, naming the held lock(s) explicitly;
  * `__init__`/`__new__` (construction happens-before publication).

Nested functions and lambdas do NOT inherit the enclosing `with` block or
the `__init__` exemption: a callback defined under the lock runs later,
off the lock — exactly the escape that makes lexical checking of
closures unsound, so the closure body must take (or be annotated to
hold, or suppress with justification) the lock itself.

Rules:

  GL401  guarded attribute written outside its declared lock
  GL402  guarded attribute read outside its declared lock
  GL403  `# guarded by self.X` names a lock never assigned in the class

The opt-in *runtime* assertion mode (tests) is analysis.runtime: swap the
lock for an `OwnedLock` and `instrument()` the instance, and off-lock
writes raise at the exact line instead of losing updates silently.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, register_checker, register_rules

register_rules({
    "GL401": "guarded attribute written outside its declared lock",
    "GL402": "guarded attribute read outside its declared lock",
    "GL403": "guard annotation names a lock the class never assigns",
})

_GUARD_RE = re.compile(r"#\s*guarded\s+by\s+self\.([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*holds:?\s+(self\.[A-Za-z_]\w*"
                       r"(?:\s*,\s*self\.[A-Za-z_]\w*)*)")


def _holds_from_comment(comment: str) -> set[str]:
    m = _HOLDS_RE.search(comment)
    if not m:
        return set()
    return {part.strip()[len("self."):] for part in m.group(1).split(",")}


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.guards: dict[str, str] = {}  # attr -> lock attr
        self.decl_lines: dict[str, int] = {}
        self.assigned_attrs: set[str] = set()


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """Scan one method (or one nested scope within it) for guarded-attr
    touches, tracking the lexically-held lock set."""

    def __init__(self, checker, cls: _ClassInfo, held: set[str],
                 exempt: bool):
        self.c = checker
        self.cls = cls
        self.held = held
        self.exempt = exempt  # __init__/__new__ top-level scope

    def visit_With(self, node):
        added = set()
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None:
                added.add(attr)
        self.held |= added
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    def _nested(self, node, name: str):
        # a closure: runs later, off the lexical lock; fresh scope, and the
        # __init__ exemption does not follow it. An explicit `# holds:`
        # annotation on the def line still applies.
        held = _holds_from_comment(self.c.module.line_comment(node.lineno))
        if not held and node.lineno > 1:
            held = _holds_from_comment(
                self.c.module.line_comment(node.lineno - 1))
        if name.endswith("_locked"):
            held |= set(self.cls.guards.values())
        scan = _MethodScan(self.c, self.cls, held, exempt=False)
        for stmt in node.body if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)) else []:
            scan.visit(stmt)
        if isinstance(node, ast.Lambda):
            scan.visit(node.body)

    def visit_FunctionDef(self, node):
        self._nested(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._nested(node, node.name)

    def visit_Lambda(self, node):
        self._nested(node, "<lambda>")

    def visit_Attribute(self, node):
        attr = _self_attr(node)
        if attr is not None and attr in self.cls.guards and not self.exempt:
            lock = self.cls.guards[attr]
            if lock not in self.held:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    rule, verb = "GL401", "written"
                else:
                    rule, verb = "GL402", "read"
                self.c.report(
                    rule, node,
                    f"self.{attr} is declared `# guarded by self.{lock}` "
                    f"but {verb} without holding it "
                    f"[class {self.cls.node.name}]",
                )
        self.generic_visit(node)


class _Checker:
    def __init__(self, module):
        self.module = module
        self.findings: list[Finding] = []

    def report(self, rule, node, msg) -> None:
        self.findings.append(Finding(
            rule, self.module.path, node.lineno, node.col_offset, msg))

    def run(self) -> list[Finding]:
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)
        return self.findings

    def _collect(self, cls_node: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(cls_node)
        for node in ast.walk(cls_node):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    info.assigned_attrs.add(attr)
                    m = _GUARD_RE.search(
                        self.module.line_comment(node.lineno))
                    if m:
                        info.guards[attr] = m.group(1)
                        info.decl_lines[attr] = node.lineno
        return info

    def _check_class(self, cls_node: ast.ClassDef) -> None:
        info = self._collect(cls_node)
        if not info.guards:
            return
        for attr, lock in info.guards.items():
            if lock not in info.assigned_attrs:
                line = info.decl_lines[attr]
                self.findings.append(Finding(
                    "GL403", self.module.path, line, 0,
                    f"self.{attr} declared guarded by self.{lock}, but "
                    f"{cls_node.name} never assigns self.{lock}",
                ))
        for node in cls_node.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            held = _holds_from_comment(
                self.module.line_comment(node.lineno))
            if not held and node.lineno > 1:
                held |= _holds_from_comment(
                    self.module.line_comment(node.lineno - 1))
            if node.name.endswith("_locked"):
                held |= set(info.guards.values())
            exempt = node.name in ("__init__", "__new__")
            scan = _MethodScan(self, info, held, exempt)
            for stmt in node.body:
                scan.visit(stmt)


def check(module) -> list[Finding]:
    return _Checker(module).run()


register_checker("GL4", check)

"""GL8xx sharding & partition-consistency: specs, padding, placement.

The two numbers the ROADMAP says to beat — MULTICHIP_r06 (adding devices
LOSES throughput: every shard pads to the global max row block, skew
3.64) and FLEET_r01 (3.7x partition imbalance from naive hashing) — are
both sharding/partitioning diseases. GL1xx–GL7xx audit tracer leaks, the
int32 envelope, recompiles, locks, transfers, donation, and thread
escapes; nothing audited pjit/shard_map specs, spec flow between
entries, or partition-policy discipline. This family does:

  GL801  out-spec→in-spec mismatch between chained sharded entries: the
         result of one jit/shard_map entry flows into another whose
         declared spec for that position differs — XLA inserts a
         reshard on EVERY call (the pjit guidance: "make sure the
         partitioning matches").
  GL802  global-max padding: a per-shard row block / pad width derived
         from a reduction over ALL shards' live counts and multiplied
         by the mesh size — every shard pays the hottest shard's rows
         (the exact `_grid_geometry` disease behind MULTICHIP_r06).
  GL803  ad-hoc partition hashing: a symbol→partition/lane mapping via
         a private hash (`crc32(s) % n`, `hash(s) % n`, a local fnv)
         instead of the blessed placement helpers
         (`gome_tpu.fleet.router.partition_of` /
         `gome_tpu.parallel.router.ShardRouter`) — two hash policies in
         one fleet double-route symbols (FLEET_r01's imbalance was a
         private crc32 before PR 14).
  GL804  donation across a sharding boundary: a donated argument whose
         declared sharding matches no output sharding of the same entry
         — the donated buffer cannot be reused in place across a spec
         boundary, so XLA pays a reshard/copy AND frees the input
         (extends the GL6xx audit with spec awareness).
  GL805  host materialization between shard-resident frames: a device-
         resident value is fetched to host (`jax.device_get` / numpy
         coercion) and then re-dispatched to the mesh (`shard_batch` /
         `jax.device_put` / a sharded entry) — a device→host→device
         round trip; keep it resident and reshard on device.
  GL806  sharding manifest drift: the per-entry manifest extracted from
         the shared engine trace + the mesh module's declared specs
         differs from the committed `shard_manifest.json` — spec
         changes must be reviewed (``--update-manifest``), never
         silently absorbed.

Division of labor with the traced memo (one engine trace per run, shared
with GL2xx/GL6xx — envelope.traced_entries): the manifest extractor
derives each engine entry's in/out avals and donation from that memo and
each mesh entry's axes/specs/donation from `parallel/mesh.py`'s AST;
GL806 ratchets the result. GL801/GL804 are AST spec-flow over the same
declared specs (canonicalized with local-alias substitution, so
``spec = P(SYM_AXIS)`` and ``P('sym')`` compare equal); GL802/GL803 are
pure AST; GL805 rides the project call graph (jit detection) with a
lexical device/fetch taint per function.

Documented limits (a linter, not a partitioner): spec comparison is
textual after alias substitution — two spellings of one sharding that
alias through helpers this pass cannot see compare unequal (and vice
versa never: equal text is equal spec); GL805's taint is per-function
(a fetch returned from a helper and re-dispatched by its caller is
missed); GL801 tracks positional arguments bound to plain names.
"""

from __future__ import annotations

import ast
import copy
import json
import os
import re

from . import callgraph
from .core import (
    TOOL_VERSION,
    Finding,
    register_checker,
    register_project_checker,
    register_rules,
)
from .trace_safety import _const_int_tuple, _dotted, _is_jit_expr

register_rules({
    "GL801": "out-spec of a sharded entry feeds an entry declaring a "
             "different in-spec (reshard on every call)",
    "GL802": "per-shard row block derived from a reduction over ALL "
             "shards (global-max padding, the MULTICHIP skew tax)",
    "GL803": "ad-hoc symbol->partition hashing outside the blessed "
             "placement helpers (fleet.router.partition_of)",
    "GL804": "donated argument's sharding matches no output sharding "
             "(donation across a spec boundary is a copy, not a reuse)",
    "GL805": "host materialization of device-resident state re-"
             "dispatched to the mesh (device->host->device round trip)",
    "GL806": "sharding manifest drift — spec surface changed without "
             "--update-manifest",
})

#: Committed manifest location, relative to the repo root (mirrors
#: baseline.DEFAULT_BASELINE).
DEFAULT_MANIFEST = os.path.join("gome_tpu", "analysis",
                                "shard_manifest.json")

#: Modules allowed to implement hash->partition maps: the blessed
#: placement helpers everything else must route through.
_BLESSED_PARTITION_MODULES = ("fleet/router.py", "parallel/router.py")

_HASH_LEAVES = {"crc32", "adler32", "md5", "sha1", "sha256", "blake2b",
                "fnv1a", "hash"}

_PLACEMENT_LEAVES = {"shard_batch", "device_put"}


# --- canonical spec text (alias-substituted unparse) ----------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _own_nodes(scope: ast.AST, types) -> list[ast.AST]:
    """Nodes of the given types belonging to `scope` itself — recursing
    through control flow but NOT into nested defs/lambdas/classes, which
    are their own scopes."""
    out: list[ast.AST] = []

    def walk(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            if isinstance(child, types):
                out.append(child)
            walk(child)

    walk(scope)
    return out


def _direct_defs(scope: ast.AST) -> list[ast.AST]:
    """Defs whose nearest enclosing scope is `scope`."""
    out: list[ast.AST] = []

    def walk(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            elif not isinstance(child, ast.Lambda):
                walk(child)

    walk(scope)
    return out


def _simple_assigns(scope: ast.AST) -> dict[str, ast.expr]:
    """Single-Name-target assignments in `scope` (nested scopes
    excluded). Self-referential assigns are skipped — _canon's bounded
    fixpoint must terminate."""
    env: dict[str, ast.expr] = {}
    for node in _own_nodes(scope, ast.Assign):
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if not any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node.value)):
                env[name] = node.value
    return env


def _canon(node: ast.expr, env: dict[str, ast.expr]) -> str:
    """Canonical text of a spec expression with simple Name aliases
    substituted (bounded fixpoint): `P(SYM_AXIS)` with SYM_AXIS='sym'
    renders as "P('sym')"."""
    node = copy.deepcopy(node)
    for _ in range(5):
        changed = [False]

        class _Sub(ast.NodeTransformer):
            def visit_Name(self, n):  # noqa: N805 - ast API
                if isinstance(n.ctx, ast.Load) and n.id in env:
                    changed[0] = True
                    return copy.deepcopy(env[n.id])
                return n

        node = _Sub().visit(node)
        if not changed[0]:
            break
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed synthetic trees
        return ""


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _spec_tuple(node: ast.expr | None,
                env: dict[str, ast.expr]) -> tuple[str, ...] | None:
    """A specs keyword value -> per-position canonical strings (a non-
    tuple spec is a 1-tuple); None when the keyword is absent."""
    if node is None:
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_canon(el, env) for el in node.elts)
    return (_canon(node, env),)


def _sharded_call_specs(call: ast.Call, env: dict[str, ast.expr]):
    """(in_specs, out_specs, donate_argnums) of a spec-carrying
    construction — ``jax.jit(f, in_shardings=..., out_shardings=...)``
    or ``shard_map(f, in_specs=..., out_specs=...)`` — else None."""
    if _is_jit_expr(call.func):
        ins = _kw(call, "in_shardings")
        outs = _kw(call, "out_shardings")
        if ins is None and outs is None:
            return None
        return (_spec_tuple(ins, env), _spec_tuple(outs, env),
                _const_int_tuple(_kw(call, "donate_argnums")))
    leaf = (_dotted(call.func) or "").rsplit(".", 1)[-1]
    if leaf == "shard_map":
        ins = _kw(call, "in_specs")
        outs = _kw(call, "out_specs")
        if ins is None and outs is None:
            return None
        return (_spec_tuple(ins, env), _spec_tuple(outs, env), ())
    return None


def _scopes(root: ast.AST, env: dict[str, ast.expr]):
    """Yield (scope_node, accumulated_env) depth-first: module, then
    every def with its enclosing scopes' aliases visible."""
    own = dict(env)
    own.update(_simple_assigns(root))
    yield root, own
    for child in _direct_defs(root):
        yield from _scopes(child, own)


# --- the project-wide spec registry (GL801/GL805 consumers) ---------------

class _Entry:
    """One declared sharded entry: a name callers can invoke whose
    result/arguments carry declared specs."""

    __slots__ = ("name", "module", "module_level", "in_specs", "out_specs",
                 "donate", "line", "factory")

    def __init__(self, name, module, module_level, in_specs, out_specs,
                 donate, line, factory):
        self.name = name
        self.module = module
        self.module_level = module_level
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.donate = donate
        self.line = line
        #: True when `name` is a function RETURNING the entry (the
        #: `sharded_batch_step` idiom): calling it constructs a stepper
        #: (aliased by assignment), it does not itself dispatch.
        self.factory = factory


class _SpecRegistry:
    """name -> declared sharded entries, scoped like GL603's donation
    registry: module-level definitions are importable and match project-
    wide, local ones match only their own module. Two forms register:

      * ``name = jax.jit(f, in_shardings=..., ...)`` (and the shard_map
        analogue) — calls of ``name`` are the sharded dispatch;
      * ``def factory(...): return jax.jit(f, in_shardings=..., ...)``
        — a variable assigned from ``factory(...)`` carries the
        returned entry's specs (the `sharded_batch_step` idiom).
    """

    def __init__(self, project):
        self.entries: dict[str, list[_Entry]] = {}
        for module in project.modules:
            for scope, env in _scopes(module.tree, {}):
                is_module = isinstance(scope, ast.Module)
                for node in _own_nodes(scope, (ast.Assign, ast.Return)):
                    if isinstance(node, ast.Assign) \
                            and isinstance(node.value, ast.Call):
                        specs = _sharded_call_specs(node.value, env)
                        if specs is None:
                            continue
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self._add(t.id, module, is_module,
                                          specs, node.lineno, False)
                    elif isinstance(node, ast.Return) and not is_module \
                            and isinstance(node.value, ast.Call):
                        specs = _sharded_call_specs(node.value, env)
                        if specs is not None:
                            self._add(scope.name, module,
                                      scope in module.tree.body,
                                      specs, node.lineno, True)

    def _add(self, name, module, module_level, specs, line,
             factory) -> None:
        ins, outs, donate = specs
        self.entries.setdefault(name, []).append(
            _Entry(name, module, module_level, ins, outs, donate, line,
                   factory)
        )

    def lookup(self, name: str, module) -> _Entry | None:
        for e in self.entries.get(name, ()):
            if e.module is module or e.module_level:
                return e
        return None


# --- GL801: chained-entry spec flow (project checker) ---------------------

class _SpecFlowScan(ast.NodeVisitor):
    """One function body: track variables produced by sharded entries
    (with the out-spec of their position) and flag calls that feed them
    into an entry declaring a different in-spec."""

    def __init__(self, registry: _SpecRegistry, fn: callgraph.FuncNode):
        self.reg = registry
        self.fn = fn
        #: var -> _Entry it was built from (factory-call aliasing)
        self.aliases: dict[str, _Entry] = {}
        #: var -> (entry, out position) of the producing call
        self.produced: dict[str, tuple[_Entry, int]] = {}
        self.findings: list[Finding] = []

    def _dispatch_entry(self, func: ast.expr) -> _Entry | None:
        """The sharded entry a call of `func` DISPATCHES: an alias built
        from a factory, or a directly-registered jit name. Calling a
        factory by name only constructs — it never dispatches."""
        if isinstance(func, ast.Name):
            if func.id in self.aliases:
                return self.aliases[func.id]
            e = self.reg.lookup(func.id, self.fn.module)
            if e is not None and not e.factory:
                return e
        return None

    def visit_FunctionDef(self, node):
        if node is not self.fn.node:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self.fn.node:
            self.visit(node.body)

    def visit_Assign(self, node):
        self.generic_visit(node)
        value = node.value
        if not isinstance(value, ast.Call):
            self._kill(node.targets)
            return
        entry = self._dispatch_entry(value.func)
        if entry is not None and entry.out_specs:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._kill([t])
                    if len(entry.out_specs) == 1:
                        self.produced[t.id] = (entry, 0)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for i, el in enumerate(t.elts):
                        if isinstance(el, ast.Name) \
                                and i < len(entry.out_specs):
                            self._kill([el])
                            self.produced[el.id] = (entry, i)
            return
        # factory aliasing: stepper = sharded_dense_step(...)
        if isinstance(value.func, ast.Name):
            fac = self.reg.lookup(value.func.id, self.fn.module)
            if fac is not None and fac.factory:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.aliases[t.id] = fac
                return
        self._kill(node.targets)

    def _kill(self, targets) -> None:
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    self.produced.pop(n.id, None)
                    self.aliases.pop(n.id, None)

    def visit_Call(self, node):
        self.generic_visit(node)
        entry = self._dispatch_entry(node.func)
        if entry is None or not entry.in_specs:
            return
        called = node.func.id if isinstance(node.func, ast.Name) \
            else entry.name
        for i, arg in enumerate(node.args):
            if i >= len(entry.in_specs):
                break
            want = entry.in_specs[i]
            got = None
            src = None
            if isinstance(arg, ast.Name) and arg.id in self.produced:
                prod, pos = self.produced[arg.id]
                if prod.out_specs and pos < len(prod.out_specs):
                    got = prod.out_specs[pos]
                    src = prod.name
            elif isinstance(arg, ast.Call):
                prod = self._dispatch_entry(arg.func)
                if prod is not None and prod.out_specs \
                        and len(prod.out_specs) == 1:
                    got = prod.out_specs[0]
                    src = prod.name
            if got is not None and want and got != want:
                self.findings.append(Finding(
                    "GL801", self.fn.module.path, node.lineno,
                    node.col_offset,
                    f"argument #{i} of {called}() carries {src}'s "
                    f"out-spec {got} but the entry declares in-spec "
                    f"{want} — XLA resharding on every call; align the "
                    f"specs [in {self.fn.qualname}]",
                ))

    def run(self) -> list[Finding]:
        node = self.fn.node
        if isinstance(node, ast.Lambda):
            self.visit(node.body)
        else:
            for stmt in node.body:
                self.visit(stmt)
        return self.findings


# --- GL805: fetch-then-redispatch (project checker) -----------------------

class _RoundTripScan(ast.NodeVisitor):
    """One function body: lexical device/fetch taint. dev = values from
    device_put/shard_batch/jnp.*/jitted project calls; fetched = host
    materializations (device_get / np coercion) OF dev values; flag a
    fetched value handed to a mesh placement call or sharded entry."""

    def __init__(self, checker: "_ProjectChecker", fn: callgraph.FuncNode):
        self.c = checker
        self.fn = fn
        self.dev: set[str] = set()
        self.fetched: set[str] = set()
        self.dispatch: set[str] = set()  # aliases of factory entries
        self.findings: list[Finding] = []

    # -- expression classification ----------------------------------------
    def _mentions(self, node: ast.AST, names: set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in names
                   for n in ast.walk(node))

    def _is_device(self, node: ast.AST) -> bool:
        if self._mentions(node, self.dev):
            return True
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func) or ""
            leaf = d.rsplit(".", 1)[-1]
            root = d.split(".", 1)[0]
            if leaf in ("device_put", "shard_batch") or root == "jnp":
                return True
            if isinstance(n.func, ast.Name):
                if n.func.id in self.dispatch:
                    return True  # a sharded entry's result is resident
                for target in self.c.graph.resolve_name(n.func.id,
                                                        self.fn):
                    if target.jitted:
                        return True
        return False

    def _fetch_of_device(self, node: ast.AST) -> str | None:
        """'device_get'/'np.asarray' when `node` is a host
        materialization of a device value, else None."""
        if not isinstance(node, ast.Call) or not node.args:
            return None
        d = _dotted(node.func) or ""
        leaf = d.rsplit(".", 1)[-1]
        root = d.split(".", 1)[0]
        is_fetch = leaf == "device_get" or (
            root in ("np", "numpy") and leaf in ("asarray", "array"))
        if is_fetch and self._is_device(node.args[0]):
            return d
        return None

    def _is_fetched(self, node: ast.AST) -> bool:
        return self._mentions(node, self.fetched) \
            or self._fetch_of_device(node) is not None

    # -- statements --------------------------------------------------------
    def _assign(self, targets, value) -> None:
        fetched = self._is_fetched(value)
        dev = not fetched and self._is_device(value)
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    self.fetched.discard(n.id)
                    self.dev.discard(n.id)
                    if fetched:
                        self.fetched.add(n.id)
                    elif dev:
                        self.dev.add(n.id)

    def visit_Assign(self, node):
        self.generic_visit(node)
        value = node.value
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
            fac = self.c.registry.lookup(value.func.id, self.fn.module)
            if fac is not None and fac.factory:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.dispatch.add(t.id)
                return
        self._assign(node.targets, value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.generic_visit(node)
            self._assign([node.target], node.value)

    def visit_Call(self, node):
        self.generic_visit(node)
        d = _dotted(node.func) or ""
        leaf = d.rsplit(".", 1)[-1]
        is_placement = leaf in _PLACEMENT_LEAVES
        if not is_placement and isinstance(node.func, ast.Name):
            if node.func.id in self.dispatch:
                is_placement = True
            else:
                e = self.c.registry.lookup(node.func.id, self.fn.module)
                is_placement = e is not None and not e.factory
        if not is_placement:
            return
        for arg in node.args:
            how = self._fetch_of_device(arg)
            if how is None and self._mentions(arg, self.fetched):
                how = "a host copy"
            if how is not None:
                self.findings.append(Finding(
                    "GL805", self.fn.module.path, node.lineno,
                    node.col_offset,
                    f"{leaf}() re-dispatches a value materialized to "
                    f"host via {how} — device->host->device round trip; "
                    "keep it device-resident (reshard/device_put the "
                    "original, or shard the host source before upload) "
                    f"[in {self.fn.qualname}]",
                ))

    def visit_FunctionDef(self, node):
        if node is self.fn.node:
            for stmt in node.body:
                self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self.fn.node:
            self.visit(node.body)

    def run(self) -> list[Finding]:
        node = self.fn.node
        if isinstance(node, ast.Lambda):
            self.visit(node.body)
        else:
            for stmt in node.body:
                self.visit(stmt)
        return self.findings


class _ProjectChecker:
    def __init__(self, project):
        self.graph = callgraph.build(project)
        self.registry = _SpecRegistry(project)

    def run(self) -> list[Finding]:
        findings: list[Finding] = []
        for fn in self.graph.funcs:
            if fn.jitted:
                continue  # inside the graph, specs are XLA's problem
            if self.registry.entries:
                findings.extend(_SpecFlowScan(self.registry, fn).run())
            findings.extend(_RoundTripScan(self, fn).run())
        return findings


def check_spec_flow(project) -> list[Finding]:
    return _ProjectChecker(project).run()


register_project_checker("GL8", check_spec_flow)


# --- GL802/GL803/GL804: module checkers -----------------------------------

def _is_mesh_size(node: ast.expr) -> bool:
    """`<something>.mesh.size` / `mesh.size` — the shard count."""
    if isinstance(node, ast.Attribute) and node.attr == "size":
        d = _dotted(node.value) or ""
        return d.split(".")[-1].endswith("mesh")
    return False


class _GeometryScan(ast.NodeVisitor):
    """GL802 within one function: a variable reduced over ALL shards'
    counts (bincount -> .max()/np.max) that is later multiplied by the
    mesh size is the global-max padding idiom. One finding per derived
    variable, anchored at its derivation."""

    def __init__(self, module, fn_node):
        self.module = module
        self.fn = fn_node
        self.counts: set[str] = set()     # np.bincount products
        self.gmax: dict[str, int] = {}    # global-max vars -> def line
        self.mesh: set[str] = set()       # mesh-size vars
        self.findings: list[Finding] = []
        self.reported: set[str] = set()

    def _has_global_reduction(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            # counts.max() — argless full reduction of a shard histogram
            if isinstance(n.func, ast.Attribute) and n.func.attr == "max" \
                    and not n.args and not n.keywords:
                recv = _dotted(n.func.value) or ""
                if recv.split(".")[-1] in self.counts:
                    return True
            d = _dotted(n.func) or ""
            if d in ("np.max", "numpy.max") and n.args:
                first = _dotted(n.args[0]) or ""
                if first.split(".")[-1] in self.counts:
                    return True
        return False

    def visit_Assign(self, node):
        self.generic_visit(node)
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not names:
            return
        value = node.value
        d = _dotted(value.func) if isinstance(value, ast.Call) else None
        if d and d.rsplit(".", 1)[-1] == "bincount":
            self.counts.update(names)
            return
        if _is_mesh_size(value):
            self.mesh.update(names)
            return
        if self._has_global_reduction(value) \
                or any(isinstance(n, ast.Name) and n.id in self.gmax
                       for n in ast.walk(value)):
            for name in names:
                self.gmax.setdefault(name, node.lineno)

    def visit_BinOp(self, node):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Mult):
            return
        sides = (node.left, node.right)
        mesh_side = any(
            (isinstance(s, ast.Name) and s.id in self.mesh)
            or _is_mesh_size(s) for s in sides)
        gm = next((s.id for s in sides if isinstance(s, ast.Name)
                   and s.id in self.gmax), None)
        if mesh_side and gm is not None and gm not in self.reported:
            self.reported.add(gm)
            self.findings.append(Finding(
                "GL802", self.module.path, self.gmax[gm], 0,
                f"per-shard row block {gm!r} is a reduction over ALL "
                f"shards' live counts and is multiplied by the mesh size "
                f"(line {node.lineno}) — every shard pads to the hottest "
                "shard's rows (the MULTICHIP_r06 skew tax); derive the "
                "block per shard",
            ))

    def visit_FunctionDef(self, node):
        if node is self.fn:
            for stmt in node.body:
                self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def run(self) -> list[Finding]:
        for stmt in self.fn.body:
            self.visit(stmt)
        return self.findings


def _check_geometry(module) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_GeometryScan(module, node).run())
    return findings


def _check_partition_hash(module) -> list[Finding]:
    """GL803: `hashlike(sym) % n` outside the blessed router modules."""
    path = module.path.replace(os.sep, "/")
    if path.endswith(_BLESSED_PARTITION_MODULES):
        return []
    findings: list[Finding] = []
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Mod)):
            continue
        left = node.left
        if isinstance(left, ast.Call):
            leaf = (_dotted(left.func) or "").rsplit(".", 1)[-1]
            if leaf in _HASH_LEAVES:
                findings.append(Finding(
                    "GL803", module.path, node.lineno, node.col_offset,
                    f"ad-hoc {leaf}()-modulo partition map — route "
                    "symbol placement through gome_tpu.fleet.router."
                    "partition_of (one policy tree-wide; FLEET_r01's "
                    "3.7x imbalance came from a private hash)",
                ))
    return findings


def _check_donation_specs(module) -> list[Finding]:
    """GL804: a jit construction that both donates and pins shardings,
    where a donated argument's in-sharding matches no out-sharding."""
    findings: list[Finding] = []
    for scope, env in _scopes(module.tree, {}):
        for call in _own_nodes(scope, ast.Call):
            if not _is_jit_expr(call.func):
                continue
            specs = _sharded_call_specs(call, env)
            if specs is None:
                continue
            ins, outs, donate = specs
            if not donate or ins is None or outs is None:
                continue
            for i in donate:
                if i >= len(ins):
                    continue
                if ins[i] not in outs:
                    findings.append(Finding(
                        "GL804", module.path, call.lineno,
                        call.col_offset,
                        f"donated argument #{i} is declared {ins[i]} "
                        "but no output declares that sharding — across "
                        "a spec boundary XLA copies instead of reusing "
                        "the buffer (and still frees the input); align "
                        "the specs or drop the donation",
                    ))
    return findings


def _check_module(module) -> list[Finding]:
    out = _check_geometry(module)
    out.extend(_check_partition_hash(module))
    out.extend(_check_donation_specs(module))
    return out


register_checker("GL8", _check_module)


# --- the sharding manifest (extract / save / drift ratchet) ---------------

#: Stable traced contexts recorded in the manifest, with the shard-
#: locality classification of each entry's data. The best-effort pallas
#: interpret record is deliberately excluded: its presence varies by
#: environment, and a manifest must diff clean across machines.
_TRACED_MANIFEST_CONTEXTS = (
    ("engine/step.py:step_impl", "lane_local"),
    ("engine/batch.py:batch_step", "sym_sharded"),
    ("engine/batch.py:dense_batch_step", "sym_sharded"),
    ("engine/batch.py:lane_scan", "lane_local"),
    ("engine/frames.py:compact_accum", "replicated"),
    ("engine/frames.py:_scatter_grid_fn", "replicated"),
    ("sim/flow.py:gen_ops", "sym_sharded"),
)


def _aval_str(aval) -> str:
    shape = "x".join(str(int(d)) for d in aval.shape)
    return f"{shape or 'scalar'}:{aval.dtype}"


def _mesh_ast_entries(root: str) -> dict:
    """parallel/mesh.py's declared mesh entries: every function whose
    return is a jit with pinned shardings, plus the inner shard_map
    specs and axis names parsed from the canonicalized spec text."""
    rel = os.path.join("parallel", "mesh.py")
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    # Spec-carrying calls are canonicalized in the scope they appear in
    # (an inner shard_map's `spec = P(SYM_AXIS)` alias lives in the
    # nested stepper, not the factory), then attributed to the TOP-LEVEL
    # function — the name callers import.
    jit_specs: dict[str, tuple] = {}
    sm_specs: dict[str, tuple] = {}

    def walk(scope, env, top) -> None:
        env = dict(env)
        env.update(_simple_assigns(scope))
        if top is not None:
            for call in _own_nodes(scope, ast.Call):
                specs = _sharded_call_specs(call, env)
                if specs is None:
                    continue
                if _is_jit_expr(call.func):
                    jit_specs.setdefault(top, specs)
                else:
                    sm_specs.setdefault(top, specs[:2])
        for child in _direct_defs(scope):
            walk(child, env, top or child.name)

    walk(tree, {}, None)
    entries: dict[str, dict] = {}
    for name, (ins, outs, donate) in jit_specs.items():
        sm_ins, sm_outs = sm_specs.get(name, (None, None))
        spec_text = " ".join(
            s for block in (ins, outs, sm_ins, sm_outs) if block
            for s in block
        )
        axes = sorted(set(re.findall(r"'([A-Za-z_]\w*)'", spec_text)))
        entries[f"parallel/mesh.py:{name}"] = dict(
            kind="mesh_entry",
            mesh_axes=axes,
            in_shardings=list(ins or ()),
            out_shardings=list(outs or ()),
            shard_map_in_specs=list(sm_ins or ()),
            shard_map_out_specs=list(sm_outs or ()),
            donate_argnums=list(donate),
            classification="shard_local" if axes else "replicated",
        )
    return entries


def extract_manifest(dtype: str = "int32") -> dict:
    """The per-entry sharding manifest: engine entries from the SHARED
    trace memo (envelope.traced_entries — one trace per run, same memo
    GL2xx/GL6xx walk) + mesh entries from parallel/mesh.py's AST.
    Deterministic for a given tree: no line numbers, no timestamps."""
    from .donation import _ENGINE_WRAPPERS, wrapper_jit_spec
    from .envelope import traced_entries

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    records = {r["context"]: r for r in traced_entries(dtype)}
    tree_cache: dict[str, ast.AST] = {}
    entries: dict[str, dict] = {}
    for context, classification in _TRACED_MANIFEST_CONTEXTS:
        rec = records.get(context)
        if rec is None:
            continue
        closed = rec["closed"]
        donation: dict[str, list[int]] = {}
        for rel, wrapper, ctx, _arg_map, _params in _ENGINE_WRAPPERS:
            if ctx != context:
                continue
            if rel not in tree_cache:
                with open(os.path.join(root, rel), encoding="utf-8") as fh:
                    tree_cache[rel] = ast.parse(fh.read())
            spec = wrapper_jit_spec(tree_cache[rel], wrapper)
            if spec is not None:
                donation[wrapper] = sorted(spec[1])
        entries[context] = dict(
            kind="engine_entry",
            mesh_axes=[],
            in_avals=[_aval_str(v.aval) for v in closed.jaxpr.invars
                      if hasattr(getattr(v, "aval", None), "shape")],
            out_avals=[_aval_str(v.aval) for v in closed.jaxpr.outvars
                       if hasattr(getattr(v, "aval", None), "shape")],
            donation=donation,
            classification=classification,
        )
    entries.update(_mesh_ast_entries(root))
    return dict(
        version=1,
        tool=f"gomelint {TOOL_VERSION}",
        dtype=dtype,
        note="Per-entry sharding surface (mesh axes, specs, donation, "
             "shard-locality), extracted from the shared engine trace + "
             "parallel/mesh.py. CI fails on drift (GL806); regenerate "
             "with scripts/gomelint.py --jaxpr --update-manifest and "
             "review the diff like any spec change.",
        entries=entries,
    )


def save_manifest(path: str, manifest: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_manifest(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except OSError:
        return None


def check_sharding_manifest(dtype: str = "int32",
                            path: str | None = None) -> list[Finding]:
    """GL806 drift ratchet: the extracted manifest must equal the
    committed one entry-for-entry. Findings anchor on the manifest file
    so the fix-it action (--update-manifest + review) is unambiguous."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if path is None:
        path = os.path.join(root, DEFAULT_MANIFEST)
    rel = os.path.relpath(path, root) if os.path.isabs(path) else path
    committed = load_manifest(path)
    if committed is None:
        return [Finding(
            "GL806", rel, 1, 0,
            "no committed sharding manifest — run scripts/gomelint.py "
            "--jaxpr --update-manifest and commit the file",
        )]
    if committed.get("dtype") != dtype:
        return []  # the manifest pins the CI dtype; other audits skip
    current = extract_manifest(dtype)
    findings: list[Finding] = []
    cur, com = current["entries"], committed.get("entries", {})
    for ctx in sorted(set(cur) | set(com)):
        if ctx not in com:
            what = "entry is new (not in the committed manifest)"
        elif ctx not in cur:
            what = ("entry vanished from the trace/AST but is still in "
                    "the manifest")
        elif cur[ctx] != com[ctx]:
            changed = sorted(
                k for k in set(cur[ctx]) | set(com[ctx])
                if cur[ctx].get(k) != com[ctx].get(k)
            )
            what = f"{', '.join(changed)} changed vs the committed manifest"
        else:
            continue
        findings.append(Finding(
            "GL806", rel, 1, 0,
            f"{ctx}: {what} — review the spec change and regenerate "
            "with --update-manifest",
        ))
    return findings

"""Eraser-style dynamic lockset race detection (the dynamic prong of
gomerace; the static prong is analysis/threads.py's GL7xx family).

The static checker reasons about *declared* contracts; this module
observes *actual* executions. It implements the classic lockset
algorithm (Savage et al., "Eraser", SOSP '97) over watched attributes:

  * every :class:`TrackedLock` records, per thread, the set of locks
    that thread currently holds;
  * each watched variable carries a *candidate lockset* — the locks
    held at EVERY access so far once the variable is shared between
    threads;
  * a write to a shared variable whose candidate set has emptied means
    no single lock consistently protected it: a race report, with the
    current access site AND the previous one (both sides of the race),
    deduplicated by a stable fingerprint.

State machine per variable (the Eraser refinement that avoids
init-then-publish false positives): EXCLUSIVE while only the first
thread has touched it (no tracking cost, no reports — single-threaded
init is fine); SHARED once a second thread reads it (candidate refines,
nothing reported — read-only sharing after init is fine); SHARED_MOD
once any thread writes it post-sharing (candidate refines and an empty
set reports).

Armament mirrors the tracer/faults contract: the module-level
:data:`RACECHECK` singleton is disabled by default, ``note_access`` is
one attribute check and zero allocations when disabled, and nothing in
the production paths imports this module except the ``GOME_RACECHECK=1``
hook in service/app.py (a local import behind an env check).

``watch(obj, attrs)`` rebinds an instance to a dynamic subclass exposing
each watched attribute as a data property feeding the detector — both
reads and writes, unlike analysis.runtime.instrument (which asserts on
writes only). ``arm_service(svc)`` applies it to the cross-thread
hotspots of a running EngineService; ``scripts/race_drill.py`` drives
real gateway→bus→consumer→matchfeed traffic under it in CI.

Known limits (by design, documented not hidden): container mutation via
method call (``list.append``) is an attribute *read* to the detector;
the GIL serializes the detector's own bookkeeping, so this finds
*discipline* violations (no consistent lock), not torn reads.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import os
import threading
import traceback

from .runtime import OwnedLock

#: Frames from these files are machinery, not race sites — dropped from
#: captured stacks so reports lead with the code under test.
_OWN_FILES = ("racecheck.py", "interleave.py", "runtime.py")

_EXCLUSIVE, _SHARED, _SHARED_MOD = 0, 1, 2

_labels = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class RaceReport:
    """One deduplicated lockset violation (both access sites)."""

    label: str  # watch() label, usually the class name
    attr: str
    kind: str  # "write/write" or "read/write"
    threads: tuple[str, str]  # (previous, current) thread names
    site_prev: tuple[str, ...]  # short stack, innermost last
    site_here: tuple[str, ...]
    fingerprint: str  # stable id (class.attr + both top frames)

    def format(self) -> str:
        here = self.site_here[-1] if self.site_here else "?"
        prev = self.site_prev[-1] if self.site_prev else "?"
        return (
            f"RACE {self.fingerprint} {self.label}.{self.attr} "
            f"[{self.kind}] {self.threads[1]} at {here} vs "
            f"{self.threads[0]} at {prev}"
        )


class _VarState:
    __slots__ = (
        "state", "owner", "candidate", "prev_site", "prev_thread",
    )

    def __init__(self, owner: int):
        self.state = _EXCLUSIVE
        self.owner = owner
        self.candidate: frozenset | None = None
        self.prev_site: tuple[str, ...] = ()
        self.prev_thread = ""


class _HeldLocal(threading.local):
    """Per-thread held-lock stack (threading.local: each thread sees its
    own ``locks`` list, so no cross-thread sharing to guard)."""

    def __init__(self):
        self.locks: list = []


def _short_stack(limit: int = 12) -> tuple[str, ...]:
    out = []
    for fr in traceback.extract_stack(limit=limit):
        fname = os.path.basename(fr.filename)
        if fname in _OWN_FILES:
            continue
        out.append(f"{fname}:{fr.lineno} in {fr.name}")
    return tuple(out[-6:])


class RaceCheck:
    """The lockset detector. One process-wide instance (:data:`RACECHECK`
    below); tests may build private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        # The ONLY attribute the disabled hot path reads — see
        # note_access(); everything else is cold-path state.
        self.enabled = False  # guarded by self._lock
        self._vars: dict = {}  # guarded by self._lock ((label, attr) -> _VarState)
        self._reports: list[RaceReport] = []  # guarded by self._lock
        self._fingerprints: set[str] = set()  # guarded by self._lock
        self._suppressed: set[str] = set()  # guarded by self._lock
        self._held = _HeldLocal()

    # -- lifecycle -------------------------------------------------------
    def enable(self) -> "RaceCheck":
        """Arm the detector with fresh per-variable state (reports and
        suppressions persist across enable/disable cycles)."""
        with self._lock:
            self._vars = {}
            self.enabled = True
        return self

    def disable(self) -> None:
        with self._lock:
            self.enabled = False

    def reset(self) -> None:
        """Drop everything: variable state, reports, suppressions."""
        with self._lock:
            self._vars = {}
            self._reports = []
            self._fingerprints = set()
            self._suppressed = set()

    def suppress(self, key: str) -> None:
        """Silence reports whose ``label.attr`` or fingerprint equals
        ``key`` (the drill's allowlist for documented benign races; an
        entry here should cite WHY at the call site)."""
        with self._lock:
            self._suppressed.add(key)

    def reports(self, include_suppressed: bool = False) -> list[RaceReport]:
        with self._lock:
            reports = list(self._reports)
            suppressed = set(self._suppressed)
        if include_suppressed:
            return reports
        return [
            r for r in reports
            if r.fingerprint not in suppressed
            and f"{r.label}.{r.attr}" not in suppressed
        ]

    # -- lock tracking (TrackedLock calls these) -------------------------
    def _held_stack(self) -> list:
        return self._held.locks

    # -- the algorithm ---------------------------------------------------
    def note_access(self, label: str, attr: str, is_write: bool) -> None:
        """Feed one access. The disabled path is one attribute check and
        zero allocations (same contract as TRACER/JOURNAL/FAULTS —
        tests/test_race.py holds it to getallocatedblocks)."""
        # gomelint: disable=GL402 — benign stale read: a bool load is one
        # bytecode under the GIL (merely stale, never torn); enable()
        # happens-before the first armed access in every harness.
        if not self.enabled:  # gomelint: hotpath  # gomelint: disable=GL402
            return
        tid = threading.get_ident()
        held = frozenset(self._held.locks)
        with self._lock:
            key = (label, attr)
            var = self._vars.get(key)
            if var is None:
                self._vars[key] = _VarState(tid)
                return
            if var.state == _EXCLUSIVE:
                if tid == var.owner:
                    return
                # Second thread: the variable is now shared. Candidate
                # lockset starts as what THIS access holds.
                var.state = _SHARED_MOD if is_write else _SHARED
                var.candidate = held
            else:
                var.candidate &= held
                if is_write:
                    var.state = _SHARED_MOD
            site = _short_stack()
            thread_name = threading.current_thread().name
            if (
                var.state == _SHARED_MOD
                and not var.candidate
                and var.prev_site
            ):
                self._report_locked(
                    label, attr, is_write, var, site, thread_name
                )
            var.prev_site = site
            var.prev_thread = thread_name

    def _report_locked(self, label, attr, is_write, var, site, thread_name):
        kind = "write/write" if is_write else "read/write"
        top_here = site[-1] if site else "?"
        top_prev = var.prev_site[-1] if var.prev_site else "?"
        base = label.split("#", 1)[0]  # instance counter is not stable
        fingerprint = hashlib.sha1(
            f"{base}.{attr}|{top_prev}|{top_here}".encode()
        ).hexdigest()[:12]
        if fingerprint in self._fingerprints:
            return
        self._fingerprints.add(fingerprint)
        self._reports.append(RaceReport(
            label=base,
            attr=attr,
            kind=kind,
            threads=(var.prev_thread, thread_name),
            site_prev=var.prev_site,
            site_here=site,
            fingerprint=fingerprint,
        ))


#: Process-wide detector, disabled by default (tracer/faults contract).
RACECHECK = RaceCheck()


class TrackedLock(OwnedLock):
    """An OwnedLock that feeds the detector's per-thread held set. Drops
    into any ``with self._lock:`` site; when the detector is disabled it
    behaves exactly like its parent (no bookkeeping)."""

    def __init__(self, name: str = "lock"):
        super().__init__()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = super().acquire(blocking, timeout)
        if got and RACECHECK.enabled:
            RACECHECK._held_stack().append(self)
        return got

    def release(self) -> None:
        if RACECHECK.enabled:
            stack = RACECHECK._held_stack()
            if self in stack:
                stack.remove(self)
        super().release()


def watch(obj, attrs, lock_attrs=("_lock",), label: str | None = None):
    """Arm lockset tracking on ``obj`` for the named attributes.

    Each named lock attribute (that exists) is replaced by a
    :class:`TrackedLock` — same interface, so the object's own ``with
    self._lock:`` sites work unchanged but become visible to the
    detector. The instance is then rebound to a one-off subclass where
    every watched attribute is a data property: reads and writes flow
    through :meth:`RaceCheck.note_access` while values stay in the
    instance ``__dict__``. Returns ``obj`` (re-watching an instance
    rebuilds the subclass from the original class)."""
    if isinstance(lock_attrs, str):
        lock_attrs = (lock_attrs,)
    for la in lock_attrs:
        cur = getattr(obj, la, None)
        if cur is not None and not isinstance(cur, TrackedLock):
            object.__setattr__(
                obj, la, TrackedLock(name=f"{type(obj).__name__}.{la}")
            )
    cls = type(obj)
    base = getattr(cls, "_racecheck_base", cls)
    if label is None:
        label = f"{base.__name__}#{next(_labels)}"
    ns: dict = {"_racecheck_label": label, "_racecheck_base": base}
    for attr in attrs:
        ns[attr] = _tracked_property(attr)
    sub = type(f"{base.__name__}@racecheck", (base,), ns)
    object.__setattr__(obj, "__class__", sub)
    return obj


def _tracked_property(name: str) -> property:
    def fget(self):
        RACECHECK.note_access(type(self)._racecheck_label, name, False)
        try:
            return self.__dict__[name]
        except KeyError:
            raise AttributeError(name) from None

    def fset(self, value):
        RACECHECK.note_access(type(self)._racecheck_label, name, True)
        self.__dict__[name] = value

    return property(fget, fset)


# -- service integration ---------------------------------------------------


def arm_service(svc) -> list:
    """Instrument the cross-thread hotspots of an EngineService: the
    matchfeed counters + SeqTracker, the consumer's seq frontier, and
    (when the gateway batches) the batcher's degraded-mode state. The
    attribute lists mirror the ``# guarded by`` / ``# single-writer``
    contracts those modules declare — the drill checks the contracts
    hold under real traffic. Returns the watched objects."""
    watched = []
    feed = getattr(svc, "feed", None)
    if feed is not None:
        watch(
            feed, ("events_seen", "suppressed"),
            lock_attrs=("_lock", "_life"), label="MatchFeed",
        )
        watch(
            feed.seq, ("last_seq", "dupes", "gaps", "observed"),
            lock_attrs=(), label="SeqTracker",
        )
        watched += [feed, feed.seq]
    consumer = getattr(svc, "consumer", None)
    if consumer is not None:
        watch(
            consumer,
            ("match_seq", "_seq_committed", "_fail_count",
             "_last_step_failed"),
            lock_attrs=("_life",), label="OrderConsumer",
        )
        watched.append(consumer)
    gateway = getattr(svc, "gateway", None)
    batcher = getattr(gateway, "_batcher", None)
    if batcher is not None:
        watch(
            batcher,
            ("degraded_seconds_total", "_degraded_since", "_oldest",
             "_stop"),
            lock_attrs=("_lock",), label="FrameBatcher",
        )
        watched.append(batcher)
    persist = getattr(svc, "persist", None)
    if persist is not None:
        watch(
            persist,
            ("snapshots_taken", "last_snapshot_unix",
             "last_snapshot_bytes"),
            lock_attrs=(), label="Persister",
        )
        watched.append(persist)
    return watched


def maybe_arm(svc) -> bool:
    """The ``GOME_RACECHECK=1`` hook (service/app.py calls this behind
    its own env check, via a local import — zero cost, zero imports in
    a normal boot). Enables the process-wide detector and instruments
    the service; returns whether it armed."""
    if os.environ.get("GOME_RACECHECK") != "1":
        return False
    RACECHECK.enable()
    arm_service(svc)
    return True

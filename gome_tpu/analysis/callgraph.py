"""Whole-package call graph + hot-path reachability (the GL5xx/GL6xx base).

The paper's design deletes the per-order Redis round trip by keeping book
state device-resident; the residual hazard is *host-side* code on the
order path quietly reintroducing a per-order device round trip. Deciding
"is this line on the order path" is an interprocedural question, so this
module builds a conservative call graph over every module of one analysis
run and computes forward reachability from annotated seeds.

Annotation grammar (documented in ARCHITECTURE.md "Static analysis"):

    def run_once(self) -> int:  # gomelint: hotpath
        ...

    # gomelint: hotpath
    def _loop(self) -> None:
        ...

A ``# gomelint: hotpath`` comment on the ``def`` line, on any decorator
line, or on the line immediately above the first decorator/``def`` marks
the function as a hot-path SEED. Everything reachable from a seed is hot:

  * direct calls (``f(...)``, ``self.m(...)``, ``obj.m(...)``) — names
    resolve same-scope first, then same-module, then project-wide by bare
    name; method names resolve against every class in the project
    (conservative over-approximation: matching is by name, not type);
  * callback/closure edges — a bare REFERENCE to a known function
    (``Thread(target=self._loop)``, ``submit(fn)``, a handler stored in a
    dict) counts as a call edge, because the linter cannot prove it is
    never invoked;
  * nested defs/lambdas inherit an edge from their enclosing function
    (a closure defined on the hot path runs on the hot path unless shown
    otherwise).

Reachability STOPS at jit/pallas-traced functions (detected with the same
machinery trace_safety uses): inside a traced function, host-sync idioms
are GL1xx's domain — the compiled graph executes on device and the GL5xx
transfer rules would be wrong there. A jitted function reached from a hot
seed is recorded (``hot`` for bookkeeping) but its body and callees are
not hot-scanned.
"""

from __future__ import annotations

import ast
import re

from .trace_safety import (
    _dotted,
    _is_jit_expr,
    _is_partial,
    _is_trace_transform,
    _jit_spec,
)

_HOTPATH_RE = re.compile(r"#\s*gomelint:\s*hotpath\b")


class FuncNode:
    """One function/method/lambda in the project."""

    __slots__ = ("module", "node", "qualname", "name", "cls",
                 "jitted", "hot", "seed", "enclosing")

    def __init__(self, module, node, qualname: str, name: str,
                 cls: str | None, enclosing: "FuncNode | None"):
        self.module = module
        self.node = node
        self.qualname = qualname  # module-relative dotted scope
        self.name = name  # bare name ("<lambda:LINE>" for lambdas)
        self.cls = cls  # enclosing class name for methods
        self.enclosing = enclosing  # lexically enclosing FuncNode
        self.jitted = False
        self.hot = False
        self.seed = False

    @property
    def ref(self) -> str:
        return f"{self.module.path}::{self.qualname}"

    def __repr__(self):  # pragma: no cover - debug aid
        flags = "".join(
            f for f, on in (("J", self.jitted), ("H", self.hot),
                            ("S", self.seed)) if on
        )
        return f"<FuncNode {self.ref} {flags}>"


def _is_hotpath_annotated(module, node) -> bool:
    lines = [node.lineno]
    first = node.lineno
    for dec in getattr(node, "decorator_list", ()):
        lines.append(dec.lineno)
        first = min(first, dec.lineno)
    lines.append(first - 1)  # the line immediately above
    return any(_HOTPATH_RE.search(module.line_comment(ln)) for ln in lines)


class _Collector(ast.NodeVisitor):
    """Collect every function of one module with scope/class context, mark
    hotpath seeds, and detect jit/pallas-traced functions (decorators AND
    wrapper assignments like ``step = partial(jax.jit, ...)(step_impl)``)."""

    def __init__(self, graph: "CallGraph", module):
        self.g = graph
        self.module = module
        self._scope: list[str] = []
        self._cls: list[str] = []
        self._func: list[FuncNode] = []

    def _add(self, node, name: str) -> FuncNode:
        qual = ".".join(self._scope + [name])
        fn = FuncNode(
            self.module, node, qual, name,
            self._cls[-1] if self._cls else None,
            self._func[-1] if self._func else None,
        )
        self.g._add(fn)
        return fn

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()
        self._scope.pop()

    def _visit_func(self, node):
        fn = self._add(node, node.name)
        if _is_hotpath_annotated(self.module, node):
            fn.seed = True
        for dec in node.decorator_list:
            if _jit_spec(dec)[2] or _is_trace_transform(dec):
                fn.jitted = True
        self._scope.append(node.name)
        self._func.append(fn)
        cls = self._cls
        self._cls = []  # nested defs inside a method are plain functions
        self.generic_visit(node)
        self._cls = cls
        self._func.pop()
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node)

    def visit_Lambda(self, node):
        self._add(node, f"<lambda:{node.lineno}>")
        self.generic_visit(node)

    def visit_Call(self, node):
        # jax.jit(f) / partial(jax.jit, ...)(f) / jax.vmap(f) /
        # pl.pallas_call(kernel, ...): the callable argument is traced.
        func = node.func
        is_wrap = _is_jit_expr(func) or _is_trace_transform(func)
        if not is_wrap and isinstance(func, ast.Call):
            is_wrap = _jit_spec(func)[2]
        if not is_wrap:
            d = _dotted(func) or ""
            is_wrap = d == "pallas_call" or d.endswith(".pallas_call")
        if is_wrap:
            for arg in node.args[:1]:
                target = arg
                if isinstance(arg, ast.Call) and _is_partial(arg.func) \
                        and arg.args:
                    target = arg.args[0]
                if isinstance(target, ast.Name):
                    self.g._pending_wrapped.append((self.module, target.id))
                elif isinstance(target, ast.Lambda):
                    self.g._pending_lambda.append(target)
        self.generic_visit(node)


class _EdgeScan(ast.NodeVisitor):
    """Record call/reference edges out of ONE function body. Nested defs
    are separate nodes (an enclosing→nested closure edge is added by the
    builder); their bodies are not re-walked here."""

    def __init__(self, graph: "CallGraph", fn: FuncNode):
        self.g = graph
        self.fn = fn

    def visit_FunctionDef(self, node):
        if node is not self.fn.node:
            return  # nested scope: its own _EdgeScan walks it

        # arguments' defaults evaluate in the enclosing scope
        for d in node.args.defaults + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            self.visit(d)
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is not self.fn.node:
            return
        self.visit(node.body)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            for target in self.g.resolve_name(node.id, self.fn):
                self.g.add_edge(self.fn, target)

    def visit_Attribute(self, node):
        # self.m / obj.m — method reference by name (call or callback)
        for target in self.g.resolve_method(node.attr, self.fn):
            self.g.add_edge(self.fn, target)
        self.visit(node.value)


class CallGraph:
    """Project-wide function index + conservative call/reference edges."""

    def __init__(self, project):
        self.funcs: list[FuncNode] = []
        self.by_node: dict[ast.AST, FuncNode] = {}
        self.by_name: dict[str, list[FuncNode]] = {}
        self.methods: dict[str, list[FuncNode]] = {}
        self.edges: dict[FuncNode, set[FuncNode]] = {}
        #: jit/pallas wrapper targets seen during collection, resolved once
        #: every function of every module is indexed.
        self._pending_wrapped: list[tuple[object, str]] = []
        self._pending_lambda: list[ast.Lambda] = []
        for module in project.modules:
            _Collector(self, module).visit(module.tree)
        for module, name in self._pending_wrapped:
            for fn in self.by_name.get(name, ()):
                if fn.module is module:
                    fn.jitted = True
        for lam in self._pending_lambda:
            fn = self.by_node.get(lam)
            if fn is not None:
                fn.jitted = True
        for fn in self.funcs:
            if fn.enclosing is not None:
                self.add_edge(fn.enclosing, fn)  # closure edge
            _EdgeScan(self, fn).visit(fn.node)
        self._propagate()

    # -- construction ------------------------------------------------------
    def _add(self, fn: FuncNode) -> None:
        self.funcs.append(fn)
        self.by_node[fn.node] = fn
        self.by_name.setdefault(fn.name, []).append(fn)
        if fn.cls is not None:
            self.methods.setdefault(fn.name, []).append(fn)

    def add_edge(self, src: FuncNode, dst: FuncNode) -> None:
        self.edges.setdefault(src, set()).add(dst)

    # -- name resolution ---------------------------------------------------
    def resolve_name(self, name: str, ctx: FuncNode) -> list[FuncNode]:
        cands = self.by_name.get(name, ())
        if not cands:
            return []
        scope = ctx.qualname.rsplit(".", 1)[0]
        sibs = [c for c in cands
                if c.module is ctx.module
                and c.qualname.rsplit(".", 1)[0] == scope]
        if sibs:
            return sibs
        local = [c for c in cands if c.module is ctx.module]
        return local or list(cands)

    def resolve_method(self, name: str, ctx: FuncNode) -> list[FuncNode]:
        cands = self.methods.get(name, ())
        if cands:
            same_cls = [c for c in cands
                        if ctx.cls is not None and c.cls == ctx.cls
                        and c.module is ctx.module]
            return same_cls or list(cands)
        # not a method anywhere: a module-attribute call like
        # `frames.submit_frame(...)` — fall back to plain functions
        return [c for c in self.by_name.get(name, ()) if c.cls is None]

    # -- hot-path reachability ---------------------------------------------
    def _propagate(self) -> None:
        work = [fn for fn in self.funcs if fn.seed]
        for fn in work:
            fn.hot = True
        while work:
            fn = work.pop()
            if fn.jitted:
                continue  # device graph: GL1xx territory, not GL5xx
            for nxt in self.edges.get(fn, ()):
                if not nxt.hot:
                    nxt.hot = True
                    work.append(nxt)

    def hot_functions(self) -> list[FuncNode]:
        """Hot, host-side (non-jitted) functions — the GL5xx scan set."""
        return [fn for fn in self.funcs if fn.hot and not fn.jitted]


def build(project) -> CallGraph:
    """Build (or reuse) the project's call graph — several rule families
    consume it, and one project build per run is enough."""
    cached = getattr(project, "_callgraph", None)
    if cached is None:
        cached = project._callgraph = CallGraph(project)
    return cached

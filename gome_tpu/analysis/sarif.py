"""SARIF 2.1.0 output: findings as code-review annotations.

SARIF (Static Analysis Results Interchange Format, OASIS 2.1.0) is what
code hosts ingest to render linter findings as inline review comments.
``gomelint --format sarif`` / ``--sarif FILE`` emit one run with:

  * ``tool.driver.rules`` — the full rule catalogue (id + description),
    so viewers can show the rule help without a second lookup;
  * one ``result`` per finding with a ``physicalLocation`` (relative URI,
    1-based line/column per the spec) and ``partialFingerprints`` carrying
    the SAME content-addressed fingerprint the baseline uses
    (``gomelint/v1``) — host-side dedup and the CI ratchet agree on
    finding identity;
  * baselined findings are still emitted but marked with an ``external``
    suppression (reviewers see them greyed out, not hidden) and
    ``baselineState: "unchanged"``; new findings are ``level: error`` so
    the annotation severity mirrors the exit code.

:func:`validate_sarif` structurally validates a document against the
2.1.0 schema's required properties/enums (the subset gomelint emits —
the test suite runs every emitted document through it; no network schema
fetch in CI).
"""

from __future__ import annotations

from .baseline import FINGERPRINT_KEY
from .core import TOOL_VERSION, Finding, rule_catalogue

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(
    fingerprinted: list[tuple[Finding, str]],
    baselined: set[str] | None = None,
    root: str = "",
) -> dict:
    """Build one SARIF 2.1.0 document. `baselined` is the set of
    fingerprints present in the committed baseline; `root` is stripped
    from finding paths to keep artifact URIs repo-relative."""
    baselined = baselined or set()
    rules = [
        dict(
            id=rule,
            shortDescription=dict(text=desc),
            defaultConfiguration=dict(level="warning"),
        )
        for rule, desc in rule_catalogue().items()
    ]
    rule_index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for f, fp in fingerprinted:
        uri = f.path
        if root and uri.startswith(root):
            uri = uri[len(root):].lstrip("/\\")
        uri = uri.replace("\\", "/")
        known = fp in baselined
        result = dict(
            ruleId=f.rule,
            ruleIndex=rule_index.get(f.rule, -1),
            level="warning" if known else "error",
            message=dict(text=f.message),
            locations=[dict(
                physicalLocation=dict(
                    artifactLocation=dict(uri=uri),
                    region=dict(
                        startLine=max(f.line, 1),
                        startColumn=f.col + 1,
                    ),
                ),
            )],
            partialFingerprints={FINGERPRINT_KEY: fp},
            baselineState="unchanged" if known else "new",
        )
        if known:
            result["suppressions"] = [dict(
                kind="external",
                justification="baselined in gome_tpu/analysis/"
                              "baseline.json (ratchet: only new findings "
                              "fail CI)",
            )]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [dict(
            tool=dict(driver=dict(
                name="gomelint",
                version=TOOL_VERSION,
                informationUri="https://github.com/lxalano/gome",
                rules=rules,
            )),
            results=results,
        )],
    }


_LEVELS = {"none", "note", "warning", "error"}
_BASELINE_STATES = {"new", "unchanged", "updated", "absent"}
_SUPPRESSION_KINDS = {"inSource", "external"}


def validate_sarif(doc) -> list[str]:
    """Structural validation against SARIF 2.1.0's required properties
    and enums (the emitted subset). Returns a list of violations — empty
    means valid. Paths in messages use JSON-pointer-ish notation."""
    errs: list[str] = []

    def need(cond, where, what):
        if not cond:
            errs.append(f"{where}: {what}")

    need(isinstance(doc, dict), "$", "document must be an object")
    if not isinstance(doc, dict):
        return errs
    need(doc.get("version") == SARIF_VERSION, "$.version",
         f"must be the string {SARIF_VERSION!r}")
    runs = doc.get("runs")
    need(isinstance(runs, list) and runs, "$.runs",
         "must be a non-empty array")
    for i, run in enumerate(runs or []):
        w = f"$.runs[{i}]"
        need(isinstance(run, dict), w, "must be an object")
        if not isinstance(run, dict):
            continue
        driver = (run.get("tool") or {}).get("driver")
        need(isinstance(driver, dict), f"{w}.tool.driver",
             "required object")
        if isinstance(driver, dict):
            need(isinstance(driver.get("name"), str) and driver["name"],
                 f"{w}.tool.driver.name", "required non-empty string")
            seen_ids: set[str] = set()
            for j, rule in enumerate(driver.get("rules", [])):
                rw = f"{w}.tool.driver.rules[{j}]"
                need(isinstance(rule.get("id"), str) and rule["id"],
                     f"{rw}.id", "required non-empty string")
                need(rule.get("id") not in seen_ids, f"{rw}.id",
                     "rule ids must be unique within a driver")
                seen_ids.add(rule.get("id"))
        for j, res in enumerate(run.get("results", [])):
            rw = f"{w}.results[{j}]"
            msg = res.get("message")
            need(isinstance(msg, dict) and isinstance(msg.get("text"), str),
                 f"{rw}.message.text", "required string")
            if "ruleId" in res:
                need(isinstance(res["ruleId"], str), f"{rw}.ruleId",
                     "must be a string")
            if "level" in res:
                need(res["level"] in _LEVELS, f"{rw}.level",
                     f"must be one of {sorted(_LEVELS)}")
            if "baselineState" in res:
                need(res["baselineState"] in _BASELINE_STATES,
                     f"{rw}.baselineState",
                     f"must be one of {sorted(_BASELINE_STATES)}")
            if "partialFingerprints" in res:
                pf = res["partialFingerprints"]
                need(
                    isinstance(pf, dict) and all(
                        isinstance(k, str) and isinstance(v, str)
                        for k, v in pf.items()
                    ),
                    f"{rw}.partialFingerprints",
                    "must map strings to strings",
                )
            for k, loc in enumerate(res.get("locations", [])):
                lw = f"{rw}.locations[{k}].physicalLocation"
                phys = loc.get("physicalLocation")
                if phys is None:
                    continue
                art = phys.get("artifactLocation")
                if art is not None:
                    need(isinstance(art.get("uri"), str), f"{lw}"
                         ".artifactLocation.uri", "must be a string")
                region = phys.get("region")
                if region is not None:
                    for prop in ("startLine", "startColumn", "endLine",
                                 "endColumn"):
                        if prop in region:
                            need(
                                isinstance(region[prop], int)
                                and region[prop] >= 1,
                                f"{lw}.region.{prop}",
                                "must be an integer >= 1",
                            )
            for k, sup in enumerate(res.get("suppressions", [])):
                need(sup.get("kind") in _SUPPRESSION_KINDS,
                     f"{rw}.suppressions[{k}].kind",
                     f"must be one of {sorted(_SUPPRESSION_KINDS)}")
    return errs

"""gomelint — domain-specific static analysis for the matching engine.

The engine's correctness contracts are mostly *implicit* in dynamic
behavior: the int32 price/volume envelope only trips when a soak test
overflows it, a host-Python leak inside a jitted function only trips when
a new shape traces, a compile-cache bypass only shows up as a latency
cliff in production, and an unguarded shared attribute only loses an
update under the exact interleaving the test suite never schedules. This
package checks those contracts *statically*, before a soak test runs:

  GL1xx  trace-safety      — host-Python leaks in jit/pallas-reachable code
                             (analysis.trace_safety)
  GL2xx  int32-envelope    — abstract-eval (jaxpr) dtype-envelope audit of
                             the engine step/batch/frame/kernel graphs
                             (analysis.envelope)
  GL3xx  recompile-hazard  — jit wrappers that bypass the compile cache
                             (analysis.recompile)
  GL4xx  lock-discipline   — `# guarded by self._lock` annotations enforced
                             lexically (analysis.locks); the opt-in runtime
                             assertion mode lives in analysis.runtime

Run it via ``python scripts/gomelint.py gome_tpu`` (CI's analysis job) or
programmatically through :func:`run_paths`. Findings carry stable rule
ids and ``file:line`` anchors; suppress one line with a trailing
``# gomelint: disable=GL101`` comment, or a whole file with
``# gomelint: disable-file=GL101`` on any line (see analysis.core).
"""

from __future__ import annotations

from .core import (
    ALL_RULES,
    Finding,
    SourceModule,
    rule_catalogue,
    run_paths,
    run_source,
)

__all__ = [
    "ALL_RULES",
    "Finding",
    "SourceModule",
    "rule_catalogue",
    "run_paths",
    "run_source",
]

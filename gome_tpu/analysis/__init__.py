"""gomelint — domain-specific static analysis for the matching engine.

The engine's correctness contracts are mostly *implicit* in dynamic
behavior: the int32 price/volume envelope only trips when a soak test
overflows it, a host-Python leak inside a jitted function only trips when
a new shape traces, a compile-cache bypass only shows up as a latency
cliff in production, and an unguarded shared attribute only loses an
update under the exact interleaving the test suite never schedules. This
package checks those contracts *statically*, before a soak test runs:

  GL1xx  trace-safety      — host-Python leaks in jit/pallas-reachable code
                             (analysis.trace_safety)
  GL2xx  int32-envelope    — abstract-eval (jaxpr) dtype-envelope audit of
                             the engine step/batch/frame/kernel graphs
                             (analysis.envelope)
  GL3xx  recompile-hazard  — jit wrappers that bypass the compile cache
                             (analysis.recompile)
  GL4xx  lock-discipline   — `# guarded by self._lock` annotations enforced
                             lexically (analysis.locks); the opt-in runtime
                             assertion mode lives in analysis.runtime
  GL5xx  transfer-hygiene  — host<->device syncs on `# gomelint: hotpath`
                             reachable code OUTSIDE jit (analysis.transfers,
                             over the analysis.callgraph hot-path engine)
  GL6xx  buffer-donation   — jitted entries that double-buffer dead state
                             arguments, no-op donations, and use-after-
                             donation call sites (analysis.donation)
  GL7xx  thread-escape     — attributes reachable from more than one
                             thread (Thread-owning classes, module
                             singletons, transitive construction) mutated
                             without a `# guarded by` / `# single-writer`
                             contract (analysis.threads); the dynamic
                             companion — an Eraser-style lockset detector
                             + seeded interleaving driver — lives in
                             analysis.racecheck / analysis.interleave
  GL8xx  sharding           — partition-spec flow between sharded entries,
                             global-max padding, ad-hoc partition hashing,
                             cross-spec donation, host round trips, and
                             the committed shard_manifest.json drift
                             ratchet (analysis.sharding)
  GL9xx  compile-surface    — quantizer-lattice taint on jit shape sinks,
                             combo-key site agreement, precompile-replay
                             coverage, hot-path geometry resets, the
                             committed combo_universe.json bound, and the
                             runtime journal-escape cross-check
                             (analysis.surface)

Run it via ``python scripts/gomelint.py gome_tpu`` (CI's analysis job) or
programmatically through :func:`run_paths`. Findings carry stable rule
ids, ``file:line`` anchors, and content-addressed fingerprints
(analysis.baseline) that drive the CI ratchet — only findings NOT in the
committed ``analysis/baseline.json`` fail the gate — and the SARIF 2.1.0
output (analysis.sarif). Suppress one line with a trailing
``# gomelint: disable=GL101`` comment, or a whole file with
``# gomelint: disable-file=GL101`` on any line (see analysis.core).
"""

from __future__ import annotations

from .core import (
    ALL_RULES,
    TOOL_VERSION,
    Finding,
    Project,
    SourceModule,
    rule_catalogue,
    run_paths,
    run_source,
    run_sources,
)

__version__ = TOOL_VERSION

__all__ = [
    "ALL_RULES",
    "TOOL_VERSION",
    "Finding",
    "Project",
    "SourceModule",
    "rule_catalogue",
    "run_paths",
    "run_source",
    "run_sources",
]

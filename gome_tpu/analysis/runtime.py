"""Opt-in runtime lock-discipline assertions (the dynamic half of GL4xx).

The static checker (analysis.locks) proves *lexical* discipline; this
module catches what lexical analysis cannot — a method called on the
wrong thread, a callback invoked after the lock was released — by making
violations raise at the exact write instead of losing an update silently.
It is test-harness machinery: nothing in the production paths imports it.

Usage (tests/test_analysis.py shows the pattern):

    lock = OwnedLock()
    obj = Thing(lock=lock)
    instrument(obj, ("counter", "items"), lock_attr="_lock")
    obj.bump()          # fine: bump() takes the lock
    obj.counter = 7     # raises LockDisciplineError: write off-lock

`instrument` swaps the instance's lock for an :class:`OwnedLock` (when it
is not one already) and rebinds the instance to a dynamic subclass whose
``__setattr__`` asserts the lock is held by the current thread for the
watched attributes. Reads are not intercepted (a ``__getattribute__``
hook would tax every attribute access in the hot path the test drives;
GL402 covers reads statically).
"""

from __future__ import annotations

import threading


class LockDisciplineError(AssertionError):
    """A watched attribute was written without holding its declared lock."""


class OwnedLock:
    """A (non-reentrant) lock that knows its owner thread. Context-manager
    compatible with threading.Lock so it drops into any `with self._lock:`
    site; `held_by_me()` is the assertion primitive."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owner: int | None = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
        return got

    def release(self) -> None:
        self._owner = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self) -> bool:
        return self._lock.locked()


def instrument(obj, attrs, lock_attr: str = "_lock"):
    """Arm runtime write-assertions on `obj` for the named attributes.

    Replaces ``getattr(obj, lock_attr)`` with an OwnedLock when needed
    (same interface, so the object's own `with self._lock:` sites work
    unchanged) and rebinds ``obj.__class__`` to a one-off subclass whose
    ``__setattr__`` raises :class:`LockDisciplineError` on an off-lock
    write to a watched attribute. Returns the OwnedLock so the test can
    assert with it directly. Idempotent per instance."""
    lock = getattr(obj, lock_attr)
    if not isinstance(lock, OwnedLock):
        lock = OwnedLock()
        object.__setattr__(obj, lock_attr, lock)
    watched = frozenset(attrs)
    cls = type(obj)
    if getattr(cls, "_gomelint_instrumented", False):
        object.__setattr__(obj, "_gomelint_watched", watched)
        return lock

    def __setattr__(self, name, value, _base=cls):
        if name in getattr(self, "_gomelint_watched", ()):  # pragma: no branch
            guard = getattr(self, lock_attr, None)
            if isinstance(guard, OwnedLock) and not guard.held_by_me():
                raise LockDisciplineError(
                    f"write to {_base.__name__}.{name} without holding "
                    f"{lock_attr} (runtime lock-discipline assertion)"
                )
        super(sub, self).__setattr__(name, value)

    sub = type(
        f"{cls.__name__}@gomelint", (cls,),
        {"__setattr__": __setattr__, "_gomelint_instrumented": True},
    )
    object.__setattr__(obj, "__class__", sub)
    object.__setattr__(obj, "_gomelint_watched", watched)
    return lock

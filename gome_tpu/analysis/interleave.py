"""Deterministic seeded interleaving driver (race regression harness).

A race report is only actionable if the schedule that exposed it can be
replayed. This module runs N worker callables under a *cooperative*
scheduler: exactly one worker executes at a time, and every context
switch happens at an explicit yield point — a ``step()`` call made by
the worker itself or by an instrumented primitive (:class:`SteppingLock`,
:class:`SteppingEvent`) dropped into the code under test. The next
worker is drawn from a seeded RNG, so

  * the full schedule is captured as a trace (list of worker indices),
  * the same seed replays the same schedule, bit for bit — a seed that
    exposes a race goes straight into a regression test,
  * sweeping seeds explores distinct interleavings deterministically.

This is the regression-side companion of analysis.racecheck: the lockset
detector *finds* a race under free-running threads; the interleaver
*pins* the offending schedule so the fix's test can prove the window is
closed on the exact interleaving that used to lose.

Blocking under a cooperative scheduler
--------------------------------------
A descheduled worker holds whatever real locks it holds. If the
scheduled worker then blocks on one of them, nobody ever yields again —
the classic cooperative-scheduler deadlock. The rule: any primitive a
worker can block on inside the explored region must be *stepping*:

  * :class:`SteppingLock` converts a blocking acquire into a
    try-acquire/yield/retry poll, so contention becomes schedule points
    instead of an invisible block;
  * :class:`SteppingEvent` yields around the mutating calls (``set`` /
    ``clear``), making a check-then-act window that spans one of them
    explorable.

Threads spawned *by* the code under test (e.g. a service loop) are not
scheduled: ``step()`` from an unregistered thread is a no-op, so the
spawned thread free-runs while the workers stay deterministic. A worker
that stays blocked anyway trips the watchdog and the run fails with
:class:`InterleaveDeadlock` naming the stuck worker.
"""

from __future__ import annotations

import random
import threading
import time


class InterleaveDeadlock(RuntimeError):
    """The scheduled worker made no progress within the watchdog window
    (it is almost certainly blocked on a non-stepping primitive held by
    a descheduled worker)."""


class Interleaver:
    """One seeded schedule over N workers. Single-use: build, ``run``,
    inspect ``trace`` / ``results`` / ``errors``."""

    def __init__(self, seed: int = 0, timeout_s: float = 10.0):
        self.seed = seed
        self.timeout_s = timeout_s
        self._rng = random.Random(seed)
        self._cv = threading.Condition()
        self._ident = threading.local()  # .idx on registered workers
        self.trace: list[int] = []  # guarded by self._cv (schedule order)
        self._alive: list[int] = []  # guarded by self._cv
        self._turn: int | None = None  # guarded by self._cv
        self.results: list[object] = []  # guarded by self._cv (per worker)
        self.errors: list[BaseException | None] = []  # guarded by self._cv

    # -- yield point (the public hook) ----------------------------------
    def step(self) -> None:
        """Yield to the scheduler: pick the next worker (possibly this
        one) and block until rescheduled. No-op from threads the driver
        did not spawn, so instrumented primitives are safe to leave in
        place while service loops run."""
        idx = getattr(self._ident, "idx", None)
        if idx is None:
            return
        with self._cv:
            self._pick_locked()
            self._wait_turn_locked(idx)

    # -- internals ------------------------------------------------------
    def _pick_locked(self) -> None:
        if self._alive:
            self._turn = self._rng.choice(self._alive)
            self.trace.append(self._turn)
            self._cv.notify_all()

    def _wait_turn_locked(self, idx: int) -> None:
        deadline = time.monotonic() + self.timeout_s
        while self._turn != idx:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self._cv.wait(remaining):
                raise InterleaveDeadlock(
                    f"worker {idx} starved waiting for its turn (turn is "
                    f"{self._turn}; a descheduled worker likely holds a "
                    f"non-stepping lock)"
                )

    def _worker(self, idx: int, fn) -> None:
        self._ident.idx = idx
        try:
            with self._cv:
                self._wait_turn_locked(idx)
            result = fn(self.step)
            with self._cv:
                self.results[idx] = result
        except BaseException as e:  # workers report, the driver decides
            with self._cv:
                self.errors[idx] = e
        finally:
            with self._cv:
                self._alive.remove(idx)
                self._turn = None
                self._pick_locked()

    # -- driver ---------------------------------------------------------
    def run(self, *fns) -> list[int]:
        """Run the workers to completion under one seeded schedule.

        Each ``fn`` is called as ``fn(step)`` — workers thread the yield
        callable into whatever they drive. Worker exceptions are
        *collected*, not raised (a regression test often EXPECTS one
        loser to raise); read ``errors[i]`` / ``results[i]``. Returns
        the schedule trace."""
        if not fns:
            return []
        with self._cv:
            self._alive = list(range(len(fns)))
            self.results = [None] * len(fns)
            self.errors = [None] * len(fns)
            self._pick_locked()
        threads = [
            threading.Thread(
                target=self._worker,
                args=(i, fn),
                name=f"interleave-{self.seed}-{i}",
                daemon=True,
            )
            for i, fn in enumerate(fns)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s + 5.0)
            if t.is_alive():
                raise InterleaveDeadlock(
                    f"{t.name} never finished (schedule wedged)"
                )
        with self._cv:  # join() is the happens-before; the lock is form
            return list(self.trace)


class SteppingLock:
    """``threading.Lock`` drop-in whose blocking acquire polls: try, and
    on contention yield to the scheduler and retry. A worker blocked on
    a lock held by a descheduled worker thereby keeps yielding until the
    holder is scheduled and releases — contention becomes schedule
    points instead of a cooperative deadlock."""

    def __init__(self, step):
        self._lock = threading.Lock()
        self._step = step

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not blocking:
            return self._lock.acquire(False)
        while not self._lock.acquire(False):
            self._step()
        return True

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class SteppingEvent:
    """``threading.Event`` wrapper that yields to the scheduler before
    the mutating calls. Dropping one into an object under test turns a
    ``clear()`` (or ``set()``) inside a suspected race window into an
    explicit schedule point — the exact spot a seeded schedule can
    deschedule one worker mid-window."""

    def __init__(self, step):
        self._event = threading.Event()
        self._step = step

    def set(self) -> None:
        self._step()
        self._event.set()

    def clear(self) -> None:
        self._step()
        self._event.clear()

    def is_set(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

"""Findings baseline: content-addressed fingerprints + the CI ratchet.

Turning new rule families on over a living tree needs a migration story:
the tree may carry findings that are understood and deliberately deferred
(or permanently justified at a coarser granularity than a line
suppression). The baseline file records their FINGERPRINTS; the CLI then
fails only on findings *not* in the baseline — new debt is blocked, old
debt can only shrink (``--update-baseline`` refuses to grow silently: it
rewrites the file to exactly the current findings, and the diff is
reviewed like any other code change).

Fingerprints are content-addressed so routine refactors do not churn the
baseline:

  * the file PATH is not hashed — moving a module keeps its findings
    baselined;
  * the LINE NUMBER is not hashed — inserting code above a finding keeps
    it baselined;
  * what IS hashed: the rule id, the finding message (which carries the
    function qualname / jaxpr context — logical anchors that survive
    moves), the stripped TEXT of the flagged source line, and an
    occurrence index that disambiguates identical (rule, message, text)
    triples in their sorted order.

Changing the flagged line's code — the thing a reviewer must re-judge —
changes the fingerprint, which is exactly the invalidation we want. The
SARIF output carries the same fingerprint as ``partialFingerprints``
(``gomelint/v1``) so code-review annotation dedup agrees with CI.
"""

from __future__ import annotations

import hashlib
import json
import os

from .core import TOOL_VERSION, Finding

FINGERPRINT_KEY = "gomelint/v1"

#: Default baseline location, relative to the repo root (the CLI resolves
#: it from its own location so CI and local runs agree).
DEFAULT_BASELINE = os.path.join("gome_tpu", "analysis", "baseline.json")


def _source_line(finding: Finding, cache: dict) -> str:
    """The stripped text of the flagged physical line; '' when the path
    is not a readable file (jaxpr pseudo-paths, <memory> fixtures)."""
    path = finding.path
    if path not in cache:
        try:
            with open(path, encoding="utf-8") as fh:
                cache[path] = fh.read().splitlines()
        except OSError:
            cache[path] = None
    lines = cache[path]
    if not lines or not 1 <= finding.line <= len(lines):
        return ""
    return lines[finding.line - 1].strip()


def fingerprint_findings(
    findings: list[Finding], root: str = "",
) -> list[tuple[Finding, str]]:
    """[(finding, fingerprint)] in the findings' given order. `root`
    resolves relative finding paths when reading source lines."""
    cache: dict = {}
    keyed: list[tuple[tuple, Finding]] = []
    for f in findings:
        probe = f if os.path.isabs(f.path) or not root else dataclass_with(
            f, path=os.path.join(root, f.path)
        )
        text = _source_line(probe, cache)
        keyed.append(((f.rule, f.message, text), f))
    counts: dict[tuple, int] = {}
    by_id: dict[int, str] = {}
    # occurrence index assigned in (line, col) order and scoped PER FILE:
    # within one file duplicates stay stably numbered as lines drift (and
    # reordering identical-text duplicates only swaps interchangeable
    # indices — the fingerprint multiset is invariant), while editing,
    # moving, or renaming one module can never renumber ANOTHER module's
    # duplicates. The path is still not hashed, so a moved file keeps its
    # own fingerprints; identical keys in different files intentionally
    # share a fingerprint — either instance matches the baseline entry.
    for key, f in sorted(keyed, key=lambda kf: (kf[1].path, kf[1].line,
                                                kf[1].col, kf[1].rule)):
        scope = (f.path, key)
        n = counts.get(scope, 0)
        counts[scope] = n + 1
        blob = "|".join((key[0], key[1], key[2], str(n)))
        by_id[id(f)] = hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]
    return [(f, by_id[id(f)]) for f in findings]


def dataclass_with(f: Finding, **kw) -> Finding:
    import dataclasses

    return dataclasses.replace(f, **kw)


def load_baseline(path: str) -> dict:
    """{} when missing — an absent baseline means 'everything is new'."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return {}
    return doc.get("fingerprints", {})


def save_baseline(path: str, fingerprinted: list[tuple[Finding, str]]) -> None:
    """Rewrite the baseline to exactly the given findings. The per-entry
    metadata (rule/path/line/message) is for the human reading the diff;
    matching uses only the fingerprint key."""
    fps: dict[str, dict] = {}
    for f, fp in sorted(fingerprinted,
                        key=lambda ff: (ff[0].path, ff[0].line, ff[0].rule)):
        fps[fp] = dict(rule=f.rule, path=f.path, line=f.line,
                       message=f.message)
    doc = dict(
        version=1,
        tool=f"gomelint {TOOL_VERSION}",
        note="CI fails only on findings NOT in this file (ratchet). "
             "Regenerate with scripts/gomelint.py --update-baseline; "
             "review the diff — shrinking is progress, growing is debt.",
        fingerprints=fps,
    )
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")


def partition(
    fingerprinted: list[tuple[Finding, str]], baseline: dict,
) -> tuple[list[tuple[Finding, str]], list[tuple[Finding, str]]]:
    """(new, baselined) split against a loaded baseline."""
    new: list[tuple[Finding, str]] = []
    known: list[tuple[Finding, str]] = []
    for f, fp in fingerprinted:
        (known if fp in baseline else new).append((f, fp))
    return new, known

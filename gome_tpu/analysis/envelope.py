"""GL2xx int32-envelope: abstract-eval (jaxpr) dtype audit of the engine.

The matching core's exactness argument (SURVEY §2.2, step.py SAT32_MAX) is
an *integer* argument: every device value is a scaled tick/lot int, depth
prefix sums saturate below 2^31, and nothing ever passes through floating
point. jax, meanwhile, loves to promote — a bare Python float literal, an
accidental `jnp.mean`, or an x64-mode Python int can silently widen an
int32 graph to int64 (2x HBM traffic on every book array — the dtype knob
exists precisely to halve it) or drift it through f32/f64 (silently
*inexact* lots). Dynamic tests only see the dtypes of the outputs they
assert on; this pass abstract-evals the actual jaxprs and audits EVERY
intermediate value:

  GL201  float64 anywhere in an engine graph (never legitimate)
  GL202  any float dtype in the integer matching envelope
  GL203  an integer wider than the declared book dtype (e.g. int64
         intermediates in an int32-mode engine)

Driven by the CLI (`gomelint --jaxpr`) and tests via
:func:`check_engine_envelope`, which traces the real entry points — the
single-op step, the scan x vmap batch step, the dense (gather/scatter)
step, the frame-compaction accumulator, the grid scatter-builder, and the
Pallas kernel in interpret mode — with small int32 geometry. The walk
recurses into nested jaxprs (pjit/scan/cond/pallas_call params), so a
promotion buried four combinators deep still surfaces, attributed to the
`gome_tpu` source line that created the offending equation.
"""

from __future__ import annotations

from .core import Finding, register_rules

register_rules({
    "GL201": "float64 value in an engine jaxpr (x64 creep)",
    "GL202": "float value inside the integer matching envelope",
    "GL203": "integer wider than the declared book dtype in the jaxpr",
})

#: dtype names always allowed in engine graphs regardless of declared
#: width: predicates and sub-word index/code types.
_ALWAYS_OK = {"bool", "int8", "uint8", "int16", "uint16"}

_INT_WIDTH = {"int8": 8, "uint8": 8, "int16": 16, "uint16": 16,
              "int32": 32, "uint32": 32, "int64": 64, "uint64": 64}


def _src_line(eqn) -> tuple[str, int] | None:
    """Best-effort `file:line` for one jaxpr equation, preferring frames
    inside this repo (the traceback also walks jax internals)."""
    try:
        frames = list(eqn.source_info.traceback.frames)
    except Exception:
        return None
    best = None
    for fr in frames:
        fname = getattr(fr, "file_name", "")
        if "gome_tpu" in fname:
            best = (fname, int(getattr(fr, "start_line", 0) or
                               getattr(fr, "line_num", 0)))
            break
        if best is None and "site-packages" not in fname \
                and "jax/_src" not in fname:
            best = (fname, int(getattr(fr, "start_line", 0) or
                               getattr(fr, "line_num", 0)))
    return best


def _iter_jaxprs(params: dict):
    """Yield nested (closed) jaxprs hiding in an eqn's params — pjit's
    `jaxpr`, scan/while's `jaxpr`/`cond_jaxpr`/`body_jaxpr`, cond's
    `branches`, pallas_call's kernel jaxpr."""
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr  # ClosedJaxpr
            elif hasattr(item, "eqns"):  # raw Jaxpr
                yield item


def check_jaxpr(closed, declared_dtype: str, context: str,
                allow_floats: bool = False) -> list[Finding]:
    """Audit one (closed) jaxpr against the declared integer envelope.
    `declared_dtype` is the book dtype name ("int32"/"int64")."""
    findings: list[Finding] = []
    width = _INT_WIDTH[declared_dtype]
    seen: set[tuple] = set()

    def audit_aval(aval, eqn, where: str) -> None:
        dtype = getattr(aval, "dtype", None)
        if dtype is None:
            return
        name = dtype.name
        loc = _src_line(eqn) if eqn is not None else None
        path, line = loc if loc else (f"<jaxpr:{context}>", 0)
        key = (name, path, line, where)
        if key in seen:
            return
        prim = getattr(eqn, "primitive", None)
        prim = f" [{prim}]" if prim is not None else ""
        if name == "float64":
            if (allow_floats and not aval.shape
                    and getattr(aval, "weak_type", False)):
                # A float-tolerant entry (the sim flow generator): a
                # WEAK-typed f64 scalar is a python literal inside jax
                # library code (jax.random defaults under x64) — it
                # demotes against any strong operand and never widens
                # data. Strong float64 is still x64 creep below.
                return
            seen.add(key)
            findings.append(Finding(
                "GL201", path, line, 0,
                f"float64 {where} in {context}{prim}: x64 creep — every "
                "engine value is an exact scaled integer",
            ))
        elif name.startswith(("float", "complex", "bfloat")):
            if not allow_floats:
                seen.add(key)
                findings.append(Finding(
                    "GL202", path, line, 0,
                    f"{name} {where} in {context}{prim}: the matching "
                    "envelope is integer-only (inexact lots otherwise)",
                ))
        elif _INT_WIDTH.get(name, 0) > width and name not in _ALWAYS_OK:
            seen.add(key)
            findings.append(Finding(
                "GL203", path, line, 0,
                f"{name} {where} in {context}{prim}: wider than the "
                f"declared {declared_dtype} book dtype (silent promotion "
                "— 2x HBM traffic and a broken saturation argument)",
            ))

    def walk(jaxpr) -> None:
        for var in list(jaxpr.invars) + list(jaxpr.constvars):
            audit_aval(getattr(var, "aval", None), None, "input")
        for eqn in jaxpr.eqns:
            for var in eqn.outvars:
                audit_aval(getattr(var, "aval", None), eqn, "value")
            for sub in _iter_jaxprs(eqn.params):
                walk(sub)

    walk(closed.jaxpr if hasattr(closed, "jaxpr") else closed)
    return findings


#: Per-dtype memo of the traced entry records: the GL2xx envelope walk,
#: the GL6xx donation audit, AND the device cost model (gome_tpu.obs.
#: costmodel) all consume these, and the host trace (~seconds on CPU)
#: must be paid once per CLI/CI run, not per family.
_TRACE_CACHE: dict[str, list[dict]] = {}


def traced_entries(dtype: str = "int32") -> list[dict]:
    """Trace the engine's device entry points with small geometry ONCE
    per dtype; returns records ``{"context", "closed", "args"?,
    "params"?, "jits"?, "n_ops"?}``. ``jits`` pairs each record with its
    compiled public entry (and, where one exists, its ``_donating``
    twin) as ``((label, jit_fn), ...)`` — the cost model lowers these
    with the record's own ``args`` so attribution shares this memo's
    canonical geometry; ``n_ops`` is the orders applied per call (the
    per-order normalizer). Imports jax lazily — the pure-AST checkers
    must not pay for it.

    Tracing runs under the dtype's NATIVE x64 mode (int32 books deploy
    with x64 off; int64 books require it — engine/book.py flips it).
    Auditing an int32 graph traced under x64-on would drown the report in
    jnp.sum's int32→int64 promotion, which the deployment configuration
    never executes."""
    if dtype not in _TRACE_CACHE:
        from jax.experimental import enable_x64, disable_x64

        ctx = enable_x64 if dtype == "int64" else disable_x64
        with ctx():
            _TRACE_CACHE[dtype] = list(_entry_records_x64_scoped(dtype))
    return _TRACE_CACHE[dtype]


def engine_entry_jaxprs(dtype: str = "int32"):
    """Back-compat view of traced_entries: (context, closed_jaxpr)."""
    for rec in traced_entries(dtype):
        yield rec["context"], rec["closed"]


def _entry_records_x64_scoped(dtype: str):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engine import frames as fr
    from ..engine.batch import (
        _lane_scan_impl,
        batch_step,
        batch_step_donating,
        dense_batch_step,
        dense_batch_step_donating,
        lane_scan,
        lane_scan_donating,
    )
    from ..engine.book import BookConfig, DeviceOp, init_books
    from ..engine.step import step_impl

    config = BookConfig(cap=8, max_fills=4, dtype=jnp.dtype(dtype))
    dt = jnp.dtype(dtype)
    s, t = 2, 4

    books = init_books(config, s)
    op_grid = DeviceOp(**{
        f: jnp.zeros((s, t), jnp.int32 if f in ("action", "side", "is_market")
                     else dt)
        for f in DeviceOp._fields
    })
    one_book = jax.tree.map(lambda a: a[0], books)
    one_op = jax.tree.map(lambda a: a[0, 0], op_grid)
    ops_lane = jax.tree.map(lambda a: a[0], op_grid)

    yield dict(
        context="engine/step.py:step_impl",
        closed=jax.make_jaxpr(
            lambda b, o: step_impl(config, b, o))(one_book, one_op),
        args=(config, one_book, one_op),
        params=["config", "book", "op"],
        n_ops=1,
    )
    yield dict(
        context="engine/batch.py:batch_step",
        closed=jax.make_jaxpr(
            lambda b, o: batch_step(config, b, o))(books, op_grid),
        args=(config, books, op_grid),
        params=["config", "books", "ops"],
        jits=(
            ("batch_step", batch_step),
            ("batch_step_donating", batch_step_donating),
        ),
        n_ops=s * t,
    )
    lane_ids = jnp.zeros((s,), jnp.int32)
    yield dict(
        context="engine/batch.py:dense_batch_step",
        closed=jax.make_jaxpr(
            lambda b, l_, o: dense_batch_step(config, b, l_, o)
        )(books, lane_ids, op_grid),
        args=(config, books, lane_ids, op_grid),
        params=["config", "books", "lane_ids", "ops"],
        jits=(
            ("dense_batch_step", dense_batch_step),
            ("dense_batch_step_donating", dense_batch_step_donating),
        ),
        n_ops=s * t,
    )
    yield dict(
        context="engine/batch.py:lane_scan",
        closed=jax.make_jaxpr(
            lambda b, o: _lane_scan_impl(config, b, o))(one_book, ops_lane),
        args=(config, one_book, ops_lane),
        params=["config", "book", "ops_lane"],
        jits=(
            ("lane_scan", lane_scan),
            ("lane_scan_donating", lane_scan_donating),
        ),
        n_ops=t,
    )

    # frame compaction accumulator (the fast-path event path)
    from ..engine.book import StepOutput
    wide = jnp.result_type(jnp.int32, dt)
    k = config.max_fills
    outs = StepOutput(**{
        f: (jnp.zeros((s, t), jnp.int32)
            if f in ("n_fills", "fill_overflow", "rested", "book_overflow",
                     "cancel_found")
            else jnp.zeros((s, t), dt)
            if f in ("taker_remaining", "cancel_volume")
            else jnp.zeros((s, t, k), dt))
        for f in StepOutput._fields
    })
    fills_acc = jnp.zeros((len(fr._FILL_FIELDS), 64), wide)
    cancels_acc = jnp.zeros((len(fr._CANCEL_FIELDS), 64), wide)
    totals_acc = jnp.zeros((8, 4), jnp.int32)
    yield dict(
        context="engine/frames.py:compact_accum",
        closed=jax.make_jaxpr(
            lambda o, f, c, tt: fr.compact_accum(config, o, f, c, tt,
                                                 np.int32(0))
        )(outs, fills_acc, cancels_acc, totals_acc),
        args=(config, outs, fills_acc, cancels_acc, totals_acc,
              np.int32(0)),
        jits=(("compact_accum", fr.compact_accum),),
        n_ops=s * t,
    )

    # device-side grid scatter-builder
    scatter = fr._scatter_grid_fn(dt.name, 2, 4)
    cols = jnp.zeros((7, 64), dt)
    flat = jnp.full((64,), 8, jnp.int32)
    yield dict(
        context="engine/frames.py:_scatter_grid_fn",
        closed=jax.make_jaxpr(scatter)(cols, flat),
        args=(cols, flat),
        jits=(("scatter_grid", scatter),),
        n_ops=64,
    )

    # Pallas kernel, interpret mode (same jaxpr the TPU lowering consumes)
    try:
        from ..ops.pallas_match import pallas_batch_step
        yield dict(
            context="ops/pallas_match.py:pallas_batch_step",
            closed=jax.make_jaxpr(
                lambda b, o: pallas_batch_step(config, b, o, block_s=2,
                                               interpret=True)
            )(books, op_grid),
        )
    except Exception:  # pragma: no cover - interpret support varies
        pass

    # Simulator flow generator (gome_tpu.sim): the emitted op grid must
    # honor the same envelope as the engine that consumes it. Hawkes
    # intensities are float32 BY DESIGN (the stochastic model, never book
    # state), so GL202 is waived for this entry; GL201 (f64 creep) and
    # GL203 (int widening) still audit the integer grid path.
    from ..sim.flow import FlowConfig, flow_init, gen_ops
    fcfg = FlowConfig(n_lanes=s, t_bins=t)
    fstate = flow_init(fcfg, jax.random.PRNGKey(0))
    yield dict(
        context="sim/flow.py:gen_ops",
        closed=jax.make_jaxpr(
            lambda st, b: gen_ops(fcfg, st, b))(fstate, books),
        allow_floats=True,
    )


def check_engine_envelope(dtype: str = "int32") -> list[Finding]:
    """The whole-engine envelope audit the CLI and CI run."""
    findings: list[Finding] = []
    for rec in traced_entries(dtype):
        findings.extend(check_jaxpr(
            rec["closed"], dtype, rec["context"],
            allow_floats=bool(rec.get("allow_floats", False)),
        ))
    return findings

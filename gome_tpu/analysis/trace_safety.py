"""GL1xx trace-safety: host-Python leaks inside jit/pallas-reachable code.

A function is *traced* when jax transforms it: `@jax.jit` (directly or via
`functools.partial(jax.jit, ...)`), a `jax.jit(f)` / `jax.vmap(f)` wrapper
assignment, a kernel handed to `pl.pallas_call`, or any local function a
traced function calls (including bodies handed to `lax.scan` / `lax.cond`
/ `lax.while_loop` / `lax.fori_loop` / `jax.vmap` / `jax.tree.map` inside
traced code). Inside traced functions, the non-static parameters are
*tracers*, and host-Python operations on them either crash at trace time
(`bool()`, `.item()`, `np.asarray`) or — worse — silently bake a single
traced value into the compiled graph. The rules:

  GL101  float()/int()/bool()/complex() on a tracer-derived value
  GL102  .item()/.tolist() on a tracer-derived value
  GL103  Python control flow (`if`/`while`/ternary/`assert`, or a `for`
         directly over a tracer) on a tracer-derived value
  GL104  numpy (`np.*`) call with a tracer-derived argument

Taint model (documented limits — this is a linter, not an interpreter):

  * non-static parameters of traced functions are TRACER; values derived
    from them stay TRACER through arithmetic, jnp/lax calls, subscripts,
    and attribute access;
  * `.shape` / `.dtype` / `.ndim` / `.size` / `.itemsize` and `len(...)`
    are static under tracing — accessing them DE-taints (this is exactly
    why `while k < n` over a shape bound is fine in a jitted body);
  * `list()/tuple()/zip()/enumerate()/reversed()/sorted()` over tracers
    produce host CONTAINERS of tracers: iterating them is static
    unrolling (the NamedTuple-of-rows idiom all over engine/step.py), so
    only *direct* iteration of a TRACER value raises GL103;
  * static args (`static_argnums`/`static_argnames`, values bound by a
    `functools.partial` before `pallas_call`) are not tainted, and
    closures over host values are never tainted;
  * propagation is intra-module (entry points cover the public cross-
    module surfaces in this codebase).
"""

from __future__ import annotations

import ast

from .core import Finding, register_checker, register_rules

register_rules({
    "GL101": "host cast (float/int/bool/complex) of a tracer inside traced code",
    "GL102": ".item()/.tolist() on a tracer inside traced code",
    "GL103": "Python control flow on a tracer-derived value inside traced code",
    "GL104": "numpy call on a tracer-derived value inside traced code",
})

# taint lattice
UNTAINTED, CONTAINER, TRACER = 0, 1, 2

_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "name", "_fields",
    "weak_type", "sharding", "aval",
}
_DETAINT_CALLS = {"len", "isinstance", "type", "id", "repr", "str", "hash"}
_CONTAINER_CALLS = {
    "list", "tuple", "zip", "enumerate", "reversed", "sorted", "dict", "set",
    "vars",
}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_NUMPY_ROOTS = {"np", "numpy"}
#: call-combinators whose function-valued arguments are traced with all
#: params tainted when invoked from traced code: (root-path suffixes).
_BODY_COMBINATORS = {
    "scan", "cond", "while_loop", "fori_loop", "switch", "map",
    "vmap", "pmap", "checkpoint", "remat", "custom_vjp", "associative_scan",
}


def _dotted(node: ast.AST) -> str | None:
    """a.b.c -> 'a.b.c' (Names/Attributes only)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote jax.jit (possibly bare `jit`)?"""
    d = _dotted(node)
    return d is not None and (d == "jit" or d.endswith(".jit"))


def _is_partial(node: ast.AST) -> bool:
    d = _dotted(node) or ""
    return d == "partial" or d.endswith(".partial")


def _const_int_tuple(node: ast.AST | None) -> tuple[int, ...]:
    if node is None:
        return ()
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)):
        return tuple(x for x in v if isinstance(x, int))
    return ()


def _const_str_tuple(node: ast.AST | None) -> tuple[str, ...]:
    if node is None:
        return ()
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, str):
        return (v,)
    if isinstance(v, (tuple, list)):
        return tuple(x for x in v if isinstance(x, str))
    return ()


class _FuncInfo:
    """One function/lambda/method in the module."""

    def __init__(self, node, qualname: str, cls: str | None):
        self.node = node
        self.qualname = qualname
        self.cls = cls  # enclosing class name for methods
        args = node.args
        self.params = [a.arg for a in args.posonlyargs + args.args]
        self.kwonly = [a.arg for a in args.kwonlyargs]
        # param name -> taint (joined over call sites / entry marking)
        self.param_taint: dict[str, int] = {
            p: UNTAINTED for p in self.params + self.kwonly
        }
        self.traced = False

    def mark_entry(self, static_nums: tuple[int, ...],
                   static_names: tuple[str, ...],
                   bound: int = 0) -> bool:
        """Mark as a traced entry; params except static/bound become
        TRACER. Returns True if anything changed."""
        changed = not self.traced
        self.traced = True
        for i, p in enumerate(self.params):
            if i < bound or i in static_nums or p in static_names:
                continue
            if self.param_taint.get(p, UNTAINTED) < TRACER:
                self.param_taint[p] = TRACER
                changed = True
        for p in self.kwonly:
            if p in static_names:
                continue
            if self.param_taint.get(p, UNTAINTED) < TRACER:
                self.param_taint[p] = TRACER
                changed = True
        return changed

    def join_call(self, arg_taints: dict[str, int]) -> bool:
        changed = not self.traced
        self.traced = True
        for p, t in arg_taints.items():
            if t > self.param_taint.get(p, UNTAINTED):
                self.param_taint[p] = t
                changed = True
        return changed


class _Collector(ast.NodeVisitor):
    """Collect every function/lambda with qualnames + class context, the
    jit/pallas entry points, and wrapper assignments."""

    def __init__(self):
        self.funcs: dict[str, _FuncInfo] = {}  # qualname -> info
        self.by_name: dict[str, list[_FuncInfo]] = {}  # bare name -> infos
        self.by_node: dict[ast.AST, _FuncInfo] = {}
        self.entries: list[tuple[_FuncInfo, tuple, tuple, int]] = []
        self._scope: list[str] = []
        self._cls: list[str] = []

    # -- helpers -----------------------------------------------------------
    def _add(self, node, name: str) -> _FuncInfo:
        qual = ".".join(self._scope + [name])
        info = _FuncInfo(node, qual, self._cls[-1] if self._cls else None)
        self.funcs[qual] = info
        self.by_name.setdefault(name, []).append(info)
        self.by_node[node] = info
        return info

    def _mark_from_decorators(self, node, info: _FuncInfo) -> None:
        for dec in node.decorator_list:
            nums, names, is_jit = _jit_spec(dec)
            if is_jit:
                self.entries.append((info, nums, names, 0))
            elif _is_trace_transform(dec):
                self.entries.append((info, (), (), 0))

    # -- visitors ----------------------------------------------------------
    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()
        self._scope.pop()

    def _visit_func(self, node):
        info = self._add(node, node.name)
        self._mark_from_decorators(node, info)
        self._scope.append(node.name)
        cls = self._cls
        self._cls = []  # nested defs inside a method are plain functions
        self.generic_visit(node)
        self._cls = cls
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node)

    def visit_Lambda(self, node):
        self._add(node, f"<lambda:{node.lineno}>")
        self.generic_visit(node)

    def visit_Assign(self, node):
        # x = jax.jit(f) / partial(jax.jit, ...)(f) / jax.vmap(f)
        self._check_wrapper(node.value)
        self.generic_visit(node)

    def visit_Call(self, node):
        self._check_wrapper(node)
        self.generic_visit(node)

    def _check_wrapper(self, call) -> None:
        if not isinstance(call, ast.Call):
            return
        func = call.func
        nums: tuple = ()
        names: tuple = ()
        is_jit = False
        if _is_jit_expr(func):
            is_jit = True
            nums, names = _jit_kwargs(call)
        elif isinstance(func, ast.Call):
            n2, s2, j2 = _jit_spec(func)
            if j2:
                is_jit, nums, names = True, n2, s2
        elif _is_trace_transform(func) or (
            isinstance(func, ast.Attribute) and _dotted(func) and
            (_dotted(func).endswith(".pallas_call") or
             _dotted(func) == "pallas_call")
        ):
            is_jit = True
        if not is_jit:
            return
        for arg in call.args[:1]:
            self._mark_callable_arg(arg, nums, names)

    def _mark_callable_arg(self, arg, nums, names) -> None:
        bound = 0
        target = arg
        if isinstance(arg, ast.Call) and _is_partial(arg.func) and arg.args:
            target = arg.args[0]
            bound = len(arg.args) - 1
        if isinstance(target, ast.Name):
            for info in self.by_name.get(target.id, ()):
                self.entries.append((info, nums, names, bound))
        elif isinstance(target, ast.Lambda):
            info = self.by_node.get(target)
            if info is not None:
                self.entries.append((info, nums, names, bound))


def _jit_kwargs(call: ast.Call) -> tuple[tuple, tuple]:
    nums: tuple = ()
    names: tuple = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _const_int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            names = _const_str_tuple(kw.value)
    return nums, names


def _jit_spec(dec: ast.AST) -> tuple[tuple, tuple, bool]:
    """Decode a decorator/wrapper expression into (static_argnums,
    static_argnames, is_jit)."""
    if _is_jit_expr(dec):
        return (), (), True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            nums, names = _jit_kwargs(dec)
            return nums, names, True
        if _is_partial(dec.func) and dec.args and _is_jit_expr(dec.args[0]):
            nums, names = _jit_kwargs(dec)
            return nums, names, True
    return (), (), False


def _is_trace_transform(node: ast.AST) -> bool:
    """jax.vmap / jax.pmap / shard_map-style transform references."""
    d = _dotted(node)
    if d is None:
        return False
    leaf = d.rsplit(".", 1)[-1]
    return leaf in ("vmap", "pmap", "shard_map", "grad", "value_and_grad")


class _BodyScan(ast.NodeVisitor):
    """Taint scan of ONE function body. Nested defs/lambdas are separate
    scopes (visited by the driver, not here) — we only record the calls
    that pass them around."""

    def __init__(self, checker: "_Checker", info: _FuncInfo, emit: bool):
        self.c = checker
        self.info = info
        self.emit = emit
        self.taint: dict[str, int] = dict(info.param_taint)
        self.findings: list[Finding] = []

    # -- expression taint --------------------------------------------------
    def t(self, node: ast.AST | None) -> int:
        if node is None:
            return UNTAINTED
        method = getattr(self, f"_t_{type(node).__name__}", None)
        if method is not None:
            return method(node)
        # default: join over child expressions
        out = UNTAINTED
        for child in ast.iter_child_nodes(node):
            out = max(out, self.t(child))
        return min(out, TRACER)

    def _t_Name(self, node):
        return self.taint.get(node.id, UNTAINTED)

    def _t_Constant(self, node):
        return UNTAINTED

    def _t_Lambda(self, node):
        return UNTAINTED  # a function object, not a tracer

    def _t_Attribute(self, node):
        if node.attr in _STATIC_ATTRS:
            self.t(node.value)  # still scan for leaks inside
            return UNTAINTED
        return self.t(node.value)

    def _t_Subscript(self, node):
        return max(self.t(node.value), self.t(node.slice))

    def _t_IfExp(self, node):
        if self.t(node.test) >= TRACER:
            self._report("GL103", node,
                         "ternary condition on a tracer-derived value")
        return max(self.t(node.body), self.t(node.orelse))

    def _t_Compare(self, node):
        out = self.t(node.left)
        for cmp_ in node.comparators:
            out = max(out, self.t(cmp_))
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # identity tests (`x is None`) are host-static: they never
            # concretize a tracer, so branching on them is fine.
            return UNTAINTED
        return out

    def _t_Call(self, node):
        fname = _dotted(node.func)
        leaf = (fname or "").rsplit(".", 1)[-1]
        arg_ts = [self.t(a) for a in node.args]
        kw_ts = [self.t(k.value) for k in node.keywords]
        worst = max(arg_ts + kw_ts, default=UNTAINTED)

        # .item()/.tolist() on a tracer
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("item", "tolist"):
            recv = self.t(node.func.value)
            if recv >= TRACER:
                self._report(
                    "GL102", node,
                    f".{node.func.attr}() forces a device sync / concretizes "
                    "a tracer inside traced code",
                )
            return UNTAINTED
        if fname in _DETAINT_CALLS:
            return UNTAINTED
        if fname in _HOST_CASTS:
            if worst >= TRACER:
                self._report(
                    "GL101", node,
                    f"{fname}() concretizes a tracer inside traced code "
                    "(TracerBoolConversionError at trace time, or a baked-in "
                    "constant)",
                )
            return UNTAINTED
        if fname in _CONTAINER_CALLS:
            return CONTAINER if worst else UNTAINTED
        root = (fname or "").split(".", 1)[0]
        if root in _NUMPY_ROOTS:
            if worst >= TRACER:
                self._report(
                    "GL104", node,
                    f"numpy call {fname}() on a tracer-derived value "
                    "(host materialization inside traced code)",
                )
            return UNTAINTED
        # combinators that trace a function argument
        if leaf in _BODY_COMBINATORS and self.info.traced:
            self.c.note_combinator(node, self)
        # calls into local functions propagate taint to params
        self.c.note_call(node, self, arg_ts)
        # method call on a tainted receiver keeps taint (e.g. _replace)
        if isinstance(node.func, ast.Attribute):
            worst = max(worst, self.t(node.func.value))
        return min(worst, TRACER)

    # -- statements --------------------------------------------------------
    def _assign(self, target, taint: int) -> None:
        if isinstance(target, ast.Name):
            self.taint[target.id] = taint
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                # unpacking a CONTAINER yields tracers
                self._assign(el, TRACER if taint else UNTAINTED)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, taint)
        # attribute/subscript stores don't track

    def visit_Assign(self, node):
        t = self.t(node.value)
        for target in node.targets:
            self._assign(target, t)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._assign(node.target, self.t(node.value))

    def visit_AugAssign(self, node):
        t = self.t(node.value)
        if isinstance(node.target, ast.Name):
            prev = self.taint.get(node.target.id, UNTAINTED)
            self.taint[node.target.id] = max(prev, t)

    def visit_If(self, node):
        if self.t(node.test) >= TRACER:
            self._report("GL103", node.test,
                         "`if` on a tracer-derived value (host branch on a "
                         "traced value; use jnp.where/lax.cond)")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.t(node.test) >= TRACER:
            self._report("GL103", node.test,
                         "`while` on a tracer-derived value (use "
                         "lax.while_loop)")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.t(node.test) >= TRACER:
            self._report("GL103", node.test,
                         "`assert` on a tracer-derived value (use "
                         "checkify or a masked guard)")
        self.generic_visit(node)

    def _iter_taint(self, node):
        it = self.t(node.iter)
        if it >= TRACER and isinstance(node.iter, ast.Name):
            self._report(
                "GL103", node.iter,
                "`for` directly over a tracer (unrolls per-element; use "
                "lax.scan/fori_loop)",
            )
        self._assign(node.target, TRACER if it else UNTAINTED)

    def visit_For(self, node):
        self._iter_taint(node)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _comp(self, node):
        for gen in node.generators:
            it = self.t(gen.iter)
            self._assign(gen.target, TRACER if it else UNTAINTED)
            for cond in gen.ifs:
                self.t(cond)
        return self.t(getattr(node, "elt", None) or node.key), node

    def _t_ListComp(self, node):
        return self._comp(node)[0]

    def _t_SetComp(self, node):
        return self._comp(node)[0]

    def _t_GeneratorExp(self, node):
        return self._comp(node)[0]

    def _t_DictComp(self, node):
        for gen in node.generators:
            it = self.t(gen.iter)
            self._assign(gen.target, TRACER if it else UNTAINTED)
        return max(self.t(node.key), self.t(node.value))

    def visit_Expr(self, node):
        self.t(node.value)

    def visit_Return(self, node):
        if node.value is not None:
            self.t(node.value)

    def visit_FunctionDef(self, node):
        pass  # nested scopes visited by the driver

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_With(self, node):
        for item in node.items:
            self.t(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)

    def generic_visit(self, node):
        # statements we don't special-case: evaluate expressions for leaks
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.t(child)
            else:
                self.visit(child)

    def run(self):
        for stmt in self.info.node.body if not isinstance(
                self.info.node, ast.Lambda) else []:
            self.visit(stmt)
        if isinstance(self.info.node, ast.Lambda):
            self.t(self.info.node.body)
        return self

    def _report(self, rule: str, node: ast.AST, msg: str) -> None:
        if self.emit:
            self.findings.append(Finding(
                rule, self.c.module.path, getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0),
                f"{msg} [in {self.info.qualname}]",
            ))


class _Checker:
    def __init__(self, module):
        self.module = module
        self.collector = _Collector()
        self.collector.visit(module.tree)
        self._changed = False

    # -- call-graph notes (invoked during body scans) ----------------------
    def note_call(self, node: ast.Call, scan: _BodyScan,
                  arg_ts: list[int]) -> None:
        if not scan.info.traced:
            return
        target = self._resolve(node.func, scan)
        if target is None:
            return
        taints: dict[str, int] = {}
        params = target.params
        offset = 0
        if isinstance(node.func, ast.Attribute) and params[:1] == ["self"]:
            taints["self"] = min(scan.t(node.func.value), TRACER)
            offset = 1
        for i, t in enumerate(arg_ts):
            if offset + i < len(params):
                taints[params[offset + i]] = t
        for kw, t in zip(node.keywords,
                         [scan.t(k.value) for k in node.keywords]):
            if kw.arg:
                taints[kw.arg] = t
        if target.join_call(taints):
            self._changed = True

    def note_combinator(self, node: ast.Call, scan: _BodyScan) -> None:
        """lax.scan(body, ...) etc. inside traced code: the function-valued
        args become traced with all params TRACER."""
        for arg in list(node.args) + [k.value for k in node.keywords]:
            target = None
            if isinstance(arg, ast.Name):
                cands = self.collector.by_name.get(arg.id, ())
                target = cands[0] if len(cands) == 1 else None
            elif isinstance(arg, ast.Lambda):
                target = self.collector.by_node.get(arg)
            if target is not None and target.mark_entry((), ()):
                self._changed = True

    def _resolve(self, func: ast.AST, scan: _BodyScan):
        if isinstance(func, ast.Name):
            cands = self.collector.by_name.get(func.id, ())
            if len(cands) == 1:
                return cands[0]
            # prefer a sibling nested function in the same enclosing scope
            for c in cands:
                if c.qualname.rsplit(".", 1)[0] == \
                        scan.info.qualname.rsplit(".", 1)[0]:
                    return c
            return cands[0] if cands else None
        if isinstance(func, ast.Attribute):
            # self.method()/obj.method(): resolve by unique method name
            cands = [c for c in self.collector.by_name.get(func.attr, ())
                     if c.cls is not None]
            if len(cands) == 1:
                return cands[0]
        return None

    def run(self) -> list[Finding]:
        # seed entries
        for info, nums, names, bound in self.collector.entries:
            info.mark_entry(nums, names, bound)
        # fixpoint: propagate taint along the intra-module call graph
        for _ in range(12):
            self._changed = False
            for info in self.collector.funcs.values():
                if info.traced:
                    _BodyScan(self, info, emit=False).run()
            if not self._changed:
                break
        findings: list[Finding] = []
        for info in self.collector.funcs.values():
            if info.traced:
                findings.extend(_BodyScan(self, info, emit=True).run().findings)
        return findings


def check(module) -> list[Finding]:
    return _Checker(module).run()


register_checker("GL1", check)

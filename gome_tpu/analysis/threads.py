"""GL7xx thread-escape analysis: find shared state with NO sharing contract.

GL4xx (analysis.locks) enforces the `# guarded by` contract on attributes
that *declare* one — it says nothing about shared attributes that never
declared anything. This pass closes that hole. It computes the project's
*thread-escape set*: classes whose instances are reachable by more than
one thread, because the class

  * owns a thread — any ``threading.Thread(...)`` constructed in its body
    (the daemon-loop pattern: batcher deadline loop, consumer, matchfeed
    fan-out, watchdog, samplers, broker accept loops);
  * is published as a module-level singleton — ``FAULTS = FaultRegistry()``
    style ALL-CAPS assignments, reachable from every thread that imports
    the module (FAULTS/HOSTPROF/TIMELINE/PROFILER/TRACER/REGISTRY/...);
  * is constructed INTO an escaped class — ``self.seq = SeqTracker()``
    inside MatchFeed escapes SeqTracker too (transitively).

Within an escaped class, every attribute **mutation** outside
``__init__``/``__new__`` must carry a sharing contract:

  * ``# guarded by self._lock`` on the attribute's declaration — GL4xx
    then enforces the lock on every touch (the strong contract);
  * ``# single-writer: <who>`` on the declaration line — documents that
    exactly one thread mutates it (readers tolerate staleness; a GIL-
    atomic store is never torn). A class-level claim on the ``class`` line
    (or the line above) covers every attribute of the class;
  * neither ⇒ GL701. A mutation that happens to sit under a ``with
    self.<lock>:`` the declaration never mentions ⇒ GL702 (annotation
    drift: the code locks, the contract doesn't say so).

The single-writer claim is *checked*, not just trusted, where the writer
thread is statically known: for a thread-owning class, methods reachable
from the ``Thread(target=...)`` entry (over the PR 4 interprocedural call
graph) are thread-side; a single-writer attribute mutated BOTH thread-side
and from outside that reach has two writers ⇒ GL704 at the outside site.
Pre-start recovery hooks (a real happens-before edge the AST cannot see)
suppress with justification: ``# gomelint: disable=GL704 — called before
start()``.

Known lexical limits (same trade as GL4xx, documented not hidden):
container mutation through method calls (``self._buf.append(x)``) is a
Load of the attribute, not a Store — the guard contract for containers
lives in GL4xx once declared; mutations of a singleton's attributes from
*outside* its class (``FAULTS.enabled = True`` in a script) are not
scanned; and reads are never flagged (a stale read of one attribute is a
semantics question, not a torn-write question).

Rules:

  GL701  thread-escaped attribute mutated with no lock held and no
         sharing contract
  GL702  thread-escaped attribute mutated under a lock its declaration
         does not name
  GL703  attribute declares BOTH `# guarded by` and `# single-writer`
  GL704  single-writer attribute mutated outside the writer thread's
         reach while the writer thread also mutates it

The dynamic half of this story is analysis.racecheck (Eraser-style
lockset detection at runtime) — GL7xx is the cheap always-on gate, the
lockset detector is the witness generator.
"""

from __future__ import annotations

import ast
import re

from .callgraph import build
from .core import Finding, register_project_checker, register_rules
from .locks import _GUARD_RE, _holds_from_comment, _self_attr

register_rules({
    "GL701": "thread-escaped attribute mutated with no sharing contract",
    "GL702": "thread-escaped attribute mutated under an undeclared lock",
    "GL703": "attribute declares both `# guarded by` and `# single-writer`",
    "GL704": "single-writer attribute also mutated outside the writer "
             "thread's reach",
})

_SINGLE_RE = re.compile(r"#\s*single-writer\b(?::\s*(\S[^#]*))?")
_SINGLETON_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


def _dotted_tail(node: ast.AST) -> str | None:
    """Bare name of a Name/Attribute callee ('Thread' for threading.Thread)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Cls:
    """One class of the project: attribute contracts + escape evidence."""

    def __init__(self, module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.assigned: set[str] = set()
        self.guards: dict[str, str] = {}  # attr -> lock attr (GL4 grammar)
        self.single: dict[str, str] = {}  # attr -> documented writer
        self.decl_lines: dict[str, int] = {}
        self.class_single: str | None = None  # class-wide single-writer
        #: Thread(target=...) entries: ("method", name) | ("name", name)
        self.thread_targets: list[tuple[str, str]] = []
        self.constructs: list[str] = []  # class names built into self.<attr>
        self.escape: str | None = None  # reason, once escaped

    def contract(self, attr: str) -> str | None:
        if attr in self.guards:
            return "guarded"
        if attr in self.single or self.class_single is not None:
            return "single-writer"
        return None


class _Mut:
    """One attribute mutation site inside an escaped class."""

    __slots__ = ("attr", "node", "func_ast", "held")

    def __init__(self, attr, node, func_ast, held):
        self.attr = attr
        self.node = node
        self.func_ast = func_ast  # enclosing function's AST node
        self.held = held  # lock attrs lexically held at the site


def _class_body_nodes(cls_node: ast.ClassDef):
    """Walk a class body without descending into nested classes."""
    stack = list(cls_node.body)
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, ast.ClassDef):
            stack.extend(ast.iter_child_nodes(n))


def _collect_class(module, node: ast.ClassDef) -> _Cls:
    cls = _Cls(module, node)
    for ln in (node.lineno, node.lineno - 1):
        m = _SINGLE_RE.search(module.line_comment(ln))
        if m:
            cls.class_single = (m.group(1) or "").strip()
            break
    for n in _class_body_nodes(node):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                cls.assigned.add(attr)
                comment = module.line_comment(n.lineno)
                gm = _GUARD_RE.search(comment)
                sm = _SINGLE_RE.search(comment)
                if gm and attr not in cls.guards:
                    cls.guards[attr] = gm.group(1)
                    cls.decl_lines.setdefault(attr, n.lineno)
                if sm and attr not in cls.single:
                    cls.single[attr] = (sm.group(1) or "").strip()
                    cls.decl_lines.setdefault(attr, n.lineno)
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and _self_attr(n.targets[0]) is not None \
                    and isinstance(n.value, ast.Call):
                callee = _dotted_tail(n.value.func)
                if callee and callee[:1].isupper():
                    cls.constructs.append(callee)
        elif isinstance(n, ast.Call):
            callee = _dotted_tail(n.func)
            if callee == "Thread":
                for kw in n.keywords:
                    if kw.arg != "target":
                        continue
                    tattr = _self_attr(kw.value)
                    if tattr is not None:
                        cls.thread_targets.append(("method", tattr))
                    elif isinstance(kw.value, ast.Name):
                        cls.thread_targets.append(("name", kw.value.id))
                if not any(kw.arg == "target" for kw in n.keywords):
                    cls.thread_targets.append(("name", "<unknown>"))
                cls.escape = cls.escape or "owns a thread"
    return cls


class _MutScan(ast.NodeVisitor):
    """Collect mutations of one method body with the lexically-held lock
    set — the GL4xx _MethodScan discipline (with-blocks, `_locked` suffix,
    `# holds:` annotations; closures start fresh, `__init__` is exempt)."""

    def __init__(self, cls: _Cls, out: list[_Mut], held: set[str],
                 exempt: bool, func_ast):
        self.cls = cls
        self.out = out
        self.held = held
        self.exempt = exempt
        self.func_ast = func_ast

    def visit_With(self, node):
        added = {a for item in node.items
                 if (a := _self_attr(item.context_expr)) is not None}
        self.held |= added
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    def _nested(self, node, name: str):
        held = _holds_from_comment(
            self.cls.module.line_comment(node.lineno))
        if not held and node.lineno > 1:
            held = _holds_from_comment(
                self.cls.module.line_comment(node.lineno - 1))
        if name.endswith("_locked"):
            held |= set(self.cls.guards.values())
        scan = _MutScan(self.cls, self.out, held, exempt=False,
                        func_ast=node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in node.body:
                scan.visit(stmt)
        else:  # Lambda
            scan.visit(node.body)

    def visit_FunctionDef(self, node):
        self._nested(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._nested(node, node.name)

    def visit_Lambda(self, node):
        self._nested(node, "<lambda>")

    def visit_Attribute(self, node):
        attr = _self_attr(node)
        if attr is not None and not self.exempt \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            self.out.append(
                _Mut(attr, node, self.func_ast, frozenset(self.held)))
        self.generic_visit(node)


def _escape_classes(classes: list[_Cls], modules) -> None:
    """Mark escaped classes: thread owners (done at collect), module-level
    ALL-CAPS singletons, then transitive construction into escaped ones."""
    by_name: dict[str, list[_Cls]] = {}
    for c in classes:
        by_name.setdefault(c.name, []).append(c)
    for module in modules:
        for stmt in module.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and _SINGLETON_NAME_RE.match(stmt.targets[0].id)
                    and isinstance(stmt.value, ast.Call)):
                continue
            callee = _dotted_tail(stmt.value.func)
            for c in by_name.get(callee or "", ()):
                c.escape = c.escape or \
                    f"module-level singleton {stmt.targets[0].id}"
    work = [c for c in classes if c.escape]
    seen = set(id(c) for c in work)
    while work:
        c = work.pop()
        for built in c.constructs:
            for d in by_name.get(built, ()):
                if id(d) not in seen:
                    seen.add(id(d))
                    d.escape = d.escape or f"constructed into escaped " \
                                           f"{c.name}"
                    work.append(d)


def _thread_side(cls: _Cls, graph) -> set:
    """FuncNodes reachable from the class's Thread(target=...) entries."""
    roots = []
    for kind, name in cls.thread_targets:
        if kind == "method":
            roots += [f for f in graph.methods.get(name, ())
                      if f.cls == cls.name and f.module is cls.module]
        else:
            roots += [f for f in graph.by_name.get(name, ())
                      if f.module is cls.module]
    seen = set(roots)
    work = list(roots)
    while work:
        fn = work.pop()
        for nxt in graph.edges.get(fn, ()):
            if nxt not in seen:
                seen.add(nxt)
                work.append(nxt)
    return seen


def _check_class(cls: _Cls, graph, findings: list[Finding]) -> None:
    # GL703 — contradictory contracts, flagged even for non-escaped
    # classes (the annotation is wrong wherever it is).
    for attr in sorted(set(cls.guards) & set(cls.single)):
        findings.append(Finding(
            "GL703", cls.module.path, cls.decl_lines[attr], 0,
            f"self.{attr} declares both `# guarded by self."
            f"{cls.guards[attr]}` and `# single-writer` — a guarded "
            f"attribute has many writers by design; pick one contract "
            f"[class {cls.name}]",
        ))
    if cls.escape is None:
        return
    muts: list[_Mut] = []
    for node in cls.node.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        held = _holds_from_comment(cls.module.line_comment(node.lineno))
        if not held and node.lineno > 1:
            held |= _holds_from_comment(
                cls.module.line_comment(node.lineno - 1))
        if node.name.endswith("_locked"):
            held |= set(cls.guards.values())
        exempt = node.name in ("__init__", "__new__")
        scan = _MutScan(cls, muts, held, exempt, func_ast=node)
        for stmt in node.body:
            scan.visit(stmt)

    single_sites: dict[str, list[_Mut]] = {}
    for m in muts:
        contract = cls.contract(m.attr)
        if contract == "guarded":
            continue  # GL4xx enforces the declared lock on this site
        if contract == "single-writer":
            single_sites.setdefault(m.attr, []).append(m)
            continue
        if m.held:
            lock = sorted(m.held)[0]
            findings.append(Finding(
                "GL702", cls.module.path, m.node.lineno, m.node.col_offset,
                f"self.{m.attr} is thread-shared ({cls.escape}) and "
                f"mutated under self.{lock}, but its declaration has no "
                f"`# guarded by self.{lock}` — declare the guard so GL4xx "
                f"enforces it everywhere [class {cls.name}]",
            ))
        else:
            findings.append(Finding(
                "GL701", cls.module.path, m.node.lineno, m.node.col_offset,
                f"self.{m.attr} is thread-shared ({cls.escape}) but "
                f"mutated with no lock held and no sharing contract — "
                f"declare `# guarded by self.<lock>` or `# single-writer: "
                f"<who>` on its declaration [class {cls.name}]",
            ))

    # GL704 — verify single-writer claims where the writer thread is
    # statically known (the class spawns it).
    if not cls.thread_targets or not single_sites:
        return
    reach = _thread_side(cls, graph)
    if not reach:
        return
    for attr, sites in sorted(single_sites.items()):
        inside = [m for m in sites if graph.by_node.get(m.func_ast) in reach]
        outside = [m for m in sites
                   if graph.by_node.get(m.func_ast) not in reach]
        if not inside or not outside:
            continue  # one side only: the claim is consistent
        witness = inside[0].node.lineno
        for m in outside:
            findings.append(Finding(
                "GL704", cls.module.path, m.node.lineno, m.node.col_offset,
                f"self.{m.attr} is declared single-writer but this "
                f"mutation is outside the spawned thread's reach while "
                f"the thread also writes it (line {witness}) — two "
                f"writers contradict the claim; lock it, or suppress "
                f"with the happens-before justification "
                f"[class {cls.name}]",
            ))


def check(project) -> list[Finding]:
    graph = build(project)
    classes: list[_Cls] = []
    for module in project.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                classes.append(_collect_class(module, node))
    _escape_classes(classes, project.modules)
    findings: list[Finding] = []
    for cls in classes:
        _check_class(cls, graph, findings)
    return findings


register_project_checker("GL7", check)

"""GL6xx buffer-donation: double-buffered device state + donation misuse.

An undonated jitted step double-buffers every output-sized array: XLA
must materialize the new books next to the old ones, so the steady-state
HBM footprint (and allocator traffic) of `books' = step(books, ops)` is
2x the book stack — the dtype knob halves book bytes for exactly this
kind of win, and donation gets it back for free where the input really
is dead. This family audits the *declared* donation policy of the
engine's jitted entry points against the shapes that actually flow
through them (alongside the GL2xx envelope audit, which walks the same
traced jaxprs — the trace work is shared, see envelope.traced_entries):

  GL601  a non-static argument whose buffers could ALL be reused by the
         call's outputs (same shape/dtype, materially sized) is not
         donated — the call silently double-buffers it
  GL602  donate_argnums names an argument none of whose buffers any
         output can reuse — the donation is a silent no-op (XLA warns
         and simply frees it)
  GL603  a value passed in a donated position is read again after the
         call — on donation-supporting backends that raises "Array has
         been deleted" at runtime; statically it means the argument was
         NOT dead and must not be donated (AST call-site liveness check)

GL601 is a *candidate* report, not a command: the engine deliberately
keeps the pre-grid book stack alive for escalation replay and the
transactional rollback (batch.BatchEngine._run_exact/_checkpoint), so
its `books` arguments carry line suppressions documenting that retention
— the finding records the cost, the suppression records the reason.
Arguments below ``min_fraction`` (default 10%) of the output bytes are
ignored: donating a [R] lane-id vector saves nothing and the report
should name the buffers that matter.

GL601/GL602 need real avals; fixture tests drive :func:`audit_donation`
with synthetic ``(shape, dtype)`` leaves, while the CLI's ``--jaxpr``
pass drives :func:`check_engine_donation` with the engine's entries.
GL603 is a pure-AST project checker and runs with the default rules.
"""

from __future__ import annotations

import ast
from collections import Counter

from . import callgraph
from .core import Finding, register_project_checker, register_rules
from .trace_safety import _const_int_tuple, _is_jit_expr, _is_partial

register_rules({
    "GL601": "dead same-shape argument of a jitted entry is not donated "
             "(silent double-buffer)",
    "GL602": "donate_argnums names an argument no output buffer can reuse",
    "GL603": "value passed in a donated position is used after the call",
})


# --- jit wrapper spec extraction (shared by the audit and GL603) ---------

def _kw_int_tuple(call: ast.Call, name: str) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == name:
            return _const_int_tuple(kw.value)
    return ()


def _spec_of_call(call: ast.Call) -> tuple[tuple, tuple] | None:
    """(static_argnums, donate_argnums) of a jit-constructing Call:
    ``jax.jit(f, ...)`` / ``functools.partial(jax.jit, ...)``."""
    if _is_jit_expr(call.func):
        return (_kw_int_tuple(call, "static_argnums"),
                _kw_int_tuple(call, "donate_argnums"))
    if _is_partial(call.func) and call.args and _is_jit_expr(call.args[0]):
        return (_kw_int_tuple(call, "static_argnums"),
                _kw_int_tuple(call, "donate_argnums"))
    return None


def wrapper_jit_spec(tree: ast.AST, name: str):
    """Find the jit spec of wrapper `name` in a module tree: a decorated
    ``def name`` or a ``name = <jit-or-partial>(impl)`` assignment.
    Returns (static_argnums, donate_argnums, lineno) or None."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    spec = _spec_of_call(dec)
                    if spec is not None:
                        return (*spec, node.lineno)
                elif _is_jit_expr(dec):
                    return ((), (), node.lineno)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if not any(isinstance(t, ast.Name) and t.id == name
                       for t in node.targets):
                continue
            value = node.value
            # direct form: name = jax.jit(impl, donate_argnums=...)
            spec = _spec_of_call(value)
            if spec is not None and value.args:
                return (*spec, node.lineno)
            # curried form: name = partial(jax.jit, ...)(impl)
            if isinstance(value.func, ast.Call):
                spec = _spec_of_call(value.func)
                if spec is not None:
                    return (*spec, node.lineno)
    return None


# --- GL601/GL602: the aval-level audit -----------------------------------

def _leaf_bytes(leaf: tuple) -> int:
    import numpy as np

    shape, dtype = leaf
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def audit_donation(
    context: str,
    args: list,
    static_argnums: tuple,
    donate_argnums: tuple,
    out_avals: list,
    params: list | None = None,
    path: str = "",
    line: int = 0,
    min_fraction: float = 0.10,
) -> list[Finding]:
    """Audit one jitted entry's donation policy.

    args: per-argument lists of ``(shape, dtype)`` leaves (None for
    arguments with no array leaves, e.g. static configs). out_avals: the
    flat ``(shape, dtype)`` list of the traced call's outputs. Matching
    is multiset-aware: donated arguments claim their output buffers
    first; each remaining argument is then judged independently against
    the leftover pool."""
    findings: list[Finding] = []
    norm_out = [_norm_leaf(a) for a in out_avals]
    pool = Counter(norm_out)
    out_bytes = sum(_leaf_bytes(a) for a in norm_out) or 1

    def pname(i: int) -> str:
        if params and i < len(params):
            return f"#{i} ({params[i]!r})"
        return f"#{i}"

    # donated args claim their matches (and reveal GL602 no-ops)
    for i in donate_argnums:
        leaves = args[i] if i < len(args) else None
        if not leaves:
            continue
        matched = 0
        for leaf in map(_norm_leaf, leaves):
            if pool[leaf] > 0:
                pool[leaf] -= 1
                matched += 1
        if matched == 0:
            findings.append(Finding(
                "GL602", path, line, 0,
                f"{context}: donated argument {pname(i)} matches no output "
                "buffer (shape/dtype mismatch) — the donation is a silent "
                "no-op and the buffer is simply freed",
            ))

    for i, leaves in enumerate(args):
        if i in static_argnums or i in donate_argnums or not leaves:
            continue
        norm = [_norm_leaf(x) for x in leaves]
        trial = Counter(pool)
        usable = True
        for leaf in norm:
            if trial[leaf] <= 0:
                usable = False
                break
            trial[leaf] -= 1
        if not usable:
            continue
        arg_bytes = sum(_leaf_bytes(x) for x in norm)
        if arg_bytes < min_fraction * out_bytes:
            continue
        findings.append(Finding(
            "GL601", path, line, 0,
            f"{context}: argument {pname(i)} ({arg_bytes}B of buffers, all "
            "reusable by the outputs) is not donated — every call "
            "double-buffers it; add donate_argnums (or suppress with the "
            "liveness reason)",
        ))
    return findings


def _norm_leaf(leaf) -> tuple:
    shape, dtype = leaf
    return (tuple(int(d) for d in shape), str(dtype))


def _arg_leaves(tree):
    """Example pytree -> [(shape, dtype)] for array leaves; None if the
    argument carries no arrays (static config, python scalars)."""
    import jax

    leaves = [
        (tuple(x.shape), str(x.dtype))
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "shape") and hasattr(x, "dtype")
    ]
    return leaves or None


#: The audited engine wrappers: (module_rel, wrapper_name, trace_context,
#: arg_map, params). Several wrappers share one traced graph — the public
#: entry, its `_donating` twin, and the Pallas kernel variants (identical
#: output avals by construction) differ only in signature layout and
#: donate_argnums, which come from the AST. arg_map maps wrapper arg
#: positions to the trace record's example args (None = non-array static,
#: e.g. block_s/interpret).
_ENGINE_WRAPPERS = [
    ("engine/step.py", "step", "engine/step.py:step_impl",
     [0, 1, 2], ["config", "book", "op"]),
    ("engine/batch.py", "batch_step", "engine/batch.py:batch_step",
     [0, 1, 2], ["config", "books", "ops"]),
    ("engine/batch.py", "batch_step_donating", "engine/batch.py:batch_step",
     [0, 1, 2], ["config", "books", "ops"]),
    ("engine/batch.py", "dense_batch_step",
     "engine/batch.py:dense_batch_step",
     [0, 1, 2, 3], ["config", "books", "lane_ids", "ops"]),
    ("engine/batch.py", "dense_batch_step_donating",
     "engine/batch.py:dense_batch_step",
     [0, 1, 2, 3], ["config", "books", "lane_ids", "ops"]),
    ("engine/batch.py", "lane_scan", "engine/batch.py:lane_scan",
     [0, 1, 2], ["config", "book", "ops_lane"]),
    ("engine/batch.py", "lane_scan_donating", "engine/batch.py:lane_scan",
     [0, 1, 2], ["config", "book", "ops_lane"]),
    ("engine/batch.py", "full_kernel_step", "engine/batch.py:batch_step",
     [0, 1, 2, None, None],
     ["config", "books", "ops", "block_s", "interpret"]),
    ("engine/batch.py", "full_kernel_step_donating",
     "engine/batch.py:batch_step",
     [0, 1, 2, None, None],
     ["config", "books", "ops", "block_s", "interpret"]),
    ("engine/batch.py", "dense_kernel_step",
     "engine/batch.py:dense_batch_step",
     [0, 1, 2, 3, None, None],
     ["config", "books", "lane_ids", "ops", "block_s", "interpret"]),
    ("engine/batch.py", "dense_kernel_step_donating",
     "engine/batch.py:dense_batch_step",
     [0, 1, 2, 3, None, None],
     ["config", "books", "lane_ids", "ops", "block_s", "interpret"]),
]


def check_engine_donation(dtype: str = "int32") -> list[Finding]:
    """Audit the engine's jitted step/batch entry points (CLI --jaxpr).
    Reuses the jaxprs the GL2xx envelope audit already traced — the
    shared memo in envelope.traced_entries keeps the CI analysis job at
    one trace per entry for both families."""
    import os

    from .envelope import traced_entries

    records = {rec["context"]: rec for rec in traced_entries(dtype)}
    findings: list[Finding] = []
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tree_cache: dict[str, ast.AST] = {}
    for rel, wrapper, context, arg_map, params in _ENGINE_WRAPPERS:
        rec = records.get(context)
        if rec is None or "args" not in rec:
            continue
        path = os.path.join(root, rel)
        if rel not in tree_cache:
            with open(path, encoding="utf-8") as fh:
                tree_cache[rel] = ast.parse(fh.read(), filename=path)
        spec = wrapper_jit_spec(tree_cache[rel], wrapper)
        if spec is None:
            continue  # wrapper vanished; the table is stale — skip
        static, donate, lineno = spec
        closed = rec["closed"]
        out_avals = [
            (tuple(v.aval.shape), str(v.aval.dtype))
            for v in closed.jaxpr.outvars
            if hasattr(getattr(v, "aval", None), "shape")
        ]
        example = rec["args"]
        args = [
            None if src is None else _arg_leaves(example[src])
            for src in arg_map
        ]
        findings.extend(audit_donation(
            context=f"gome_tpu/{rel}:{wrapper}",
            args=args,
            static_argnums=static,
            donate_argnums=donate,
            out_avals=out_avals,
            params=params,
            path=f"gome_tpu/{rel}",
            line=lineno,
        ))
    return findings


# --- GL603: call-site use-after-donation (pure AST, project scope) -------

class _DonatingRegistry:
    """name -> [(module, is_module_level, donated positions)] for every
    jit wrapper with a non-empty donate_argnums in the project. Matching
    is by bare name, scoped: a wrapper defined INSIDE a function (a local
    like bench.py's `stepper`) only matches calls in its own module —
    an unrelated same-named local elsewhere is not it; module-level
    wrappers are importable and match project-wide."""

    def __init__(self, project):
        self.donate: dict[str, list[tuple[object, bool, tuple]]] = {}
        for module in project.modules:
            top = set(module.tree.body)
            for cls in module.tree.body:
                if isinstance(cls, ast.ClassDef):
                    top |= set(cls.body)
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            spec = _spec_of_call(dec)
                            if spec and spec[1]:
                                self._add(node.name, module,
                                          node in top, spec[1])
                elif isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    # curried: x = partial(jax.jit, ...)(impl);
                    # direct:  x = jax.jit(impl, donate_argnums=...)
                    spec = None
                    if isinstance(node.value.func, ast.Call):
                        spec = _spec_of_call(node.value.func)
                    if spec is None and node.value.args:
                        spec = _spec_of_call(node.value)
                    if spec and spec[1]:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self._add(t.id, module, node in top,
                                          spec[1])

    def _add(self, name, module, module_level, donate) -> None:
        self.donate.setdefault(name, []).append(
            (module, module_level, donate)
        )

    def lookup(self, name: str, module) -> tuple[int, ...] | None:
        """Donated positions of `name` as callable from `module`; the
        union over matching definitions (conservative)."""
        out: set[int] = set()
        for mod, module_level, donate in self.donate.get(name, ()):
            if mod is module or module_level:
                out.update(donate)
        return tuple(sorted(out)) or None


class _LivenessScan(ast.NodeVisitor):
    """One function body: collect Name load/store events and calls into
    donating wrappers, then flag donated names that are read again after
    the call without an intervening rebind (lexical liveness — the same
    approximation the GL4xx lock checker makes, documented there)."""

    def __init__(self, registry: _DonatingRegistry, fn: callgraph.FuncNode):
        self.reg = registry
        self.fn = fn
        self.events: list[tuple[int, int, str, bool]] = []  # line,col,name,is_store
        self.calls: list[tuple[ast.Call, int, set[str], tuple]] = []
        self._rebinds: list[set[str]] = []
        self._in_return = 0

    def visit_FunctionDef(self, node):
        if node is not self.fn.node:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self.fn.node:
            self.visit(node.body)

    def visit_Name(self, node):
        self.events.append((
            node.lineno, node.col_offset, node.id,
            isinstance(node.ctx, (ast.Store, ast.Del)),
        ))

    def _targets(self, targets) -> set[str]:
        names: set[str] = set()
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.add(n.id)
        return names

    def visit_Assign(self, node):
        self._rebinds.append(self._targets(node.targets))
        self.visit(node.value)
        self._rebinds.pop()
        for t in node.targets:
            self.visit(t)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._rebinds.append(self._targets([node.target]))
            self.visit(node.value)
            self._rebinds.pop()
        self.visit(node.target)

    def visit_Return(self, node):
        # `return f(x, ...)` ends the frame: nothing after it can read a
        # donated argument on THIS path, and lexically-later reads belong
        # to other branches.
        self._in_return += 1
        self.generic_visit(node)
        self._in_return -= 1

    def visit_Call(self, node):
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        donate = (
            None if self._in_return
            else self.reg.lookup(name or "", self.fn.module)
        )
        if donate:
            rebound = set().union(*self._rebinds) if self._rebinds else set()
            end = getattr(node, "end_lineno", node.lineno)
            self.calls.append((node, end, rebound, donate))
        self.generic_visit(node)

    def run(self) -> list[Finding]:
        self.visit(self.fn.node)
        findings: list[Finding] = []
        for call, end, rebound, donate in self.calls:
            name = (call.func.id if isinstance(call.func, ast.Name)
                    else call.func.attr)
            for pos in donate:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound:
                    continue  # x, ... = f(..., x, ...): the rebind IS death
                nxt = min(
                    (ev for ev in self.events
                     if ev[2] == arg.id and ev[0] > end),
                    default=None,
                )
                if nxt is not None and not nxt[3]:
                    findings.append(Finding(
                        "GL603", self.fn.module.path, nxt[0], nxt[1],
                        f"{arg.id!r} was passed in donated position {pos} "
                        f"of {name}() on line {call.lineno} and is read "
                        "again here — the donated buffer is deleted by the "
                        "call (runtime 'Array has been deleted'); rebind "
                        "or stop donating",
                    ))
        return findings


def check_use_after_donation(project) -> list[Finding]:
    registry = _DonatingRegistry(project)
    if not registry.donate:
        return []
    graph = callgraph.build(project)
    findings: list[Finding] = []
    for fn in graph.funcs:
        findings.extend(_LivenessScan(registry, fn).run())
    return findings


register_project_checker("GL6", check_use_after_donation)

"""GL3xx recompile-hazard: jit wrappers that bypass the compile cache.

The engine's dispatch cost model assumes every (shape, cap-class) combo
traces ONCE — `BatchEngine._seen_combos` records what has compiled, and
`precompile_combos` replays the manifest so live traffic never pays a
mid-stream trace. All of that is defeated by Python patterns that mint a
*fresh* jit wrapper (or a fresh closure identity) per call: each wrapper
has its own trace cache, so the ~0.3-1s host trace cost comes back as an
invisible per-call latency cliff. The rules:

  GL301  `@jax.jit` def nested inside a function that is not an
         `functools.lru_cache`/`functools.cache` factory — every call of
         the enclosing function builds (and traces) a brand-new callable.
         The sanctioned idiom is the cached factory
         (`engine/frames.py:_scatter_grid_fn`).
  GL302  `jax.jit(f)(...)` called immediately inside a function body —
         the wrapper is born, traced, and discarded per call.
  GL303  a list/dict/set literal passed in a static position of a jit
         call (static args must be hashable; this raises at call time —
         or, for the dict-in-closure variant, silently keys the cache on
         object identity).
  GL304  `@jax.jit` on an instance method (`self` is hashed by object
         identity: every instance re-traces, and the cache pins the
         instance alive) — use a free function over explicit arrays, or
         `functools.partial(jax.jit, static_argnums=0)` over a frozen
         config like `engine/step.py`.
"""

from __future__ import annotations

import ast

from .core import Finding, register_checker, register_rules
from .trace_safety import _dotted, _is_jit_expr, _is_partial, _jit_spec

register_rules({
    "GL301": "@jax.jit def inside an uncached factory re-traces per call",
    "GL302": "jax.jit(f)(...) immediate call mints a fresh trace cache",
    "GL303": "unhashable literal in a static argument position of a jit call",
    "GL304": "@jax.jit on an instance method keys the cache on `self` identity",
})


def _is_cached_factory(node) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        d = _dotted(target) or ""
        if d.rsplit(".", 1)[-1] in ("lru_cache", "cache"):
            return True
    return False


def _jit_decorated(node) -> bool:
    return any(_jit_spec(dec)[2] for dec in node.decorator_list)


class _Visitor(ast.NodeVisitor):
    def __init__(self, module):
        self.module = module
        self.findings: list[Finding] = []
        # stack of (function node, is_cached_factory)
        self._stack: list[tuple[ast.AST, bool]] = []
        self._cls_depth = 0

    def _report(self, rule: str, node, msg: str) -> None:
        self.findings.append(Finding(
            rule, self.module.path, node.lineno, node.col_offset, msg))

    def visit_ClassDef(self, node):
        self._cls_depth += 1
        stack, self._stack = self._stack, []
        self.generic_visit(node)
        self._stack = stack
        self._cls_depth -= 1

    def _visit_func(self, node):
        if _jit_decorated(node):
            in_func = bool(self._stack)
            if in_func and not any(c for _, c in self._stack):
                self._report(
                    "GL301", node,
                    f"`@jax.jit` def {node.name}() nested in an uncached "
                    "function: a fresh wrapper (and trace) per enclosing "
                    "call — wrap the factory in functools.lru_cache "
                    "(engine/frames.py:_scatter_grid_fn is the idiom)",
                )
            params = node.args.posonlyargs + node.args.args
            if not in_func and self._cls_depth and params and \
                    params[0].arg in ("self", "cls"):
                self._report(
                    "GL304", node,
                    f"`@jax.jit` on method {node.name}(): the cache keys on "
                    "`self` identity — every instance re-traces and is "
                    "pinned alive by the cache",
                )
        self._stack.append((node, _is_cached_factory(node)))
        cls_depth, self._cls_depth = self._cls_depth, 0
        self.generic_visit(node)
        self._cls_depth = cls_depth
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node)

    def visit_Call(self, node):
        # GL302: (jax.jit(...))(args) inside a function body
        if self._stack and isinstance(node.func, ast.Call):
            inner = node.func
            if _is_jit_expr(inner.func) and inner.args:
                # jit(f)(x): exempt the module-scope wrapper-def idiom
                # (we are inside a function here by construction)
                self._report(
                    "GL302", node,
                    "jax.jit(f) called immediately: the wrapper's trace "
                    "cache dies with the expression — hoist the jitted "
                    "callable to module scope or an lru_cache factory",
                )
            if isinstance(inner.func, ast.Call) and \
                    _is_partial(inner.func.func) and inner.func.args and \
                    _is_jit_expr(inner.func.args[0]):
                self._report(
                    "GL302", node,
                    "functools.partial(jax.jit, ...)(f) called immediately: "
                    "fresh wrapper per call — hoist it",
                )
        # GL303: unhashable literals in static positions
        self._check_static_args(node)
        self.generic_visit(node)

    def _check_static_args(self, node: ast.Call) -> None:
        """jit(..., static_argnums=...) called inline with literal
        list/dict/set args in static positions."""
        func = node.func
        if not isinstance(func, ast.Call):
            return
        nums, names, is_jit = _jit_spec(func)
        if not is_jit:
            return
        for i in nums:
            if i < len(node.args) and isinstance(
                    node.args[i], (ast.List, ast.Dict, ast.Set)):
                self._report(
                    "GL303", node.args[i],
                    f"static arg {i} is an unhashable "
                    f"{type(node.args[i]).__name__.lower()} literal — static "
                    "args must be hashable (tuple / frozen dataclass)",
                )
        for kw in node.keywords:
            if kw.arg in names and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set)):
                self._report(
                    "GL303", kw.value,
                    f"static arg {kw.arg!r} is an unhashable literal — "
                    "static args must be hashable",
                )


def check(module) -> list[Finding]:
    v = _Visitor(module)
    v.visit(module.tree)
    return v.findings


register_checker("GL3", check)

"""gomelint core: findings, rule registry, suppressions, and the runner.

A *checker* is a function ``check(module: SourceModule) -> list[Finding]``
registered in :data:`CHECKERS`. Checkers are pure AST passes; the jaxpr
(abstract-eval) envelope checks are driven separately by the CLI because
they need to import and trace the engine (analysis.envelope).

Suppression syntax (mirrors the familiar ``# noqa`` shape but namespaced,
so ruff/flake8 never eat our directives and vice versa):

  * line:  ``x = float(v)  # gomelint: disable=GL101`` — suppresses the
           listed rules (comma-separated) on that physical line; ``all``
           suppresses every rule. The justification convention is a
           trailing `` — why`` clause after the rule list.
  * file:  ``# gomelint: disable-file=GL104`` anywhere in the file.

Suppressed findings are dropped at collection time; ``--show-suppressed``
in the CLI resurfaces them for audits.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable

#: Tool version (CLI --version, SARIF tool.driver.version, baseline
#: provenance). Bump on rule-semantics changes: a fingerprint computed by
#: one major version may legitimately churn under the next. 2.1.0:
#: occurrence indices are file-scoped (cross-file duplicate keys no
#: longer renumber each other) and the GL8xx sharding family exists.
#: 2.2.0: the GL9xx compile-surface family (and its combo-universe
#: manifest) exists.
TOOL_VERSION = "2.2.0"

#: rule id -> one-line description (the catalogue; checkers register into
#: this at import time so the CLI's --list-rules stays complete).
ALL_RULES: dict[str, str] = {}


def register_rules(rules: dict[str, str]) -> None:
    ALL_RULES.update(rules)


def rule_catalogue() -> dict[str, str]:
    return dict(sorted(ALL_RULES.items()))


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # stable id, e.g. "GL101"
    path: str  # file path as given to the runner
    line: int  # 1-based
    col: int  # 0-based
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


_DIRECTIVE = re.compile(r"#\s*gomelint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\s]+)")


def _parse_rules(blob: str) -> set[str]:
    return {r.strip().upper() for r in blob.split(",") if r.strip()}


class SourceModule:
    """One parsed source file plus its suppression tables."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.line_disable: dict[int, set[str]] = {}
        self.file_disable: set[str] = set()
        for i, line in enumerate(self.lines, 1):
            m = _DIRECTIVE.search(line)
            if not m:
                continue
            rules = _parse_rules(m.group(2))
            if m.group(1) == "disable-file":
                self.file_disable |= rules
            else:
                self.line_disable.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        rule = rule.upper()
        for table in (self.file_disable, self.line_disable.get(line, ())):
            if rule in table or "ALL" in table:
                return True
        return False

    # -- comment helpers (annotation-driven checkers) ----------------------
    def line_comment(self, line: int) -> str:
        """The comment tail of one physical line ('' when none). A '#'
        inside a string literal can false-positive here; annotation
        directives are short ASCII tails, so in practice the regexes the
        checkers apply to this are unambiguous."""
        if not 1 <= line <= len(self.lines):
            return ""
        text = self.lines[line - 1]
        idx = text.find("#")
        return text[idx:] if idx >= 0 else ""


#: A module checker: fn(module) -> findings.
Checker = Callable[[SourceModule], list[Finding]]
#: A project checker: fn(project) -> findings.
ProjectChecker = Callable[["Project"], list[Finding]]

#: registered checkers: (family, fn). Family is the id prefix ("GL1") used
#: by --select; fn(module) -> findings.
CHECKERS: list[tuple[str, Checker]] = []


def register_checker(family: str, fn: Checker) -> None:
    CHECKERS.append((family, fn))


#: project-scope checkers: (family, fn); fn(project) -> findings. These see
#: EVERY module of the run at once — the interprocedural passes (hot-path
#: reachability, donation call-site liveness) need the whole-package call
#: graph, which no single-module pass can build.
PROJECT_CHECKERS: list[tuple[str, ProjectChecker]] = []


def register_project_checker(family: str, fn: ProjectChecker) -> None:
    PROJECT_CHECKERS.append((family, fn))


class Project:
    """One analysis run's worth of parsed modules plus per-module
    suppression routing for project-scope findings."""

    def __init__(self, modules: list[SourceModule]):
        self.modules = list(modules)
        self.by_path = {m.path: m for m in self.modules}

    def suppressed(self, rule: str, path: str, line: int) -> bool:
        m = self.by_path.get(path)
        return m is not None and m.suppressed(rule, line)


def _selected(rule: str, select: set[str] | None) -> bool:
    if not select:
        return True
    return any(rule.upper().startswith(s) for s in select)


def _collect(module: SourceModule, select: set[str] | None,
             keep_suppressed: bool = False) -> list[Finding]:
    out: list[Finding] = []
    for family, fn in CHECKERS:
        if select and not any(s.startswith(family) or family.startswith(s)
                              for s in select):
            continue
        for f in fn(module):
            if not _selected(f.rule, select):
                continue
            if not keep_suppressed and module.suppressed(f.rule, f.line):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _ensure_checkers_loaded() -> None:
    # Import-time registration; local imports avoid a hard cycle.
    from . import (  # noqa: F401
        donation,
        locks,
        recompile,
        sharding,
        surface,
        threads,
        trace_safety,
        transfers,
    )


def _run_project(modules: list[SourceModule], select: set[str] | None,
                 keep_suppressed: bool) -> list[Finding]:
    """Module checkers per module + project checkers over the whole set."""
    findings: list[Finding] = []
    for module in modules:
        findings.extend(_collect(module, select, keep_suppressed))
    project = Project(modules)
    for family, fn in PROJECT_CHECKERS:
        if select and not any(s.startswith(family) or family.startswith(s)
                              for s in select):
            continue
        for f in fn(project):
            if not _selected(f.rule, select):
                continue
            if not keep_suppressed and project.suppressed(f.rule, f.path,
                                                          f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def run_source(text: str, path: str = "<memory>",
               select: set[str] | None = None,
               keep_suppressed: bool = False) -> list[Finding]:
    """Analyze one source string (golden-fixture tests use this)."""
    return run_sources({path: text}, select, keep_suppressed)


def run_sources(sources: dict[str, str], select: set[str] | None = None,
                keep_suppressed: bool = False) -> list[Finding]:
    """Analyze a set of in-memory modules as ONE project — the fixture
    surface for the interprocedural passes (cross-module hot-path
    reachability needs at least two modules to mean anything)."""
    _ensure_checkers_loaded()
    sel = {s.upper() for s in select} if select else None
    modules = [SourceModule(path, text) for path, text in sources.items()]
    return _run_project(modules, sel, keep_suppressed)


def iter_python_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d not in ("__pycache__", ".git", ".ruff_cache")
            )
            for name in sorted(files):
                if name.endswith(".py") and name != "order_pb2.py":
                    # order_pb2 is protoc output; generated code answers to
                    # protoc, not to this linter.
                    out.append(os.path.join(root, name))
    return out


def apply_file_suppressions(findings: list[Finding],
                            root: str = "") -> list[Finding]:
    """Drop findings silenced by ``# gomelint: disable`` directives in
    their anchor files. The jaxpr-driven audits (GL2xx/GL6xx) produce
    findings outside the module-checker pipeline, so the CLI routes them
    through this to honor the same suppression syntax."""
    cache: dict[str, SourceModule | None] = {}
    out: list[Finding] = []
    for f in findings:
        path = f.path
        if root and not os.path.isabs(path):
            path = os.path.join(root, path)
        if path not in cache:
            try:
                with open(path, encoding="utf-8") as fh:
                    cache[path] = SourceModule(path, fh.read())
            except (OSError, SyntaxError):
                cache[path] = None
        mod = cache[path]
        if mod is not None and mod.suppressed(f.rule, f.line):
            continue
        out.append(f)
    return out


def run_paths(paths: list[str], select: set[str] | None = None,
              keep_suppressed: bool = False) -> list[Finding]:
    """Analyze files/directories as one project; returns sorted findings."""
    _ensure_checkers_loaded()
    sel = {s.upper() for s in select} if select else None
    findings: list[Finding] = []
    modules: list[SourceModule] = []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        try:
            modules.append(SourceModule(path, text))
        except SyntaxError as e:
            findings.append(Finding(
                "GL000", path, e.lineno or 1, e.offset or 0,
                f"syntax error: {e.msg}",
            ))
    findings.extend(_run_project(modules, sel, keep_suppressed))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


register_rules({"GL000": "file does not parse (syntax error)"})

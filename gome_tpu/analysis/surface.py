"""GL9xx compile-surface analysis: statically bound the jit combo universe.

ROADMAP item 3 demands *zero recompiles at steady state* for an elastic
symbol universe. The PR 5 compile journal OBSERVES that property; nothing
proved it. Two things make it provable: every combo-key dimension is the
output of a small quantizer lattice (pow2/pow4 rounding, the cap ladder,
grow-only buffer floors), and every site that builds / replays / persists
the nine-dimension dispatch combo agrees field-for-field. Both are kept
true by convention alone today — this family makes them machine-checked:

  GL901  data-derived int feeding a jit shape factory or a combo-key
         dimension without passing a registered quantizer. Quantizers
         are declared with a ``# gomesurface: quantizer`` annotation on
         the def (``_next_pow2``, ``_cap_ladder``, ``_buf_class``, ...);
         taint starts at per-frame/per-order reductions (``len()``,
         ``.max()``, ``.sum()``, ``np.count_nonzero``) in hot-path
         functions (the PR 4 callgraph) and an unquantized value
         reaching a shape sink is an unbounded compile surface.
  GL902  combo-key drift: the tuple built in the ``combo(build)`` site
         must agree in arity, order, and per-field provenance with the
         ``COMBO_FIELDS`` declaration, every ``combo(replay)`` unpack,
         and the ``combo(persist)`` manifest writer — adding a dimension
         in one site without the others is a finding, not a silent
         precompile no-op. Any ``_seen_combos`` reach-through outside
         ``engine/batch.py`` is also GL902: ``BatchEngine.record_combo``
         is the single writer the contract hangs off.
  GL903  a jit/pallas entry dispatched on the hot path that no
         ``# gomesurface: precompile`` replay site reaches — its first
         dispatch pays a trace+compile mid-traffic instead of at boot.
  GL904  ``reset_geometry_floors()`` / ``_seen_combos.clear()`` reachable
         from a ``# gomelint: hotpath`` seed — dropping the grow-only
         geometry ratchets mid-traffic re-mints shapes (a recompile
         storm); resets belong in warmup/maintenance code.
  GL905  combo-universe drift: the per-dimension value sets enumerated
         from config bounds + the quantizer lattice
         (``combo_universe.json``, line-number-free like
         ``shard_manifest.json``) differ from the committed manifest —
         review and regenerate with ``--update-universe``, never
         silently absorb.
  GL906  runtime escape: a compile-journal export (soak / chaos /
         obs_snapshot artifact) contains an observed dispatch combo
         outside the predicted universe — the static bound and the
         runtime behavior disagree, and one of them is wrong.

Annotation grammar (comma-separable, on the def line, a decorator line,
or the line immediately above — same placement as ``gomelint: hotpath``):

    # gomesurface: quantizer          output is on the shape lattice
    # gomesurface: combo(build)       builds the dispatch combo tuple
    # gomesurface: combo(replay)      unpacks recorded combos
    # gomesurface: combo(persist)     persists the recorded combo set
    # gomesurface: precompile         the boot-time replay entry point

Conventions the structural checks key on (documented limits): the build
tuple and the replay unpacks bind a variable named ``combo``; the
``COMBO_FIELDS`` declaration is a module-level tuple of field-name
strings. GL901's taint is per-function and lexical (like GL5xx):
parameters and attribute loads start clean, ``min``/``max``/``int`` and
arithmetic propagate, a quantizer call launders. Shape sinks are calls
of ``lru_cache``-wrapped jit factories (the GL301-blessed shape
specialization pattern) and the combo tuple itself.

GL901–GL904 are pure AST over the project call graph and ride the normal
checker pipeline; GL905 needs an engine import (the CLI gates it behind
``--jaxpr``, sharing CI's one traced run); GL906 is pure JSON — it checks
a journal artifact against the *committed* universe, so it runs anywhere.
"""

from __future__ import annotations

import ast
import json
import math
import os
import re
from typing import Iterable, TypeVar

from . import callgraph
from .core import (
    TOOL_VERSION,
    Finding,
    Project,
    SourceModule,
    register_project_checker,
    register_rules,
)
from .trace_safety import _dotted

register_rules({
    "GL901": "data-derived int reaches a jit shape sink without passing "
             "a registered quantizer (unbounded compile surface)",
    "GL902": "combo-key drift: build/replay/persist sites disagree with "
             "COMBO_FIELDS (or a _seen_combos reach-through bypasses the "
             "record_combo chokepoint)",
    "GL903": "hot-path jit/pallas entry not reachable from any "
             "`# gomesurface: precompile` boot-time replay site",
    "GL904": "geometry-ratchet reset (reset_geometry_floors / "
             "_seen_combos.clear) reachable from a hotpath seed "
             "(recompile-storm hazard)",
    "GL905": "combo-universe drift — dimension bounds changed without "
             "--update-universe",
    "GL906": "runtime escape: compile-journal combo outside the "
             "predicted combo universe",
})

#: Committed universe manifest location, relative to the repo root
#: (mirrors sharding.DEFAULT_MANIFEST).
DEFAULT_UNIVERSE = os.path.join("gome_tpu", "analysis",
                                "combo_universe.json")

_SURFACE_RE = re.compile(r"#\s*gomesurface:\s*([a-z(),\s-]+)")
_MARKER_RE = re.compile(r"([a-z-]+)(?:\(([a-z-]+)\))?")

#: Reductions over per-frame/per-order data: the GL901 taint sources.
_REDUCTIONS = frozenset({
    "max", "min", "sum", "item", "argmax", "argmin", "nonzero",
    "count_nonzero", "bincount", "prod",
})
#: Builtins that merely COMBINE operand values (clamps): taint of the
#: result is the join of the operands, never fresh.
_COMBINERS = frozenset({"min", "max", "abs", "int", "round"})

#: Per-field provenance tokens for the GL902 build-site check: element i
#: of the build tuple must mention one of field i's tokens. Unlisted
#: fields accept their own name only.
_FIELD_ALIASES: dict[str, tuple[str, ...]] = {
    "n_rows": ("n_rows", "rows"),
    "t_grid": ("t_grid",),
    "cap_g": ("cap_g", "cap"),
    "dense": ("dense", "lane_ids"),
    "m_pad": ("m_pad", "_m_pad"),
    "k_rec": ("k_rec",),
    "e_fills": ("e_fills", "fills_acc", "fills"),
    "e_cancels": ("e_cancels", "cancels_acc", "cancels"),
    "totals_len": ("totals_len", "totals_acc", "totals"),
}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


_NodeT = TypeVar("_NodeT", bound=ast.AST)


def _own_nodes(scope: ast.AST, types: type[_NodeT]) -> list[_NodeT]:
    """Nodes of the given type belonging to `scope` itself — recursing
    through control flow but NOT into nested defs/lambdas/classes."""
    out: list[_NodeT] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            if isinstance(child, types):
                out.append(child)
            walk(child)

    walk(scope)
    return out


def _markers(module: SourceModule,
             node: ast.stmt) -> set[tuple[str, str | None]]:
    """gomesurface markers on a def: ``{("quantizer", None),
    ("combo", "replay"), ("precompile", None), ...}``."""
    lines = [node.lineno]
    first = node.lineno
    for dec in getattr(node, "decorator_list", ()):
        lines.append(dec.lineno)
        first = min(first, dec.lineno)
    lines.append(first - 1)
    out: set[tuple[str, str | None]] = set()
    for ln in lines:
        m = _SURFACE_RE.search(module.line_comment(ln))
        if not m:
            continue
        for mm in _MARKER_RE.finditer(m.group(1)):
            out.add((mm.group(1), mm.group(2)))
    return out


def _leaf(node: ast.expr) -> str:
    return (_dotted(node) or "").rsplit(".", 1)[-1]


def _mentions_token(text: str, tokens: tuple[str, ...]) -> bool:
    return any(
        re.search(rf"(?<![A-Za-z0-9_]){re.escape(t)}(?![A-Za-z0-9_])", text)
        for t in tokens
    )


class _Surface:
    """One project's compile-surface index: annotated quantizers, combo
    sites, precompile replay entries, jit shape factories, and the
    COMBO_FIELDS declaration."""

    def __init__(self, project: Project):
        self.project = project
        self.graph = callgraph.build(project)
        self.quantizers: set[str] = set()
        self.build_fns: list[callgraph.FuncNode] = []
        self.replay_fns: list[callgraph.FuncNode] = []
        self.persist_fns: list[callgraph.FuncNode] = []
        self.precompile_fns: list[callgraph.FuncNode] = []
        self.fields: tuple[str, ...] | None = None
        self.fields_site: tuple[SourceModule, int] | None = None
        by_arg = {"build": self.build_fns, "replay": self.replay_fns,
                  "persist": self.persist_fns}
        for fn in self.graph.funcs:
            if isinstance(fn.node, ast.Lambda):
                continue
            for name, arg in _markers(fn.module, fn.node):
                if name == "quantizer":
                    self.quantizers.add(fn.name)
                elif name == "combo" and arg is not None and arg in by_arg:
                    by_arg[arg].append(fn)
                elif name == "precompile":
                    self.precompile_fns.append(fn)
        for module in project.modules:
            for node in module.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Name)
                        and tgt.id == "COMBO_FIELDS"
                        and isinstance(node.value, (ast.Tuple, ast.List))
                        and all(isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                                for e in node.value.elts)):
                    continue
                self.fields = tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                )
                self.fields_site = (module, node.lineno)
        # jit shape factories: an lru_cache-wrapped def whose body defines
        # a jitted inner function — the GL301-blessed shape-specialization
        # pattern. Their positional args ARE compile-shape parameters.
        inner_jitted = {
            f.enclosing for f in self.graph.funcs
            if f.jitted and f.enclosing is not None
        }
        self.factories: list[callgraph.FuncNode] = []
        for fn in self.graph.funcs:
            decs = getattr(fn.node, "decorator_list", None) or ()
            cached = any(
                _leaf(d.func if isinstance(d, ast.Call) else d)
                in ("lru_cache", "cache")
                for d in decs
            )
            if cached and fn in inner_jitted:
                self.factories.append(fn)
        self.factory_names = {f.name for f in self.factories}

    def aliases(self, field: str) -> tuple[str, ...]:
        return _FIELD_ALIASES.get(field, (field,))


# --- GL901: quantizer-lattice taint ---------------------------------------

class _TaintScan:
    """Per-function lexical taint: raw = derived from per-frame/per-order
    data by a reduction and not yet laundered through a quantizer. Flags
    raw values reaching a shape sink (jit factory arg, combo dimension).
    Single forward pass, parameters/attributes start clean — the same
    underreport-over-noise contract as GL5xx."""

    def __init__(self, surface: _Surface, fn: callgraph.FuncNode,
                 is_build: bool):
        self.s = surface
        self.fn = fn
        self.is_build = is_build
        self.raw: set[str] = set()
        self.qaliases: set[str] = set()
        self.findings: list[Finding] = []

    # -- expression taint --------------------------------------------------
    def t(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.raw
        if isinstance(node, ast.Call):
            return self._t_call(node)
        if isinstance(node, ast.BinOp):
            return self.t(node.left) or self.t(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.t(node.operand)
        if isinstance(node, ast.IfExp):
            return self.t(node.body) or self.t(node.orelse)
        if isinstance(node, ast.BoolOp):
            return any(self.t(v) for v in node.values)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.t(e) for e in node.elts)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.t(node.value)
        # Compare -> bool (cardinality 2, always bounded); Attribute ->
        # buffer shapes and config are lattice values by construction.
        return False

    def _t_call(self, node: ast.Call) -> bool:
        leaf = _leaf(node.func)
        if leaf in self.s.quantizers or leaf in self.qaliases:
            return False  # laundered onto the lattice
        if isinstance(node.func, ast.Name) and leaf in _COMBINERS:
            return any(self.t(a) for a in node.args)
        if leaf == "len":
            return True
        if isinstance(node.func, ast.Attribute) and leaf in _REDUCTIONS:
            return True  # x.max(), counts.sum(), ...
        root = (_dotted(node.func) or "").split(".", 1)[0]
        if root in ("np", "numpy", "jnp", "jax") and leaf in _REDUCTIONS:
            return True
        return False

    def _is_quant_ref(self, node: ast.AST) -> bool:
        """A VALUE that is (an alias of) a quantizer function itself —
        ``bucket = _next_pow2 if first else _next_pow4``."""
        if isinstance(node, ast.Name):
            return node.id in self.s.quantizers or node.id in self.qaliases
        if isinstance(node, ast.Attribute):
            return node.attr in self.s.quantizers
        if isinstance(node, ast.IfExp):
            return (self._is_quant_ref(node.body)
                    and self._is_quant_ref(node.orelse))
        return False

    # -- sinks -------------------------------------------------------------
    def _report(self, node: ast.expr, what: str) -> None:
        self.findings.append(Finding(
            "GL901", self.fn.module.path, node.lineno, node.col_offset,
            f"data-derived int reaches {what} without passing a "
            "registered quantizer — every distinct value is a fresh jit "
            "trace+compile (unbounded compile surface); round it through "
            "a `# gomesurface: quantizer` function "
            f"[in {self.fn.qualname}]",
        ))

    def _check_combo_tuple(self, tup: ast.Tuple) -> None:
        fields = self.s.fields or ()
        for i, el in enumerate(tup.elts):
            if self.t(el):
                dim = (f"combo dimension {fields[i]!r}" if i < len(fields)
                       else f"combo dimension #{i}")
                self._report(el, dim)

    def _check_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            leaf = _leaf(node.func)
            if leaf in self.s.factory_names:
                for i, a in enumerate(node.args):
                    if self.t(a):
                        self._report(
                            a, f"shape argument #{i} of jit factory "
                               f"{leaf}()")
            elif leaf == "record_combo":
                for a in node.args:
                    if isinstance(a, ast.Tuple):
                        self._check_combo_tuple(a)
                    elif self.t(a):
                        self._report(a, "a recorded combo")

    # -- statements --------------------------------------------------------
    def _assign(self, targets: list[ast.expr], value: ast.expr) -> None:
        only = targets[0] if len(targets) == 1 else None
        if isinstance(only, ast.Name) and self._is_quant_ref(value):
            self.qaliases.add(only.id)
            self.raw.discard(only.id)
            return
        if (isinstance(only, (ast.Tuple, ast.List))
                and isinstance(value, (ast.Tuple, ast.List))
                and len(only.elts) == len(value.elts)):
            for tgt, val in zip(only.elts, value.elts):
                self._assign([tgt], val)
            return
        raw = self.t(value)
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    (self.raw.add if raw else self.raw.discard)(n.id)
                    self.qaliases.discard(n.id)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, _SCOPE_NODES):
            return  # nested scopes are their own FuncNodes
        if isinstance(node, ast.Assign):
            self._check_expr(node.value)
            self._assign(node.targets, node.value)
            if self.is_build and isinstance(node.value, ast.Tuple) \
                    and any(isinstance(t, ast.Name) and t.id == "combo"
                            for t in node.targets):
                self._check_combo_tuple(node.value)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._check_expr(node.value)
                self._assign([node.target], node.value)
            return
        if isinstance(node, ast.AugAssign):
            self._check_expr(node.value)
            if isinstance(node.target, ast.Name) and self.t(node.value):
                self.raw.add(node.target.id)
            return
        if isinstance(node, ast.For):
            self._check_expr(node.iter)
            if self.t(node.iter):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        self.raw.add(n.id)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._check_expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._check_expr(item.context_expr)
            for s in node.body:
                self._stmt(s)
            return
        if isinstance(node, ast.Try):
            for s in (node.body + node.orelse + node.finalbody
                      + [s for h in node.handlers for s in h.body]):
                self._stmt(s)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._check_expr(child)

    def run(self) -> list[Finding]:
        node = self.fn.node
        if isinstance(node, ast.Lambda):
            return []
        for stmt in node.body:
            self._stmt(stmt)
        return self.findings


def _check_gl901(surface: _Surface) -> list[Finding]:
    build = set(surface.build_fns)
    scan = [fn for fn in surface.graph.funcs
            if (fn in build or (fn.hot and not fn.jitted))
            and not isinstance(fn.node, ast.Lambda)]
    findings: list[Finding] = []
    for fn in scan:
        findings.extend(_TaintScan(surface, fn, fn in build).run())
    return findings


# --- GL902: combo-key site agreement --------------------------------------

def _build_tuple(fn: callgraph.FuncNode,
                 arity: int | None) -> ast.Tuple | None:
    """The combo build tuple: an Assign of a Tuple literal to a Name
    ``combo`` (the convention), else any Tuple Assign of matching arity."""
    fallback: ast.Tuple | None = None
    for node in _own_nodes(fn.node, ast.Assign):
        if not isinstance(node.value, ast.Tuple):
            continue
        if any(isinstance(t, ast.Name) and t.id == "combo"
               for t in node.targets):
            return node.value
        if arity is not None and len(node.value.elts) == arity \
                and fallback is None:
            fallback = node.value
    return fallback


def _unpack_sites(fn: callgraph.FuncNode) -> list[tuple[ast.Assign,
                                                        tuple[str, ...]]]:
    """Tuple-unpacks of a plain Name — ``(a, b, ...) = combo`` — in the
    replay site. The conventional ``combo`` source wins; other Name
    sources are ignored (a replay fn unpacks other pairs too)."""
    out: list[tuple[ast.Assign, tuple[str, ...]]] = []
    for node in _own_nodes(fn.node, ast.Assign):
        tgt = node.targets[0] if len(node.targets) == 1 else None
        if (isinstance(tgt, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Name) for e in tgt.elts)
                and isinstance(node.value, ast.Name)
                and node.value.id == "combo"):
            names = tuple(e.id for e in tgt.elts
                          if isinstance(e, ast.Name))
            out.append((node, names))
    return out


def _check_gl902(surface: _Surface) -> list[Finding]:
    s = surface
    out: list[Finding] = []
    # The chokepoint contract: the recorded-combo set has ONE owner.
    for module in s.project.modules:
        path = module.path.replace(os.sep, "/")
        if path.endswith("engine/batch.py"):
            continue
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "_seen_combos":
                out.append(Finding(
                    "GL902", module.path, node.lineno, node.col_offset,
                    "_seen_combos reach-through — go through the "
                    "BatchEngine chokepoint (record_combo / combo_seen / "
                    "combo_count / combos); a private reader or writer "
                    "forks the combo bookkeeping the zero-recompile "
                    "steady-state contract audits",
                ))
    sites = s.build_fns + s.replay_fns + s.persist_fns
    if s.fields is None:
        for fn in sites:
            out.append(Finding(
                "GL902", fn.module.path, fn.node.lineno,
                fn.node.col_offset,
                f"{fn.qualname} is a combo site but no module declares "
                "COMBO_FIELDS (a module-level tuple of field-name "
                "strings) — the site-agreement check has no spine",
            ))
        return out
    fields = s.fields
    assert s.fields_site is not None  # set together with s.fields
    decl_mod, decl_line = s.fields_site

    def decl(msg: str) -> None:
        out.append(Finding("GL902", decl_mod.path, decl_line, 0, msg))

    if len(set(fields)) != len(fields):
        decl("COMBO_FIELDS repeats a field name — every dimension needs "
             "a distinct identity for the universe manifest")
    missing = [role for role, fns in (("build", s.build_fns),
                                      ("replay", s.replay_fns),
                                      ("persist", s.persist_fns))
               if not fns]
    if missing:
        decl(f"COMBO_FIELDS is declared but no `# gomesurface: "
             f"combo({'/'.join(missing)})` site is annotated — the "
             "agreement check cannot see every side of the contract")
    for fn in s.build_fns:
        tup = _build_tuple(fn, len(fields))
        if tup is None:
            out.append(Finding(
                "GL902", fn.module.path, fn.node.lineno,
                fn.node.col_offset,
                f"combo(build) site {fn.qualname} builds no combo tuple "
                "literal (convention: `combo = (...)`)",
            ))
            continue
        if len(tup.elts) != len(fields):
            out.append(Finding(
                "GL902", fn.module.path, tup.lineno, tup.col_offset,
                f"combo tuple has {len(tup.elts)} element(s) but "
                f"COMBO_FIELDS declares {len(fields)} — a dimension was "
                "added/removed in one site only; update every "
                "build/replay/persist site together",
            ))
            continue
        for i, el in enumerate(tup.elts):
            try:
                text = ast.unparse(el)
            except Exception:  # pragma: no cover - synthetic trees
                continue
            if not _mentions_token(text, s.aliases(fields[i])):
                out.append(Finding(
                    "GL902", fn.module.path, el.lineno, el.col_offset,
                    f"combo element #{i} ({text}) does not mention "
                    f"{fields[i]!r}'s provenance "
                    f"({', '.join(s.aliases(fields[i]))}) — field order "
                    "drifted between the build tuple and COMBO_FIELDS",
                ))
    for fn in s.replay_fns:
        unpacks = _unpack_sites(fn)
        if not unpacks:
            out.append(Finding(
                "GL902", fn.module.path, fn.node.lineno,
                fn.node.col_offset,
                f"combo(replay) site {fn.qualname} has no combo unpack "
                "(convention: `(f0, f1, ...) = combo`)",
            ))
        for node, names in unpacks:
            if names != fields:
                out.append(Finding(
                    "GL902", fn.module.path, node.lineno,
                    node.col_offset,
                    f"replay unpack binds ({', '.join(names)}) but "
                    f"COMBO_FIELDS declares ({', '.join(fields)}) — "
                    "arity/order/name drift makes the precompile replay "
                    "a silent no-op for the drifted dimension",
                ))
        for node in _own_nodes(fn.node, ast.Subscript):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "combo"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, int)
                    and not (-len(fields) <= node.slice.value
                             < len(fields))):
                out.append(Finding(
                    "GL902", fn.module.path, node.lineno,
                    node.col_offset,
                    f"combo[{node.slice.value}] is outside the "
                    f"{len(fields)}-field combo layout",
                ))
    for fn in s.persist_fns:
        touches = any(
            isinstance(n, ast.Attribute)
            and n.attr in ("combos", "_seen_combos")
            for n in ast.walk(fn.node)
        )
        if not touches:
            out.append(Finding(
                "GL902", fn.module.path, fn.node.lineno,
                fn.node.col_offset,
                f"combo(persist) site {fn.qualname} never reads the "
                "recorded combo set (BatchEngine.combos()) — the "
                "manifest it writes cannot carry the dispatched shapes",
            ))
    return out


# --- GL903: precompile-replay coverage ------------------------------------

def _check_gl903(surface: _Surface) -> list[Finding]:
    s = surface
    if not s.precompile_fns and s.fields is None:
        return []  # no replay system declared: nothing to register into
    factories = set(s.factories)
    entries = [fn for fn in s.graph.funcs
               if fn.hot and (fn.jitted or fn in factories)]
    covered: set[callgraph.FuncNode] = set(s.precompile_fns)
    work = list(covered)
    while work:
        fn = work.pop()
        for nxt in s.graph.edges.get(fn, ()):
            if nxt not in covered:
                covered.add(nxt)
                work.append(nxt)
    out: list[Finding] = []
    for fn in entries:
        if fn in covered:
            continue
        kind = "jit factory" if fn in factories else "jit/pallas entry"
        out.append(Finding(
            "GL903", fn.module.path, fn.node.lineno, fn.node.col_offset,
            f"{kind} {fn.qualname} is dispatched on the hot path but no "
            "`# gomesurface: precompile` replay site reaches it — its "
            "first dispatch pays the trace+compile mid-traffic; replay "
            "it from precompile_combos (or annotate the replay site)",
        ))
    return out


# --- GL904: hot-path geometry resets --------------------------------------

def _check_gl904(surface: _Surface) -> list[Finding]:
    out: list[Finding] = []
    for fn in surface.graph.hot_functions():
        for call in _own_nodes(fn.node, ast.Call):
            leaf = _leaf(call.func)
            is_reset = leaf == "reset_geometry_floors"
            is_clear = (
                leaf == "clear" and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Attribute)
                and call.func.value.attr == "_seen_combos"
            )
            if not (is_reset or is_clear):
                continue
            what = ("reset_geometry_floors()" if is_reset
                    else "_seen_combos.clear()")
            out.append(Finding(
                "GL904", fn.module.path, call.lineno, call.col_offset,
                f"{what} is reachable from a hotpath seed — dropping the "
                "grow-only geometry ratchets mid-traffic re-mints every "
                "shape (a trace+compile per combo, the recompile storm "
                "the ratchets exist to prevent); keep resets in "
                f"warmup/maintenance code [in {fn.qualname}]",
            ))
    return out


def check_surface(project: Project) -> list[Finding]:
    surface = _Surface(project)
    out = _check_gl901(surface)
    out.extend(_check_gl902(surface))
    out.extend(_check_gl903(surface))
    out.extend(_check_gl904(surface))
    return out


register_project_checker("GL9", check_surface)


# --- GL905: the combo universe (extract / save / drift ratchet) -----------

def _pow_span(base: int, lo: int, hi: int) -> int:
    """How many powers of `base` lie in [lo, hi] (lo/hi are powers)."""
    count = 0
    v = lo
    while v <= hi:
        count += 1
        v *= base
    return count


def _pow_dim(kind: str, lo: int, hi: int, generator: str) -> dict:
    base = 2 if kind == "pow2" else 4
    return dict(kind=kind, min=lo, max=hi,
                cardinality=_pow_span(base, lo, hi), generator=generator)


def extract_universe() -> dict:
    """Enumerate every combo dimension's value set from the engine's
    config bounds + the quantizer lattice. Deterministic for a given
    tree: no line numbers, no timestamps — the same diff-clean contract
    as the sharding manifest. Imports the engine (the CLI gates this
    behind --jaxpr, riding CI's one traced run)."""
    import inspect

    from ..engine import frames as eng_frames
    from ..engine.batch import CAP_CLASS_MIN, BatchEngine

    # signature() of the class follows __init__ for us; referencing the
    # dunder directly would hand the name-matched call graph an edge to
    # EVERY __init__ in the tree, polluting thread-reach verdicts when
    # the linter analyzes itself.
    sig = inspect.signature(BatchEngine)

    def default(name: str) -> int:
        return int(sig.parameters[name].default)

    max_slots = default("max_slots")
    max_cap = default("max_cap")
    dense_t_max = default("dense_t_max")
    max_t = default("max_t")
    max_ops = int(eng_frames.MAX_FRAME_OPS)
    fields = list(eng_frames.COMBO_FIELDS)

    def pow2_ceil(n: int) -> int:
        return 1 << max(n - 1, 0).bit_length()

    def pow4_ceil(n: int) -> int:
        v = 1
        while v < n:
            v *= 4
        return v

    t_cap = pow2_ceil(max(dense_t_max, max_t))
    dims = {
        "n_rows": _pow_dim(
            "pow2", 8, max_slots,
            "_grid_geometry: pow2/pow4 live-lane buckets with the "
            "grow-only rows floor; full grid = n_slots (pow2 "
            "deployments); 8 = the Pallas sublane floor"),
        "t_grid": _pow_dim(
            "pow2", 8, t_cap,
            "_pack_class_train: _next_pow2(need) clamped to [t_floor, "
            "cap_t]; tail grids snap to {max_t, 8*max_t, cap_t//4, "
            "cap_t}; full grid = max_t; cap_t <= "
            "_next_pow2(max(dense_t_max, max_t))"),
        "cap_g": _pow_dim(
            "pow2", 1, max_cap,
            "_cap_ladder: pow4 classes from CAP_CLASS_MIN plus the "
            "pow2-snapped storage cap (ensure_cap grow-only)"),
        "dense": dict(
            kind="enum", values=[False, True], cardinality=2,
            generator="lane_ids is not None — compact gather/scatter "
                      "grid vs the full [n_slots, max_t] grid"),
        "m_pad": _pow_dim(
            "pow4", 64, pow4_ceil(max_ops),
            "_next_pow4(max(m, 64)) of the grid's packed-op count, "
            "m <= MAX_FRAME_OPS"),
        "k_rec": dict(
            kind="bounded", min=1, max=max_cap, cardinality=max_cap,
            generator="min(config.max_fills, cap) — the step clamps the "
                      "record axis to the cap class (step.py rec); one "
                      "value per engine config per cap class"),
        "e_fills": _pow_dim(
            "pow2", 64, pow2_ceil(max_ops) * max_cap,
            "_compact_sizes/_buf_class pow2 op-class + the grow-only "
            "fills floor; overflow ratchets to _next_pow2(total fills), "
            "total <= MAX_FRAME_OPS * k_rec, k_rec <= max_cap"),
        "e_cancels": _pow_dim(
            "pow2", 64, pow2_ceil(max_ops),
            "_next_pow2(max(frame DEL count, 64)) with the grow-only "
            "cancels floor; DELs <= MAX_FRAME_OPS"),
        "totals_len": _pow_dim(
            "pow2", 8, pow2_ceil(max_ops),
            "_next_pow2(max(len(grids), 8)); a frame cannot pack more "
            "grids than it has ops"),
    }
    missing = [f for f in fields if f not in dims]
    for f in missing:
        # A NEW dimension lands here as an explicit hole: the drift
        # check turns it into a GL905 finding until the generator above
        # is written and --update-universe reviewed.
        dims[f] = dict(kind="unbounded", cardinality=0,
                       generator="UNKNOWN — no generator declared for "
                                 "this dimension")
    dims = {f: dims[f] for f in fields}
    log2_total = round(sum(
        math.log2(d["cardinality"]) for d in dims.values()
        if d.get("cardinality")
    ), 2)
    return dict(
        version=1,
        tool=f"gomelint {TOOL_VERSION}",
        note="Per-dimension value sets of the frame-dispatch combo key, "
             "derived from engine config bounds + the `# gomesurface: "
             "quantizer` lattice. CI fails on drift (GL905); regenerate "
             "with scripts/gomelint.py --jaxpr --update-universe and "
             "review the diff like any compile-surface change. GL906 "
             "checks runtime compile-journal exports against this file.",
        fields=fields,
        bounds=dict(
            max_slots=max_slots, max_cap=max_cap,
            dense_t_max=dense_t_max, max_t=max_t,
            cap_class_min=int(CAP_CLASS_MIN), max_frame_ops=max_ops,
        ),
        cardinality_log2_bound=log2_total,
        dimensions=dims,
    )


def save_universe(path: str, universe: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(universe, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_universe(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def check_universe(path: str | None = None) -> list[Finding]:
    """GL905 drift ratchet: the extracted universe must equal the
    committed one dimension-for-dimension. Findings anchor on the
    manifest file so the fix-it action (--update-universe + review) is
    unambiguous."""
    root = _repo_root()
    if path is None:
        path = os.path.join(root, DEFAULT_UNIVERSE)
    rel = os.path.relpath(path, root) if os.path.isabs(path) else path
    committed = load_universe(path)
    if committed is None:
        return [Finding(
            "GL905", rel, 1, 0,
            "no committed combo universe — run scripts/gomelint.py "
            "--jaxpr --update-universe and commit the file",
        )]
    current = extract_universe()
    findings: list[Finding] = []
    for key in ("fields", "bounds"):
        if current.get(key) != committed.get(key):
            findings.append(Finding(
                "GL905", rel, 1, 0,
                f"{key} changed vs the committed universe "
                f"({committed.get(key)} -> {current.get(key)}) — review "
                "the compile-surface change and regenerate with "
                "--update-universe",
            ))
    cur = current.get("dimensions", {})
    com = committed.get("dimensions", {})
    for dim in sorted(set(cur) | set(com)):
        if dim not in com:
            what = "dimension is new (not in the committed universe)"
        elif dim not in cur:
            what = "dimension vanished from the extraction but is still " \
                   "committed"
        elif cur[dim] != com[dim]:
            changed = sorted(
                k for k in set(cur[dim]) | set(com[dim])
                if cur[dim].get(k) != com[dim].get(k)
            )
            what = f"{', '.join(changed)} changed vs the committed " \
                   "universe"
        else:
            continue
        findings.append(Finding(
            "GL905", rel, 1, 0,
            f"{dim}: {what} — review the bound change and regenerate "
            "with --update-universe",
        ))
    return findings


# --- GL906: runtime escape (journal vs universe) --------------------------

def _is_pow2(v: int) -> bool:
    return v > 0 and (v & (v - 1)) == 0


def _dim_contains(spec: dict, value: object) -> bool:
    kind = spec.get("kind")
    if kind == "enum":
        return any(value == v for v in spec.get("values", ()))
    if not isinstance(value, int) or isinstance(value, bool):
        return False
    lo, hi = spec.get("min", 0), spec.get("max", 0)
    if not int(lo) <= value <= int(hi):
        return False
    if kind == "pow2":
        return _is_pow2(value)
    if kind == "pow4":
        return _is_pow2(value) and (value.bit_length() - 1) % 2 == 0
    return kind == "bounded"


def combo_escapes(combo: Iterable[object], universe: dict) -> list[str]:
    """The ways one observed combo falls outside the universe ([] =
    inside). The in-process half of GL906 — tests and the witness drill
    call this directly."""
    fields = universe.get("fields") or []
    dims = universe.get("dimensions", {})
    values = tuple(combo)
    if len(values) != len(fields):
        return [f"arity {len(values)} != the {len(fields)} declared "
                "fields"]
    out: list[str] = []
    for name, value in zip(fields, values):
        spec = dims.get(name)
        if spec is None or not _dim_contains(spec, value):
            kind = (spec or {}).get("kind", "missing")
            bound = (f"[{spec.get('min')}..{spec.get('max')}]"
                     if spec and "min" in spec
                     else repr((spec or {}).get("values", "?")))
            out.append(f"{name}={value!r} outside {kind} {bound}")
    return out


def _journal_entries(doc: object) -> list:
    """Accept every journal wire form we ship: a CompileJournal.export()
    / as_dict() payload, the ops /cost payload (obs_snapshot cost.json),
    or a bare entries list."""
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if isinstance(doc.get("entries"), list):
            return doc["entries"]
        for key in ("compile_journal", "journal"):
            inner = doc.get(key)
            if isinstance(inner, dict) \
                    and isinstance(inner.get("entries"), list):
                return inner["entries"]
    return []


def journal_escapes(entries: Iterable[object],
                    universe: dict) -> list[tuple[tuple, list[str]]]:
    """Distinct frame-dispatch combos in a journal export that fall
    outside the universe, with the per-dimension violations."""
    seen: set[tuple] = set()
    out: list[tuple[tuple, list[str]]] = []
    for e in entries:
        if not isinstance(e, dict) or e.get("entry") != "frame_dispatch":
            continue
        key = e.get("key")
        if not isinstance(key, (list, tuple)):
            continue
        combo = tuple(key)
        if combo in seen:
            continue
        seen.add(combo)
        violations = combo_escapes(combo, universe)
        if violations:
            out.append((combo, violations))
    return out


def check_journal_escape(journal_path: str,
                         universe_path: str | None = None) -> list[Finding]:
    """GL906: every observed compile-journal combo must lie inside the
    committed universe. Pure JSON (no engine import): artifacts from a
    soak, a chaos run, or obs_snapshot check anywhere the committed
    manifest is."""
    root = _repo_root()
    if universe_path is None:
        universe_path = os.path.join(root, DEFAULT_UNIVERSE)
    rel = (os.path.relpath(journal_path, root)
           if os.path.isabs(journal_path) else journal_path)
    universe = load_universe(universe_path)
    if universe is None:
        urel = (os.path.relpath(universe_path, root)
                if os.path.isabs(universe_path) else universe_path)
        return [Finding(
            "GL906", urel, 1, 0,
            "no committed combo universe to check the journal against — "
            "run scripts/gomelint.py --jaxpr --update-universe",
        )]
    try:
        with open(journal_path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        return [Finding(
            "GL906", rel, 1, 0, f"compile-journal export unreadable: {e}",
        )]
    findings: list[Finding] = []
    for combo, violations in journal_escapes(_journal_entries(doc),
                                             universe):
        findings.append(Finding(
            "GL906", rel, 1, 0,
            f"observed dispatch combo {tuple(combo)} escapes the "
            f"predicted universe: {'; '.join(violations)} — either a "
            "quantizer regressed (the runtime minted an off-lattice "
            "shape) or the universe bounds are stale "
            "(--update-universe after review)",
        ))
    return findings

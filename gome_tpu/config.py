"""Typed YAML configuration — the framework's equivalent of the reference's
config system (gomengine/util/conf.go:3-30 + config.yaml.example).

Reference parity: the same four YAML sections are accepted with the same keys
(`grpc`, `redis`, `rabbitmq`, `gomengine.accuracy` — conf.go:3-30; the dead
`mysql` block of config.yaml.example:16-21 is ignored here too). Differences,
deliberate (SURVEY §5.6 called out every weakness we fix):

  * one explicit `load_config()` call instead of four independent package
    `init()`s reading a CWD-relative path with errors ignored
    (engine.go:30-33, grpc/grpc.go:19-22, redis/redis.go:12-15);
  * validation with loud errors instead of silent zero-values;
  * new sections for what the TPU engine adds: `engine` (book geometry,
    micro-batch shape), `bus` (queue backend selection), `persist`
    (snapshot cadence/location). All have working defaults so a reference
    config.yaml loads unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import TYPE_CHECKING, Any, TypeVar

if TYPE_CHECKING:
    from .engine.book import BookConfig
    from .sim.env import EnvConfig
    from .utils.faults import FaultPlan

import yaml

from .fixed import DEFAULT_ACCURACY


@dataclasses.dataclass(frozen=True)
class GrpcConfig:
    """conf.go:24-27 (GRPC{host, port})."""

    host: str = "127.0.0.1"
    port: int = 8088


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """conf.go:11-15 (Cache = the Redis durability tier). In the TPU build
    Redis is optional (snapshots can target the local filesystem instead);
    `enabled` gates it so environments without a Redis server still run
    (the reference hard-requires Redis because Redis IS its book)."""

    host: str = "127.0.0.1"
    port: int = 6379
    password: str = ""
    enabled: bool = False


@dataclasses.dataclass(frozen=True)
class BusConfig:
    """conf.go:17-22 (RabbitMQ) generalized: the queue topology (two named
    queues, "doOrder" inbound / "matchOrder" outbound — rabbitmq.go:60-84)
    is preserved; the transport is pluggable (gome_tpu.bus backends):
      memory — in-process deques (single-binary deployments, tests)
      file   — durable append-only log segments (crash-safe, replayable)
      cfile  — the same log format via the native C++ runtime library
               (batch-amortized fsync; falls back to `file` if no toolchain)
      amqp   — external RabbitMQ via the built-in dependency-free AMQP
               0-9-1 client (bus/amqp.py); boots on the memory backend
               with a loud warning when no broker is listening
    """

    backend: str = "memory"
    dir: str = "bus_data"
    host: str = "127.0.0.1"
    port: int = 5672
    username: str = ""
    password: str = ""
    order_queue: str = "doOrder"  # rabbitmq.go: queue names
    match_queue: str = "matchOrder"
    # matchOrder payload: "json" = one reference-shape document per event
    # (rabbitmq.go parity); "frame" = one binary EVENT frame per batch
    # (bus.colwire, the high-throughput internal transport).
    match_wire: str = "json"

    _BACKENDS = ("memory", "file", "cfile", "amqp")

    def __post_init__(self) -> None:
        if self.backend not in self._BACKENDS:
            raise ValueError(
                f"bus.backend must be one of {self._BACKENDS}, "
                f"got {self.backend!r}"
            )
        if self.match_wire not in ("json", "frame"):
            raise ValueError(
                f"bus.match_wire must be json|frame, got {self.match_wire!r}"
            )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """The reference's single semantic knob (`gomengine.accuracy`,
    conf.go:29-30) plus the TPU engine's geometry: book capacity per side,
    fill-record budget, provisioned symbol lanes, micro-batch depth."""

    accuracy: int = DEFAULT_ACCURACY
    cap: int = 256
    max_fills: int = 16
    n_slots: int = 1024
    max_t: int = 32
    dtype: str = "int64"  # "int32" halves HBM traffic when ranges allow
    auto_grow: bool = True
    kernel: str = "scan"  # scan (XLA) | pallas (VMEM-resident TPU kernel)
    # Cross-frame pipelining depth for ORDER-frame traffic (0 = synchronous;
    # N > 0 keeps up to N frames in flight on the device while the host
    # packs the next — engine.pipeline.FramePipeline).
    pipeline_depth: int = 0
    # Shard the lane axis over the first N local devices as a 1-D
    # jax.sharding.Mesh (gome_tpu.parallel.make_mesh): per-chip Pallas
    # under shard_map, zero-collective dense grids (SURVEY §5.8). 0 = no
    # mesh (single chip). n_slots must be a multiple of mesh_devices.
    mesh_devices: int = 0

    def __post_init__(self) -> None:
        if not 0 <= self.accuracy <= 18:
            raise ValueError(f"accuracy must be in [0, 18], got {self.accuracy}")
        for name in ("cap", "max_fills", "n_slots", "max_t"):
            v = getattr(self, name)
            if v <= 0:
                raise ValueError(f"engine.{name} must be positive, got {v}")
        if self.pipeline_depth < 0:
            raise ValueError(
                f"engine.pipeline_depth must be >= 0, got {self.pipeline_depth}"
            )
        if self.dtype not in ("int32", "int64"):
            raise ValueError(f"engine.dtype must be int32|int64, got {self.dtype}")
        from .types import KERNELS

        if self.kernel not in KERNELS:
            raise ValueError(
                f"engine.kernel must be one of {KERNELS}, got {self.kernel}"
            )

    def book_config(self) -> "BookConfig":
        import jax.numpy as jnp

        from .engine.book import BookConfig

        return BookConfig(
            cap=self.cap,
            max_fills=self.max_fills,
            dtype=jnp.int32 if self.dtype == "int32" else jnp.int64,
        )


@dataclasses.dataclass(frozen=True)
class PersistConfig:
    """Snapshot/recovery cadence (new — the reference needs none because
    every Redis write is instantly durable, SURVEY §5.4). `enabled` defaults
    off; a `persist:` section in config.yaml switches it on (like `redis:`
    implies store.enabled)."""

    enabled: bool = False
    dir: str = "snapshots"
    every_n_batches: int = 64
    keep: int = 4

    def __post_init__(self) -> None:
        if self.every_n_batches <= 0 or self.keep <= 0:
            raise ValueError("persist cadence/keep must be positive")


@dataclasses.dataclass(frozen=True)
class OpsConfig:
    """Operator HTTP endpoint (/metrics Prometheus text + /healthz JSON +
    /trace Chrome trace-event dump) — an extension beyond the reference
    (which has logging only, SURVEY §5.5). Disabled unless an `ops:`
    section appears in config.yaml.

    trace/trace_keep/slow_ms configure the order-lifecycle tracer
    (utils.trace): with trace on, every order gets a trace id at the
    gateway and the flight recorder keeps the last `trace_keep` complete
    journeys plus every journey slower than `slow_ms` end to end.

    cost/cost_keep configure the device cost surface (gome_tpu.obs): with
    cost on, the compile journal is armed (gome_compile_seconds metrics +
    the /cost endpoint's journal section) keeping the last `cost_keep`
    compile events.

    timeline/timeline_interval_s/timeline_keep configure the host-side
    timeline sampler (gome_tpu.obs.timeline): with timeline on, the
    sampler is armed at boot and runs every `timeline_interval_s` seconds
    on a daemon thread while the service is started, keeping the last
    `timeline_keep` samples behind the /timeline endpoint and the
    gome_timeline_* gauges.

    profile/profile_keep configure the measured-roofline profiler
    (gome_tpu.obs.profiler): with profile on, the PROFILER singleton is
    armed at boot — per-shard dispatch telemetry records on the dense
    mesh path, and the /profile endpoint captures a bounded
    jax.profiler window on demand (first hit or ?refresh=1), keeping the
    last `profile_keep` measured reports behind the gome_profile_*
    gauges. Captures are seconds of work; they run only when asked,
    never on the dispatch path.

    hostprof/hostprof_hz/hostprof_keep configure the host-CPU sampling
    profiler (gome_tpu.obs.hostprof): with hostprof on, the HOSTPROF
    singleton is armed at boot and its thread-mode wall sampler runs
    while the service is started, sampling every hostprof_hz-th of a
    second with a `hostprof_keep`-deep raw-stack ring, behind the
    /hostprof endpoint and the gome_hostprof_* gauges. The admit drill
    (the measured per-stage gateway breakdown) runs only on demand
    (?drill=1), never on the serving path.

    placement/placement_topk/placement_alpha/placement_partitions
    configure the placement observatory (gome_tpu.obs.placement): with
    placement on, the PLACEMENT singleton is armed at boot — the
    gateway admit hooks feed a `placement_topk`-deep Space-Saving
    heavy-hitter sketch, the dense-dispatch hook keeps the occupancy
    ledger + per-lane EWMA rates (smoothing `placement_alpha`), and the
    skew-attribution rows compute the what-if hash imbalance over
    `placement_partitions` partitions — all behind the /placement
    endpoint and the gome_placement_* gauges. A committed
    PLACEMENT_r01.json verdict at the repo root rides the payload when
    present."""

    host: str = "127.0.0.1"
    port: int = 9109
    enabled: bool = False
    trace: bool = True  # arm the order-lifecycle tracer with the endpoint
    trace_keep: int = 64  # flight-recorder ring size (journeys)
    slow_ms: float = 50.0  # slow-order threshold (pinned in the slow ring)
    cost: bool = True  # arm the compile journal with the endpoint
    cost_keep: int = 256  # compile-journal ring size (events)
    timeline: bool = True  # arm the host-side timeline sampler
    timeline_interval_s: float = 1.0  # sampling period (seconds)
    timeline_keep: int = 512  # timeline ring size (samples)
    profile: bool = True  # arm the measured-roofline profiler
    profile_keep: int = 8  # profiler report ring size (captures)
    hostprof: bool = True  # arm the host-CPU sampling profiler
    hostprof_hz: float = 67.0  # live wall-sampler cadence (Hz)
    hostprof_keep: int = 4096  # raw-stack ring size (samples)
    placement: bool = True  # arm the placement observatory
    placement_topk: int = 64  # Space-Saving sketch capacity (symbols)
    placement_alpha: float = 0.2  # per-lane EWMA smoothing factor
    placement_partitions: int = 8  # what-if hash-imbalance partitions

    def __post_init__(self) -> None:
        if self.trace_keep <= 0:
            raise ValueError(
                f"ops.trace_keep must be positive, got {self.trace_keep}"
            )
        if self.slow_ms < 0:
            raise ValueError(
                f"ops.slow_ms must be >= 0, got {self.slow_ms}"
            )
        if self.cost_keep <= 0:
            raise ValueError(
                f"ops.cost_keep must be positive, got {self.cost_keep}"
            )
        if self.timeline_interval_s <= 0:
            raise ValueError(
                f"ops.timeline_interval_s must be positive, got "
                f"{self.timeline_interval_s}"
            )
        if self.timeline_keep <= 0:
            raise ValueError(
                f"ops.timeline_keep must be positive, got "
                f"{self.timeline_keep}"
            )
        if self.profile_keep <= 0:
            raise ValueError(
                f"ops.profile_keep must be positive, got "
                f"{self.profile_keep}"
            )
        if self.hostprof_hz <= 0:
            raise ValueError(
                f"ops.hostprof_hz must be positive, got "
                f"{self.hostprof_hz}"
            )
        if self.hostprof_keep <= 0:
            raise ValueError(
                f"ops.hostprof_keep must be positive, got "
                f"{self.hostprof_keep}"
            )
        if self.placement_topk <= 0:
            raise ValueError(
                f"ops.placement_topk must be positive, got "
                f"{self.placement_topk}"
            )
        if not (0.0 < self.placement_alpha <= 1.0):
            raise ValueError(
                f"ops.placement_alpha must be in (0, 1], got "
                f"{self.placement_alpha}"
            )
        if self.placement_partitions <= 0:
            raise ValueError(
                f"ops.placement_partitions must be positive, got "
                f"{self.placement_partitions}"
            )


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet aggregation (gome_tpu.obs.fleet) — this process polls the
    listed member processes' ops endpoints and serves the merged view
    under its own ops server's /fleet. Disabled unless a `fleet:`
    section appears in config.yaml (requires `ops:` too — the merged
    view needs an HTTP surface to live on). `members` is a YAML list of
    "name=http://host:port" strings (or {name: url} mappings)."""

    enabled: bool = False
    members: Any = ()  # "name=url" strings or {name: url} dicts
    interval_s: float = 1.0  # poll period (seconds)
    timeout_s: float = 2.0  # per-endpoint fetch timeout (seconds)

    def __post_init__(self) -> None:
        if self.interval_s <= 0:
            raise ValueError(
                f"fleet.interval_s must be positive, got {self.interval_s}"
            )
        if self.timeout_s <= 0:
            raise ValueError(
                f"fleet.timeout_s must be positive, got {self.timeout_s}"
            )
        if self.enabled and not self.members:
            raise ValueError("fleet: enabled but no members listed")
        self.member_map()  # malformed entries fail at load, not at poll

    def member_map(self) -> dict[str, str]:
        """{member name: base URL} from the YAML-friendly `members`
        forms; names must be unique (they become the `proc` label)."""
        out: dict[str, str] = {}
        for entry in self.members or ():
            if isinstance(entry, dict):
                items = list(entry.items())
            elif isinstance(entry, str) and "=" in entry:
                items = [tuple(entry.split("=", 1))]
            else:
                raise ValueError(
                    f"fleet.members entries must be 'name=url' or "
                    f"{{name: url}}, got {entry!r}"
                )
            for name, url in items:
                if name in out:
                    raise ValueError(f"fleet.members: duplicate name {name!r}")
                out[str(name)] = str(url)
        return out


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """The on-device market simulator (gome_tpu.sim): Hawkes/Zipf flow
    parameters + environment geometry. New — the reference has no
    simulator; bench.py's `--flow sim` and the RL environment read this
    section. Scalars only so the block stays YAML-friendly; the derived
    excitation matrix lives in sim.flow.FlowConfig."""

    n_lanes: int = 256
    t_bins: int = 32
    dt: float = 0.02
    submit_rate: float = 2.0
    cancel_rate: float = 1.4
    market_rate: float = 0.6
    excite_self: float = 0.25
    excite_cross: float = 0.10
    excite_kind: float = 0.05
    decay: float = 2.0
    zipf_a: float = 1.1
    offset_p: float = 0.35
    max_offset: int = 200
    ref_price: int = 100_000
    ref_spread: int = 20
    vol_max: int = 100
    n_uids: int = 256
    seed: int = 0
    # Environment geometry (sim.env.EnvConfig).
    cap: int = 16
    max_fills: int = 4
    dtype: str = "int32"
    n_agent_ops: int = 2
    obs_levels: int = 4

    def __post_init__(self) -> None:
        for name in ("n_lanes", "t_bins", "max_offset", "ref_price",
                     "ref_spread", "vol_max", "n_uids", "cap", "max_fills",
                     "n_agent_ops", "obs_levels"):
            if getattr(self, name) <= 0:
                raise ValueError(
                    f"sim.{name} must be positive, got {getattr(self, name)}"
                )
        if self.dt <= 0 or self.decay <= 0:
            raise ValueError("sim.dt and sim.decay must be positive")
        if self.dtype not in ("int32", "int64"):
            raise ValueError(
                f"sim.dtype must be int32|int64, got {self.dtype}"
            )
        # The structured excitation matrix's Perron eigenvector is the
        # all-ones vector, so the spectral radius has this closed form
        # (sim.flow.FlowConfig re-checks the general eigenvalue bound).
        br = self.excite_self + self.excite_cross + 4 * self.excite_kind
        if br >= 1.0:
            raise ValueError(
                f"sim Hawkes parameters are unstable: branching ratio "
                f"{br:.3f} >= 1 (lower excite_* or raise decay)"
            )

    def env_config(self) -> "EnvConfig":
        """Build the sim.env.EnvConfig (imports jax — call lazily)."""
        import jax.numpy as jnp

        from .engine.book import BookConfig
        from .sim.env import EnvConfig
        from .sim.flow import FlowConfig

        flow = FlowConfig(
            n_lanes=self.n_lanes, t_bins=self.t_bins, dt=self.dt,
            submit_rate=self.submit_rate, cancel_rate=self.cancel_rate,
            market_rate=self.market_rate, excite_self=self.excite_self,
            excite_cross=self.excite_cross, excite_kind=self.excite_kind,
            decay=self.decay, zipf_a=self.zipf_a, offset_p=self.offset_p,
            max_offset=self.max_offset, ref_price=self.ref_price,
            ref_spread=self.ref_spread, vol_max=self.vol_max,
            n_uids=self.n_uids,
        )
        book = BookConfig(
            cap=self.cap, max_fills=self.max_fills,
            dtype=jnp.int32 if self.dtype == "int32" else jnp.int64,
        )
        return EnvConfig(
            flow=flow, book=book, n_agent_ops=self.n_agent_ops,
            obs_levels=self.obs_levels,
        )


@dataclasses.dataclass(frozen=True)
class FaultsConfig:
    """Deterministic fault injection (utils.faults) — chaos/test tooling
    only; production configs omit the section and the FAULTS singleton
    stays a zero-allocation no-op. A `faults:` block arms the registry at
    EngineService boot so a fault *plan* (seed + schedule) travels with
    the config as a reproducible artifact. Give either `plan` (path to a
    FaultPlan JSON written by scripts/chaos.py) or `points` (inline list
    of FaultSpec dicts, YAML-friendly), not both."""

    enabled: bool = False
    seed: int = 0
    plan: str = ""  # path to a FaultPlan JSON file
    # Inline FaultSpec dicts straight from YAML; validated when the plan
    # is built (FaultSpec.from_dict), not here, so config loading stays
    # import-light.
    points: Any = ()

    def __post_init__(self) -> None:
        if self.plan and self.points:
            raise ValueError(
                "faults: give plan (file) or points (inline), not both"
            )

    def fault_plan(self) -> "FaultPlan":
        """Materialize the schedule (reads the plan file when given)."""
        from .utils.faults import FaultPlan

        if self.plan:
            with open(self.plan) as f:
                return FaultPlan.from_json(f.read())
        return FaultPlan.from_dict(
            {"seed": self.seed, "faults": list(self.points)}
        )


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Gateway admission control (service.admission) — depth/deadline
    load shedding with retryable status + retry-after hint (round 12).
    Off by default: without an `admission:` section the gateway admits
    unconditionally, exactly the pre-round-12 behavior."""

    enabled: bool = False
    #: shed (code 14) once order-queue consumer lag reaches this many
    #: orders — bounds worst-case queueing delay at max_depth/drain-rate.
    max_depth: int = 16384
    #: shed requests whose remaining gRPC deadline is below this (s);
    #: 0 disables the deadline check.
    min_deadline_s: float = 0.0
    #: retry-after hint at the ceiling (s); scales with overshoot.
    retry_after_s: float = 0.05
    retry_after_max_s: float = 2.0
    #: consumer-lag sample cache window (s) — admission is per-RPC.
    cache_s: float = 0.005

    def __post_init__(self) -> None:
        if self.max_depth < 1:
            raise ValueError("admission.max_depth must be >= 1")
        if self.min_deadline_s < 0:
            raise ValueError("admission.min_deadline_s must be >= 0")
        if self.retry_after_s <= 0:
            raise ValueError("admission.retry_after_s must be positive")
        if self.retry_after_max_s < self.retry_after_s:
            raise ValueError(
                "admission.retry_after_max_s must be >= retry_after_s"
            )


@dataclasses.dataclass(frozen=True)
class Config:
    grpc: GrpcConfig = GrpcConfig()
    store: StoreConfig = StoreConfig()
    bus: BusConfig = BusConfig()
    engine: EngineConfig = EngineConfig()
    persist: PersistConfig = PersistConfig()
    ops: OpsConfig = OpsConfig()
    fleet: FleetConfig = FleetConfig()
    sim: SimConfig = SimConfig()
    faults: FaultsConfig = FaultsConfig()
    admission: AdmissionConfig = AdmissionConfig()


_C = TypeVar("_C")


def _build(cls: type[_C], raw: dict[str, Any], section: str) -> _C:
    fields = {f.name: f for f in dataclasses.fields(cls)}  # type: ignore[arg-type]
    kwargs = {}
    for key, value in raw.items():
        if key not in fields:
            raise ValueError(f"unknown key {section}.{key}")
        ftype = fields[key].type
        # YAML strings for numeric fields (the reference's conf.go keeps
        # ports as strings) are coerced here.
        if ftype in (int, "int") and isinstance(value, str):
            value = int(value)
        kwargs[key] = value
    return cls(**kwargs)


def load_config(path: str | None = None) -> Config:
    """Load config from a YAML file; missing file ⇒ all defaults (unlike the
    reference, which silently zeroes every field on a missing config.yaml).
    Reference-shaped files load unchanged: `redis`/`rabbitmq` sections map to
    store/bus, `gomengine.accuracy` to engine.accuracy."""
    raw: dict[str, Any] = {}
    if path is not None:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
    elif os.path.exists("config.yaml"):
        with open("config.yaml") as f:
            raw = yaml.safe_load(f) or {}

    grpc_raw = raw.get("grpc", {}) or {}
    store_raw = dict(raw.get("redis", {}) or {})
    if store_raw:
        store_raw.setdefault("enabled", True)
    bus_raw = dict(raw.get("rabbitmq", {}) or {})
    if bus_raw:
        bus_raw.setdefault("backend", "amqp")
    bus_raw.update(raw.get("bus", {}) or {})
    engine_raw = dict(raw.get("gomengine", {}) or {})
    engine_raw.update(raw.get("engine", {}) or {})
    persist_raw = dict(raw.get("persist", {}) or {})
    if persist_raw:
        persist_raw.setdefault("enabled", True)
    ops_raw = dict(raw.get("ops", {}) or {})
    if ops_raw:
        ops_raw.setdefault("enabled", True)
    fleet_raw = dict(raw.get("fleet", {}) or {})
    if fleet_raw:
        fleet_raw.setdefault("enabled", True)
    sim_raw = dict(raw.get("sim", {}) or {})
    faults_raw = dict(raw.get("faults", {}) or {})
    if faults_raw:
        faults_raw.setdefault("enabled", True)
    admission_raw = dict(raw.get("admission", {}) or {})
    if admission_raw:
        admission_raw.setdefault("enabled", True)
    raw.pop("mysql", None)  # dead section, config.yaml.example:16-21

    known = {
        "grpc", "redis", "rabbitmq", "bus", "gomengine", "engine",
        "persist", "ops", "fleet", "sim", "faults", "admission",
    }
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown config sections: {sorted(unknown)}")

    return Config(
        grpc=_build(GrpcConfig, grpc_raw, "grpc"),
        store=_build(StoreConfig, store_raw, "redis"),
        bus=_build(BusConfig, bus_raw, "bus"),
        engine=_build(EngineConfig, engine_raw, "engine"),
        persist=_build(PersistConfig, persist_raw, "persist"),
        ops=_build(OpsConfig, ops_raw, "ops"),
        fleet=_build(FleetConfig, fleet_raw, "fleet"),
        sim=_build(SimConfig, sim_raw, "sim"),
        faults=_build(FaultsConfig, faults_raw, "faults"),
        admission=_build(AdmissionConfig, admission_raw, "admission"),
    )

"""On-device market simulator (ROADMAP item 3 — the RL/simulation
workload of JAX-LOB, arXiv:2308.13289, driven by the Hawkes order-flow
model of arXiv:2510.08085).

Layout:

  flow.py   — Hawkes/Zipf order-flow generator emitting engine op grids
              entirely inside jit (no host materialization)
  env.py    — gym-style vectorized environment over the stacked books
              (`reset`/`step`/`rollout`, one compiled call per step)
  replay.py — seeded deterministic replay manifests + GCO record mode
  stats.py  — host-side empirical diagnostics (Zipf fit, branching
              ratio, clustering) for statistical assertions
"""

from .env import (
    AgentAction,
    EnvConfig,
    EnvState,
    MarketEnv,
    Obs,
    StepInfo,
    env_reset,
    env_step,
    null_action,
    rollout,
)
from .flow import (
    N_EVENT_TYPES,
    FlowConfig,
    FlowState,
    flow_init,
    gen_ops,
    gen_ops_jit,
)
from .replay import (
    grid_to_columns,
    make_manifest,
    orders_from_grid,
    record_frames,
    run_from_manifest,
)

__all__ = [
    "AgentAction",
    "EnvConfig",
    "EnvState",
    "FlowConfig",
    "FlowState",
    "MarketEnv",
    "N_EVENT_TYPES",
    "Obs",
    "StepInfo",
    "env_reset",
    "env_step",
    "flow_init",
    "gen_ops",
    "gen_ops_jit",
    "grid_to_columns",
    "make_manifest",
    "null_action",
    "orders_from_grid",
    "record_frames",
    "rollout",
    "run_from_manifest",
]

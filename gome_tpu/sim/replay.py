"""Deterministic seeded replay: manifests, digests, and GCO record mode.

A run manifest is `(seed, config, n_steps)` plus a canonical-JSON
config hash — enough to regenerate a flow bit-exactly in any process
(the generator is a pure function of the PRNG key and static config;
XLA CPU/TPU executables are deterministic for this integer program).
`run_from_manifest` replays one and folds the whole trade stream + final
book state into a sha256 digest, so two processes can assert bit-exact
equality without shipping trajectories around.

Record mode dumps each step's generated background grid as a GCO ORDER
frame (bus.colwire) — the exact wire form the service path consumes —
so a sim run can be re-fed through gateway→bus→consumer for cross-stack
validation (tests/test_sim.py does, via engine.frames.orders_from_frame
+ MatchEngine admission).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.book import BookConfig
from .env import EnvConfig, _env_step_impl, env_reset, null_action, rollout
from .flow import FlowConfig, gen_ops

MANIFEST_VERSION = 1


# -- manifest ---------------------------------------------------------------

def config_dict(config: EnvConfig) -> dict:
    """JSON-able canonical form of an EnvConfig (dtype by name)."""
    return {
        "flow": dataclasses.asdict(config.flow),
        "book": {
            "cap": config.book.cap,
            "max_fills": config.book.max_fills,
            "dtype": np.dtype(config.book.dtype).name,
        },
        "n_agent_ops": config.n_agent_ops,
        "obs_levels": config.obs_levels,
        "agent_uid": config.agent_uid,
    }


def config_digest(config: EnvConfig) -> str:
    blob = json.dumps(
        config_dict(config), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def make_manifest(config: EnvConfig, seed: int, n_steps: int) -> dict:
    """The (seed, config hash, step count) record that pins one run."""
    return {
        "version": MANIFEST_VERSION,
        "seed": int(seed),
        "n_steps": int(n_steps),
        "config": config_dict(config),
        "config_sha256": config_digest(config),
    }


def env_config_from_manifest(manifest: dict) -> EnvConfig:
    """Rebuild the EnvConfig and verify the manifest's config hash (a
    hand-edited manifest must fail loudly, not replay something else)."""
    if manifest.get("version") != MANIFEST_VERSION:
        raise ValueError(
            f"unsupported sim manifest version {manifest.get('version')!r}"
        )
    c = manifest["config"]
    config = EnvConfig(
        flow=FlowConfig(**c["flow"]),
        book=BookConfig(
            cap=c["book"]["cap"],
            max_fills=c["book"]["max_fills"],
            dtype=jnp.dtype(c["book"]["dtype"]),
        ),
        n_agent_ops=c["n_agent_ops"],
        obs_levels=c["obs_levels"],
        agent_uid=c["agent_uid"],
    )
    digest = config_digest(config)
    if digest != manifest["config_sha256"]:
        raise ValueError(
            f"sim manifest config hash mismatch: manifest says "
            f"{manifest['config_sha256'][:12]}…, config rebuilds to "
            f"{digest[:12]}…"
        )
    return config


def run_from_manifest(manifest: dict) -> dict:  # gomelint: hotpath
    """Replay a manifest (background flow only) and digest the result.

    The digest folds the per-step fill-stream checksums (env.StepInfo)
    and every leaf of the final book state — any divergence anywhere in
    the trade sequence or book evolution changes it. One compiled scan,
    one device fetch at the end."""
    config = env_config_from_manifest(manifest)
    state, _ = env_reset(config, jax.random.PRNGKey(manifest["seed"]))
    final, (_rewards, info) = rollout(config, state, manifest["n_steps"])
    checks, trades, events, b_over, f_over = jax.device_get(
        (info.checksum, info.trades, info.events, info.book_overflow,
         info.fill_overflow)
    )
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(checks).tobytes())
    for leaf in jax.device_get(final.books):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return {
        "digest": h.hexdigest(),
        "n_steps": int(manifest["n_steps"]),
        "events": int(events.sum()),
        "trades": int(trades.sum()),
        "book_overflow": int(b_over.sum()),
        "fill_overflow": int(f_over.sum()),
    }


# -- grid -> host columns / orders ------------------------------------------

def grid_to_columns(ops: dict, drop_misses: bool = False) -> dict:
    """One host-side `[S, T]` op grid (numpy leaves, DeviceOp field names)
    to service-wire columns (the bench/_svc_gateway_step contract).

    Occupied cells are linearized in (t, lane) order — a grid column is
    one arrival instant across lanes, so t-major order is a faithful
    serial stream for the per-lane FIFO semantics. `drop_misses` removes
    deliberate-miss cancels (oid handle 0) for consumers that track oid
    liveness (the service pre-pool)."""
    t_idx, lane_idx = np.nonzero(np.asarray(ops["action"]).T != 0)
    pick = lambda f: np.asarray(ops[f])[lane_idx, t_idx]
    action = pick("action")
    oid_num = pick("oid").astype(np.int64)
    if drop_misses:
        keep = ~((action == 2) & (oid_num == 0))
        lane_idx, t_idx = lane_idx[keep], t_idx[keep]
        action = action[keep]
        oid_num = oid_num[keep]
    uid = pick("uid").astype(np.int64)
    return dict(
        n=len(action),
        action=action.astype(np.uint8),
        side=pick("side").astype(np.uint8),
        kind=pick("is_market").astype(np.uint8),
        price=pick("price").astype(np.int64),
        volume=pick("volume").astype(np.int64),
        symbol_idx=lane_idx.astype(np.uint32),
        # Background uids are 1..n_uids -> dictionary indices 0-based.
        uuid_idx=np.maximum(uid - 1, 0).astype(np.uint32),
        oids=np.char.add("o", oid_num.astype("U20")).astype("S"),
    )


def orders_from_grid(ops: dict, drop_misses: bool = False) -> list:
    """Host-side grid -> Order objects (for the oracle-parity fuzz
    harness). Symbols are "s{lane}", uuids "u{idx}", oids "o{handle}"."""
    from ..types import Action, Order, OrderType, Side

    cols = grid_to_columns(ops, drop_misses=drop_misses)
    out = []
    for i in range(cols["n"]):
        out.append(Order(
            uuid=f"u{int(cols['uuid_idx'][i])}",
            oid=cols["oids"][i].decode(),
            symbol=f"s{int(cols['symbol_idx'][i])}",
            side=Side(int(cols["side"][i])),
            price=int(cols["price"][i]),
            volume=int(cols["volume"][i]),
            action=Action(int(cols["action"][i])),
            order_type=OrderType(int(cols["kind"][i])),
        ))
    return out


# -- GCO record mode --------------------------------------------------------

@functools.partial(jax.jit, static_argnums=0)
def _record_step(config: EnvConfig, state):  # gomelint: disable=GL903 — offline record tool: one compile per config, paid at session start before any frame traffic; not a frame-dispatch combo, so the boot replay can't (and needn't) reach it
    """One background-only env transition that ALSO returns the generated
    grid. gen_ops is pure in (flow state, books), so re-deriving the grid
    here is bit-identical to the one `_env_step_impl` applies (and XLA
    CSEs the duplicate trace)."""
    _, bg_ops = gen_ops(config.flow, state.flow, state.books)
    state2, _obs, _reward, info = _env_step_impl(
        config, state, null_action(config)
    )
    return state2, bg_ops, info


# gomelint: hotpath
def record_frames(
    config: EnvConfig, seed: int, n_steps: int
) -> list[bytes]:
    """Replay `n_steps` of background flow, dumping each step's grid as
    one GCO ORDER frame (empty steps are skipped). The frames re-feed
    the service path: decode_order_frame -> admission -> device.

    One batched `jax.device_get` per step (the sanctioned fetch — this
    is the record path, not the rollout loop)."""
    from ..bus.colwire import encode_order_frame

    symbols = [f"s{i}" for i in range(config.flow.n_lanes)]
    uuids = [f"u{i}" for i in range(config.flow.n_uids)]
    state, _ = env_reset(config, jax.random.PRNGKey(seed))
    frames: list[bytes] = []
    for _ in range(n_steps):
        state, bg_ops, _info = _record_step(config, state)
        host = jax.device_get(bg_ops)
        cols = grid_to_columns(host._asdict())
        if cols["n"] == 0:
            continue
        frames.append(encode_order_frame(
            cols["n"], cols["action"], cols["side"], cols["kind"],
            cols["price"], cols["volume"], symbols, cols["symbol_idx"],
            uuids, cols["uuid_idx"], cols["oids"],
        ))
    return frames

"""Empirical diagnostics for the flow generator (host-side numpy).

The generator claims three statistical properties; each has an estimator
here so tests can assert them on seeded samples instead of trusting the
implementation (arXiv:2510.08085 §4 validates its simulator the same
way):

  * symbol popularity is Zipf(a)      -> `zipf_exponent` (log-log fit)
  * the Hawkes process is subcritical -> `empirical_branching_ratio` vs
    `FlowConfig.branching_ratio` (the configured spectral bound)
  * event times cluster (self-excitation) -> `dispersion_index` > 1
    where a Poisson stream of the same rate gives ~1

`sample_grids` provides the seeded sample: N generated grids' (action,
side, is_market) layers, one device fetch at the end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.book import BookConfig, init_books
from .flow import FlowConfig, flow_init, gen_ops


@functools.partial(jax.jit, static_argnums=(0, 1, 3))
def _sample_grids_impl(
    config: FlowConfig, book_config: BookConfig, key, n_grids: int
):
    """Stack n_grids of generated (action, side, is_market) layers
    [N, S, T] against a fixed empty book stack (pricing falls back to
    the reference band; cancels all miss — occurrence, type, and lane
    statistics do not depend on book state)."""
    books = init_books(book_config, config.n_lanes)
    state = flow_init(config, key)

    def body(st, _):
        st2, ops = gen_ops(config, st, books)
        return st2, (ops.action, ops.side, ops.is_market)

    _, layers = jax.lax.scan(body, state, None, length=n_grids)
    return layers


def sample_grids(
    config: FlowConfig, seed: int, n_grids: int,
    book_config: BookConfig | None = None,
) -> dict:
    """Seeded sample as host numpy: {"action", "side", "is_market"},
    each [N, S, T] int32."""
    if book_config is None:
        book_config = BookConfig(cap=4, max_fills=1, dtype=jnp.int32)
    action, side, is_market = jax.device_get(_sample_grids_impl(
        config, book_config, jax.random.PRNGKey(seed), n_grids
    ))
    return {
        "action": np.asarray(action),
        "side": np.asarray(side),
        "is_market": np.asarray(is_market),
    }


def symbol_counts(sample: dict) -> np.ndarray:
    """Events per lane [S], summed over grids and bins."""
    return (sample["action"] != 0).sum(axis=(0, 2))


def zipf_exponent(counts: np.ndarray) -> float:
    """Least-squares slope of log(frequency) vs log(rank) over the lanes
    that fired — recovers `a` when counts follow rank^(-a). Lane order IS
    rank order (flow._zipf_logits assigns lane 0 the heaviest weight)."""
    counts = np.asarray(counts, np.float64)
    ranks = np.arange(1, len(counts) + 1, dtype=np.float64)
    live = counts > 0
    if live.sum() < 2:
        raise ValueError("need events on >= 2 lanes to fit an exponent")
    x = np.log(ranks[live])
    y = np.log(counts[live])
    slope = np.polyfit(x, y, 1)[0]
    return float(-slope)


def events_per_grid(sample: dict) -> np.ndarray:
    """Event count per generated grid [N] (the bin-aggregated counting
    process the clustering/branching estimators run on)."""
    return (sample["action"] != 0).sum(axis=(1, 2))


def dispersion_index(counts_per_window: np.ndarray) -> float:
    """Index of dispersion var/mean of window counts: ~1 for Poisson,
    > 1 for a clustered (self-exciting) stream."""
    c = np.asarray(counts_per_window, np.float64)
    mean = c.mean()
    if mean == 0:
        raise ValueError("no events in sample")
    return float(c.var(ddof=1) / mean)


def empirical_branching_ratio(
    config: FlowConfig, n_events: int, n_grids: int
) -> float:
    """Moment estimator n_hat = 1 - mu_total * T / N (stationary Hawkes:
    the event rate is mu_total / (1 - n) with n the branching ratio —
    arXiv:2510.08085 eq. 6). `T` is total model time spanned; thinning
    discretization (<= 1 event/bin) biases it slightly low at high
    per-bin occupancy, so tests compare with a tolerance."""
    if n_events <= 0:
        raise ValueError("no events in sample")
    total_time = n_grids * config.t_bins * config.dt
    mu_total = float(config.mu().sum())
    return 1.0 - mu_total * total_time / n_events

"""Gym-style vectorized market environment over the stacked books.

One `step` is one compiled call: inject the agent's ops into the first
`n_agent_ops` grid columns, generate a Hawkes/Zipf background grid for
the remaining columns (sim.flow), run the engine's batched step on the
`[S, ...]` book stack, and compute observations / reward / info from the
device-resident results — no host transfer anywhere in the transition,
so `rollout` can `lax.scan` thousands of steps on the accelerator
(JAX-LOB, arXiv:2308.13289 §4: the rollout loop must live on device or
RL throughput dies on the PCIe round trip).

Reward is mark-to-market PnL delta in float32 (cash + inventory * mid).
The matching arithmetic stays exact integer (engine envelope); the f32
here is diagnostic reward shaping only, never book state.

Capacity note: a jitted rollout cannot host-escalate geometry the way
`BatchEngine` does, so overflow is *reported* per step (`StepInfo.
book_overflow` / `fill_overflow`) instead of replayed; size `book.cap` /
`max_fills` for the flow (tests/test_sim.py asserts the counters stay
zero over a 1000-step rollout at cap=32 / K=8 with the default flow).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from typing import NamedTuple

from ..engine.batch import _batch_step_impl
from ..engine.book import BookConfig, BookState, DeviceOp, init_books
from .flow import FlowConfig, FlowState, flow_init, gen_ops


@dataclasses.dataclass(frozen=True)
class EnvConfig:
    """Static environment parameters (hashable — jit static arg)."""

    flow: FlowConfig = FlowConfig()
    book: BookConfig = BookConfig(cap=16, max_fills=4, dtype=jnp.int32)
    n_agent_ops: int = 2  # agent op slots per step (grid columns 0..A-1)
    obs_levels: int = 4  # resting slots exposed per side in Obs
    agent_uid: int = 1 << 20  # above any background uid

    def __post_init__(self) -> None:
        if self.n_agent_ops <= 0:
            raise ValueError("sim env n_agent_ops must be positive")
        if not 0 < self.obs_levels <= self.book.cap:
            raise ValueError(
                f"sim env obs_levels must be in [1, cap], got "
                f"{self.obs_levels} (cap {self.book.cap})"
            )
        if self.agent_uid <= self.flow.n_uids:
            raise ValueError(
                "sim env agent_uid must exceed flow.n_uids (background "
                "uids would alias the agent's fills)"
            )


class AgentAction(NamedTuple):
    """The agent's op slots for one step — each leaf is `[A]`. `action`
    0 (NOP) makes a slot inert; ADD slots must carry volume >= 1 and the
    agent's own oid handles (disjoint from background oids, which count
    up from 1 — use e.g. oids >= 2**24). The env stamps `uid` itself."""

    lane: jax.Array  # i32 symbol lane
    action: jax.Array  # i32 0=NOP, 1=ADD, 2=DEL
    side: jax.Array  # i32 0=BUY, 1=SALE
    is_market: jax.Array  # i32 bool
    price: jax.Array  # book dtype ticks (absolute)
    volume: jax.Array  # book dtype lots
    oid: jax.Array  # book dtype order-id handle


class EnvState(NamedTuple):
    books: BookState  # [S, ...] stacked
    flow: FlowState
    t: jax.Array  # i32 step counter
    cash: jax.Array  # f32 signed cash (diagnostic units)
    inv: jax.Array  # i32 [S] net agent inventory (lots) per lane
    mtm: jax.Array  # f32 mark-to-market at the end of last step


class Obs(NamedTuple):
    """Device-side L1/L2 view of the books (the jit-compatible analogue
    of `engine.book.book_depth`). Depth slots are the top `L` *resting
    orders* per side in priority order (equal prices adjacent), masked to
    zero beyond `count` — aggregation to price levels is a host concern."""

    best_bid: jax.Array  # [S] book dtype (0 when side empty)
    best_ask: jax.Array  # [S]
    bid_prices: jax.Array  # [S, L]
    bid_lots: jax.Array  # [S, L]
    ask_prices: jax.Array  # [S, L]
    ask_lots: jax.Array  # [S, L]
    counts: jax.Array  # [S, 2] i32 resting orders per side
    mid: jax.Array  # [S] f32 (ref-banded fallback when a side is empty)
    lam: jax.Array  # [E] f32 current Hawkes intensities
    t: jax.Array  # i32 step counter


class StepInfo(NamedTuple):
    """Per-step diagnostics (all i32 scalars; sums wrap — `checksum` is
    the replay digest fold, not an exact count)."""

    events: jax.Array  # background + agent ops applied (action != 0)
    trades: jax.Array  # total fills (n_fills sum, incl. beyond-K)
    traded_qty: jax.Array  # lots traded (wrapping i32)
    fill_overflow: jax.Array  # fill records beyond K (0 = exact)
    book_overflow: jax.Array  # dropped resting inserts (0 = exact)
    cancels_missed: jax.Array  # DELs that found nothing
    agent_fills: jax.Array  # fills with the agent on either side
    checksum: jax.Array  # i32 [4] wrapping fold over the fill stream


def null_action(config: EnvConfig) -> AgentAction:
    """All-NOP agent action (background flow only)."""
    a = config.n_agent_ops
    dt = config.book.dtype
    z32 = jnp.zeros((a,), jnp.int32)
    zdt = jnp.zeros((a,), dt)
    return AgentAction(
        lane=z32, action=z32, side=z32, is_market=z32,
        price=zdt, volume=zdt, oid=zdt,
    )


def _mid(config: EnvConfig, books: BookState):
    """[S] f32 mid price with the flow's reference band as fallback."""
    ref = float(config.flow.ref_price)
    half = float(config.flow.ref_spread)
    bb = jnp.where(
        books.count[:, 0] > 0, books.price[:, 0, 0].astype(jnp.float32),
        jnp.float32(ref - half),
    )
    ba = jnp.where(
        books.count[:, 1] > 0, books.price[:, 1, 0].astype(jnp.float32),
        jnp.float32(ref + half),
    )
    return 0.5 * (bb + ba)


def _observe(config: EnvConfig, books: BookState, flow: FlowState, t):
    ell = config.obs_levels
    dt = config.book.dtype
    slots = jnp.arange(ell, dtype=jnp.int32)
    live = slots[None, None, :] < books.count[:, :, None]  # [S, 2, L]
    prices = jnp.where(live, books.price[:, :, :ell], jnp.asarray(0, dt))
    lots = jnp.where(live, books.lots[:, :, :ell], jnp.asarray(0, dt))
    zero = jnp.asarray(0, dt)
    return Obs(
        best_bid=jnp.where(books.count[:, 0] > 0, books.price[:, 0, 0],
                           zero),
        best_ask=jnp.where(books.count[:, 1] > 0, books.price[:, 1, 0],
                           zero),
        bid_prices=prices[:, 0], bid_lots=lots[:, 0],
        ask_prices=prices[:, 1], ask_lots=lots[:, 1],
        counts=books.count,
        mid=_mid(config, books),
        lam=flow.lam,
        t=t,
    )


def _agent_grid(config: EnvConfig, act: AgentAction) -> DeviceOp:
    """Scatter the agent's [A] op slots into an [S, A] grid (slot a owns
    column a, so agent ops never collide and keep their order)."""
    s = config.flow.n_lanes
    a = config.n_agent_ops
    dt = config.book.dtype
    cols = jnp.arange(a, dtype=jnp.int32)
    on32 = (act.action != 0).astype(jnp.int32)
    ondt = on32.astype(dt)
    uid = jnp.asarray(config.agent_uid, dt) * ondt
    fields = {
        "action": (act.action * on32, jnp.int32),
        "side": (act.side * on32, jnp.int32),
        "is_market": (act.is_market * on32, jnp.int32),
        "price": (act.price * ondt, dt),
        "volume": (act.volume * ondt, dt),
        "oid": (act.oid * ondt, dt),
        "uid": (uid, dt),
    }
    return DeviceOp(**{
        f: jnp.zeros((s, a), d).at[act.lane, cols].set(v.astype(d))
        for f, (v, d) in fields.items()
    })


def _env_reset_impl(config: EnvConfig, key: jax.Array):
    books = init_books(config.book, config.flow.n_lanes)
    flow = flow_init(config.flow, key)
    t = jnp.zeros((), jnp.int32)
    state = EnvState(
        books=books, flow=flow, t=t,
        cash=jnp.zeros((), jnp.float32),
        inv=jnp.zeros((config.flow.n_lanes,), jnp.int32),
        mtm=jnp.zeros((), jnp.float32),
    )
    return state, _observe(config, books, flow, t)


def _env_step_impl(config: EnvConfig, state: EnvState, act: AgentAction):
    a = config.n_agent_ops
    flow2, bg_ops = gen_ops(config.flow, state.flow, state.books)
    ops = jax.tree.map(
        lambda x, y: jnp.concatenate([x, y], axis=1),
        _agent_grid(config, act), bg_ops,
    )
    books2, outs = _batch_step_impl(config.book, state.books, ops)

    # -- agent PnL (f32 cash, i32 per-lane inventory) ----------------------
    qty = outs.fill_qty.astype(jnp.float32)  # [S, T, K]
    price = outs.fill_price.astype(jnp.float32)
    agent_uid = jnp.asarray(config.agent_uid, config.book.dtype)
    filled = outs.fill_qty > 0
    # Maker side: taker's side is the op's side; the maker BUYS when the
    # taker sells (side == 1) and vice versa.
    maker = filled & (outs.maker_uid == agent_uid)
    taker_side = ops.side[:, :, None]
    mk_sign = jnp.where(taker_side == 1, 1.0, -1.0) * maker
    inv_maker = jnp.sum(
        outs.fill_qty * jnp.where(taker_side == 1, 1, -1) * maker,
        axis=(1, 2), dtype=jnp.int32,
    )  # [S]
    cash_maker = -jnp.sum(mk_sign * qty * price)
    # Taker side: the agent's own op slots live at known coordinates
    # (act.lane, column a) — sum their fill records directly.
    cols = jnp.arange(a, dtype=jnp.int32)
    t_qty = outs.fill_qty[act.lane, cols]  # [A, K]
    t_prc = price[act.lane, cols]
    t_sign = jnp.where(act.side == 0, 1, -1)[:, None]  # buy: +inv, -cash
    inv_taker = jnp.zeros_like(state.inv).at[act.lane].add(
        jnp.sum(t_qty * t_sign, axis=1, dtype=jnp.int32)
    )
    cash_taker = -jnp.sum(
        t_qty.astype(jnp.float32) * t_prc * t_sign.astype(jnp.float32)
    )
    inv2 = state.inv + inv_maker + inv_taker
    cash2 = state.cash + cash_maker + cash_taker
    agent_fills = jnp.sum(maker, dtype=jnp.int32) + jnp.sum(
        t_qty > 0, dtype=jnp.int32
    )

    t2 = state.t + 1
    obs = _observe(config, books2, flow2, t2)
    mtm2 = cash2 + jnp.sum(inv2.astype(jnp.float32) * obs.mid)
    reward = mtm2 - state.mtm

    q32 = outs.fill_qty.astype(jnp.int32)
    checksum = jnp.stack([
        jnp.sum(outs.n_fills, dtype=jnp.int32),
        jnp.sum(q32, dtype=jnp.int32),
        jnp.sum(q32 * outs.fill_price.astype(jnp.int32), dtype=jnp.int32),
        jnp.sum(q32 * outs.maker_oid.astype(jnp.int32), dtype=jnp.int32),
    ])
    info = StepInfo(
        events=jnp.sum(ops.action != 0, dtype=jnp.int32),
        trades=jnp.sum(outs.n_fills, dtype=jnp.int32),
        traded_qty=jnp.sum(q32, dtype=jnp.int32),
        fill_overflow=jnp.sum(outs.fill_overflow, dtype=jnp.int32),
        book_overflow=jnp.sum(outs.book_overflow, dtype=jnp.int32),
        cancels_missed=jnp.sum(
            (ops.action == 2) & (outs.cancel_found == 0), dtype=jnp.int32
        ),
        agent_fills=agent_fills,
        checksum=checksum,
    )
    state2 = EnvState(
        books=books2, flow=flow2, t=t2, cash=cash2, inv=inv2, mtm=mtm2
    )
    return state2, obs, reward, info


def _rollout_impl(config: EnvConfig, state: EnvState, n_steps: int):
    """Background-only rollout: `n_steps` env transitions in one
    `lax.scan` (the zero-host-transfer acceptance path). Returns the
    final state and the stacked per-step (reward, StepInfo) trajectory."""
    nop = null_action(config)

    def body(st, _):
        st2, _obs, reward, info = _env_step_impl(config, st, nop)
        return st2, (reward, info)

    final, traj = jax.lax.scan(body, state, None, length=n_steps)
    return final, traj


env_reset = functools.partial(jax.jit, static_argnums=0)(_env_reset_impl)
env_step = functools.partial(jax.jit, static_argnums=0)(_env_step_impl)
rollout = functools.partial(
    jax.jit, static_argnums=(0, 2)
)(_rollout_impl)


class MarketEnv:
    """Thin OO wrapper over the pure entries (reset/step/rollout) for
    callers that prefer holding the config once."""

    def __init__(self, config: EnvConfig | None = None):
        self.config = config if config is not None else EnvConfig()

    def reset(self, key):
        return env_reset(self.config, key)

    def step(self, state, action):
        return env_step(self.config, state, action)

    def null_action(self):
        return null_action(self.config)

    def rollout(self, state, n_steps: int):
        return rollout(self.config, state, int(n_steps))

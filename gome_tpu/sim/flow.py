"""On-device Hawkes/Zipf order-flow generator.

Model (arXiv:2510.08085 §2, discretized): six event types — {submit,
cancel, market} x {buy, sell} — share a multivariate Hawkes intensity
vector lambda[E] with exponential kernels:

    lambda_i(t) = mu_i + sum_j sum_{t_k^j < t} alpha[i][j] exp(-beta (t - t_k^j))

Each generated grid discretizes `t_bins` micro-bins of width `dt`; per
bin at most one event occurs (Bernoulli thinning with p = 1 - exp(-Lambda
dt)), its type is categorical in lambda, its symbol lane is Zipf(a)-
categorical (JAX-LOB's symbol-popularity model), and the intensity vector
decays + self/cross-excites per bin inside a `lax.scan`. Stationarity
requires the branching matrix alpha/beta to have spectral radius < 1
(:meth:`FlowConfig.branching_ratio`).

Placement: limit orders price at a geometric offset from the *opposite*
best quote (offset 0 = a marketable limit at the touch; larger offsets
rest deeper), falling back to a reference band when the book side is
empty. Cancels target a uniformly random resting slot of the lane's book
(gathered oid + exact resting price, the DEL contract of engine/step.py);
an empty side yields a deliberate miss (oid 0 is never assigned).

Everything here runs inside jit on device values — the emitted grid is a
`DeviceOp` in exactly the `[S, T]` layout `engine.batch` consumes (int32
for `GRID_I32_FIELDS`, book dtype elsewhere), so a generated frame feeds
`_batch_step_impl` with zero host round-trips (GL5xx) and the intensity
state never leaves the accelerator.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from typing import NamedTuple

from ..engine.book import GRID_I32_FIELDS, BookState, DeviceOp

# Event-type index = kind * 2 + side (kind: 0 submit, 1 cancel, 2 market;
# side: 0 BUY, 1 SALE) — so `etype % 2` is the side and `etype // 2` the
# kind, branch-free.
EV_SUBMIT_BUY = 0
EV_SUBMIT_SALE = 1
EV_CANCEL_BUY = 2
EV_CANCEL_SALE = 3
EV_MARKET_BUY = 4
EV_MARKET_SALE = 5
N_EVENT_TYPES = 6


@dataclasses.dataclass(frozen=True)
class FlowConfig:
    """Static flow-generator parameters (hashable — jit static arg).

    Intensities are per model-time unit; `dt` is the micro-bin width, so
    the per-bin event probability is 1 - exp(-sum(mu-ish) * dt) and one
    generated grid spans `t_bins * dt` model time. The excitation matrix
    is structured: an event adds `excite_self` to its own type's
    intensity, `excite_cross` to the same kind's opposite side, and
    `excite_kind` to every other type (all scaled by `decay` so the
    *branching* contribution alpha/beta is exactly those numbers — row
    sums must stay < 1)."""

    n_lanes: int = 256
    t_bins: int = 32
    dt: float = 0.02
    # Base intensities per kind (split evenly across the two sides).
    submit_rate: float = 2.0
    cancel_rate: float = 1.4
    market_rate: float = 0.6
    # Branching fractions (alpha = these * decay).
    excite_self: float = 0.25
    excite_cross: float = 0.10
    excite_kind: float = 0.05
    decay: float = 2.0
    zipf_a: float = 1.1
    # Geometric placement offset from the opposite touch (p = offset_p;
    # offset 0 = marketable limit) clamped to max_offset ticks.
    offset_p: float = 0.35
    max_offset: int = 200
    ref_price: int = 100_000  # empty-book fallback mid (ticks)
    ref_spread: int = 20  # fallback half-spread (ticks)
    vol_max: int = 100  # volumes uniform in [1, vol_max] lots
    n_uids: int = 256  # background uids in [1, n_uids]

    def __post_init__(self) -> None:
        if self.n_lanes <= 0 or self.t_bins <= 0:
            raise ValueError("sim flow n_lanes/t_bins must be positive")
        if self.dt <= 0 or self.decay <= 0:
            raise ValueError("sim flow dt/decay must be positive")
        if not (self.submit_rate > 0 or self.cancel_rate > 0
                or self.market_rate > 0):
            raise ValueError("sim flow needs a positive base rate")
        if self.vol_max <= 0 or self.n_uids <= 0:
            raise ValueError("sim flow vol_max/n_uids must be positive")
        if not 0 < self.offset_p < 1:
            raise ValueError(
                f"sim flow offset_p must be in (0, 1), got {self.offset_p}"
            )
        if self.ref_price <= self.ref_spread:
            raise ValueError("sim flow ref_price must exceed ref_spread")
        br = self.branching_ratio()
        if br >= 1.0:
            raise ValueError(
                f"sim flow Hawkes process is unstable: branching ratio "
                f"{br:.3f} >= 1 (lower excite_* or raise decay)"
            )
        # Thinning validity: with <= 1 event per bin, the discretization
        # saturates when the stationary rate mu_total / (1 - n) fills a
        # bin with high probability — the Bernoulli cap then clips the
        # excitation (the realized process stops being Hawkes: branching
        # collapses and counts go UNDERdispersed).
        rate = float(self.mu().sum()) / (1.0 - br)
        p_bin = 1.0 - math.exp(-rate * self.dt)
        if p_bin > 0.6:
            raise ValueError(
                f"sim flow dt too coarse: stationary per-bin event "
                f"probability {p_bin:.2f} > 0.6 saturates the one-event-"
                f"per-bin thinning (lower dt or the base rates)"
            )

    # -- derived model parameters (host-side, static) ---------------------
    def mu(self) -> np.ndarray:
        """Base intensity per event type [E] (kind rate split per side)."""
        per_side = [self.submit_rate / 2, self.cancel_rate / 2,
                    self.market_rate / 2]
        return np.repeat(np.asarray(per_side, np.float64), 2)

    def alpha(self) -> np.ndarray:
        """Excitation jump matrix [E, E]: event of type j adds
        alpha[i, j] to intensity i."""
        a = np.full((N_EVENT_TYPES, N_EVENT_TYPES),
                    self.excite_kind, np.float64)
        for j in range(N_EVENT_TYPES):
            a[j, j] = self.excite_self
            a[j ^ 1, j] = self.excite_cross  # same kind, opposite side
        return a * self.decay

    def branching_ratio(self) -> float:
        """Spectral radius of the branching matrix alpha/beta — the
        Hawkes stability bound (< 1 <=> stationary; arXiv:2510.08085
        eq. 4). With the structured alpha the all-ones vector is the
        Perron eigenvector, but compute it generally."""
        m = self.alpha() / self.decay
        return float(np.max(np.abs(np.linalg.eigvals(m))))


class FlowState(NamedTuple):
    """Device-resident generator state (a scan carry)."""

    lam: jax.Array  # f32 [E] current Hawkes intensities
    key: jax.Array  # PRNG key
    next_oid: jax.Array  # i32 next order-id handle (oid 0 never assigned)
    t_model: jax.Array  # f32 elapsed model time (diagnostics)


def flow_init(config: FlowConfig, key: jax.Array) -> FlowState:
    """Fresh generator state at the base intensity."""
    return FlowState(
        lam=jnp.asarray(config.mu(), jnp.float32),
        key=key,
        next_oid=jnp.ones((), jnp.int32),
        t_model=jnp.zeros((), jnp.float32),
    )


def _zipf_logits(config: FlowConfig) -> jax.Array:
    """Static log-weights for Zipf(a) symbol popularity over ranks
    1..n_lanes (lane 0 is the hottest symbol)."""
    ranks = np.arange(1, config.n_lanes + 1, dtype=np.float64)
    return jnp.asarray(-config.zipf_a * np.log(ranks), jnp.float32)


def _bin_events(config: FlowConfig, lam, key, oid0):
    """Inner per-bin scan: thinned Hawkes event stream for one grid.

    Returns the carry (lam, key, next_oid) and per-bin arrays [T]:
    occur (i32 0/1), etype, lane, uid, oid, vol (i32) and u_price,
    u_cancel (f32 placement draws, resolved against books afterwards)."""
    # All scalar model constants are pinned f32 up front: a bare python
    # float closed over by the scan body would enter the jaxpr as a
    # weak-typed float64 constant under x64 (GL201 in the envelope audit).
    decay = jnp.float32(math.exp(-config.decay * config.dt))
    mu = jnp.asarray(config.mu(), jnp.float32)
    alpha = jnp.asarray(config.alpha(), jnp.float32)
    zipf = _zipf_logits(config)
    dt = jnp.float32(config.dt)
    one = jnp.float32(1.0)
    eps = jnp.float32(1e-12)
    zero = jnp.float32(0.0)

    def body(carry, _):
        lam, key, oid = carry
        key, k_ev, k_ty, k_ln, k_pr, k_cx, k_vol, k_uid = jax.random.split(
            key, 8
        )
        lam_total = jnp.sum(lam)
        p_event = one - jnp.exp(-lam_total * dt)
        occur = (
            jax.random.uniform(k_ev, (), jnp.float32) < p_event
        ).astype(jnp.int32)
        etype = jax.random.categorical(
            k_ty, jnp.log(lam + eps)
        ).astype(jnp.int32)
        lane = jax.random.categorical(k_ln, zipf).astype(jnp.int32)
        u_price = jax.random.uniform(k_pr, (), jnp.float32)
        u_cancel = jax.random.uniform(k_cx, (), jnp.float32)
        vol = jax.random.randint(
            k_vol, (), 1, config.vol_max + 1, jnp.int32
        )
        uid = jax.random.randint(
            k_uid, (), 1, config.n_uids + 1, jnp.int32
        )
        is_add = occur * (1 - (etype // 2 == 1).astype(jnp.int32))
        oid_here = oid  # assigned only when this bin emits an ADD
        oid = oid + is_add
        lam = mu + (lam - mu) * decay + jnp.where(
            occur > 0, alpha[:, etype], zero
        )
        out = (occur, etype, lane, uid, oid_here, vol, u_price, u_cancel)
        return (lam, key, oid), out

    carry, outs = jax.lax.scan(
        body, (lam, key, oid0), None, length=config.t_bins
    )
    return carry, outs


def gen_ops(
    config: FlowConfig, state: FlowState, books: BookState
) -> tuple[FlowState, DeviceOp]:
    """One grid of background flow: `(state, books) -> (state', ops)`.

    `books` is the frame-start `[S, ...]` stacked BookState the placement
    model quotes against (best bid/ask per lane; cancel targets gathered
    from resting slots) — the caller applies the returned `[S, T]` grid
    to those books afterwards (engine.batch semantics: each bin owns one
    grid column, so bin order is arrival order and cells never collide).
    Pure jit-traceable; all shapes static in `config`."""
    s_lanes, t_bins = config.n_lanes, config.t_bins
    dtype = books.price.dtype
    (lam, key, next_oid), outs = _bin_events(
        config, state.lam, state.key, state.next_oid
    )
    occur, etype, lane, uid, oid_new, vol, u_price, u_cancel = outs

    kind = etype // 2  # 0 submit, 1 cancel, 2 market
    side = (etype % 2).astype(jnp.int32)
    is_cancel = (kind == 1).astype(jnp.int32)
    is_market = (kind == 2).astype(jnp.int32)

    # -- placement against the frame-start books ([T] gathers) ------------
    ref_mid = jnp.asarray(config.ref_price, dtype)
    ref_half = jnp.asarray(config.ref_spread, dtype)
    cnt = books.count[lane]  # [T, 2] i32
    best_bid = jnp.where(
        cnt[:, 0] > 0, books.price[lane, 0, 0], ref_mid - ref_half
    )
    best_ask = jnp.where(
        cnt[:, 1] > 0, books.price[lane, 1, 0], ref_mid + ref_half
    )
    # Geometric offset from the opposite touch: k = floor(log(1-u) /
    # log(1-p)) in {0, 1, ...}; k = 0 is a marketable limit.
    k_off = jnp.floor(
        jnp.log1p(-u_price * jnp.float32(1.0 - 1e-7))
        * jnp.float32(1.0 / math.log(1.0 - config.offset_p))
    ).astype(jnp.int32)
    k_off = jnp.minimum(k_off, jnp.int32(config.max_offset)).astype(dtype)
    limit_price = jnp.where(side == 0, best_ask - k_off, best_bid + k_off)
    limit_price = jnp.maximum(limit_price, jnp.asarray(1, dtype))

    # -- cancel targeting: uniform resting slot of the lane's side --------
    n_side = jnp.take_along_axis(cnt, side[:, None], axis=1)[:, 0]  # [T]
    slot = jnp.minimum(
        (u_cancel * n_side.astype(jnp.float32)).astype(jnp.int32),
        jnp.maximum(n_side - 1, 0),
    )
    c_oid = books.oid[lane, side, slot]
    c_price = books.price[lane, side, slot]
    c_uid = books.uid[lane, side, slot]
    hit = (n_side > 0).astype(jnp.int32)
    # Miss: oid 0 / price 0 never matches a resting order (oids start at
    # 1, prices at 1) — the engine reports cancel_found=0, same as the
    # oracle's not-found path.
    c_oid = jnp.where(hit > 0, c_oid, jnp.asarray(0, dtype))
    c_price = jnp.where(hit > 0, c_price, jnp.asarray(0, dtype))

    # -- field resolution per bin ([T], then scattered to [S, T]) ---------
    action = occur * jnp.where(is_cancel > 0, 2, 1)
    price = jnp.where(
        is_cancel > 0, c_price,
        jnp.where(is_market > 0, jnp.asarray(0, dtype), limit_price),
    )
    oid = jnp.where(is_cancel > 0, c_oid, oid_new.astype(dtype))
    volume = jnp.where(
        is_cancel > 0, jnp.asarray(0, dtype), vol.astype(dtype)
    )
    # A hitting cancel is issued by the resting order's OWNER (uid is
    # reporting-only for matching, but the service pre-pool keys on
    # symbol:uuid:oid — a random uid there would always miss).
    uid = jnp.where(
        (is_cancel > 0) & (hit > 0), c_uid.astype(jnp.int32), uid
    )

    mask_i32 = occur
    mask_dt = occur.astype(dtype)
    cols = {
        "action": action,
        "side": side * mask_i32,
        "is_market": is_market * mask_i32,
        "price": price * mask_dt,
        "volume": volume * mask_dt,
        "oid": oid * mask_dt,
        "uid": uid.astype(dtype) * mask_dt,
    }
    tt = jnp.arange(t_bins, dtype=jnp.int32)

    def scat(vals, dt_):
        return jnp.zeros((s_lanes, t_bins), dt_).at[lane, tt].set(
            vals.astype(dt_)
        )

    ops = DeviceOp(**{
        f: scat(cols[f], jnp.int32 if f in GRID_I32_FIELDS else dtype)
        for f in DeviceOp._fields
    })
    new_state = FlowState(
        lam=lam,
        key=key,
        next_oid=next_oid,
        t_model=state.t_model + jnp.float32(t_bins * config.dt),
    )
    return new_state, ops


#: Standalone compiled entry (the env inlines gen_ops into its own step).
gen_ops_jit = functools.partial(jax.jit, static_argnums=0)(gen_ops)

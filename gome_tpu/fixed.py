"""Fixed-point scaling between external float prices/volumes and internal
integer ticks/lots.

The reference scales price and volume by 10^accuracy at ingestion using
shopspring/decimal and stores the result back into float64
(gomengine/engine/ordernode.go:76-87; accuracy default 8,
config.yaml.example:24). Go's decimal.NewFromFloat takes the shortest decimal
representation of the float — the same value Python's repr()/str() produces —
so Decimal(str(x)) * 10^accuracy reproduces the reference's scaled value
exactly. We keep the scaled value as a Python int (exact), whereas the
reference keeps float64 (exact only below 2^53 — SURVEY §2.2); parity is
defined on the event stream for in-range inputs.
"""

from __future__ import annotations

import decimal

DEFAULT_ACCURACY = 8  # config.yaml.example:24
_FLOAT53 = 1 << 53


def scale(value: float, accuracy: int = DEFAULT_ACCURACY) -> int:
    """External float → internal scaled integer (exact decimal semantics)."""
    d = decimal.Decimal(str(value)) * (decimal.Decimal(10) ** accuracy)
    # The reference truncates nothing: values with more than `accuracy`
    # decimals keep a fractional scaled part in its float64. Such inputs are
    # out of contract (the fixed-point scale IS the tick size); we reject
    # them loudly instead of silently rounding.
    if d != d.to_integral_value():
        raise ValueError(
            f"value {value!r} has more than {accuracy} decimal places; "
            f"not representable at accuracy={accuracy}"
        )
    return int(d)


def unscale(ticks: int, accuracy: int = DEFAULT_ACCURACY) -> float:
    """Internal scaled integer → the float64 the reference would hold.

    The reference's arithmetic happens on float64(scaled); below 2^53 that
    float is integer-exact, so float(ticks) reproduces it bit-for-bit.
    """
    return float(ticks)


def unscale_external(ticks: int, accuracy: int = DEFAULT_ACCURACY) -> float:
    """Internal scaled integer → external (human) units."""
    return float(
        decimal.Decimal(ticks) / (decimal.Decimal(10) ** accuracy)
    )


def is_float64_exact(ticks: int) -> bool:
    """Whether the reference's float64 representation of this scaled value is
    integer-exact (SURVEY §2.2 consequence (a))."""
    return abs(ticks) < _FLOAT53

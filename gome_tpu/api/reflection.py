"""gRPC server reflection (v1alpha) — hand-wired.

The reference registers reflection so operators can grpcurl the port
(gomengine/main.go:33 `reflection.Register(s)`). This image ships grpcio
but not the grpcio-reflection package, so the v1alpha protocol is
implemented directly: the two message types the flow needs
(ServerReflectionRequest/Response) are tiny, and raw-bytes generic
handlers let us serve them with manual protobuf wire framing — no
generated code required.

Supported requests (what grpcurl/evans use):
  list_services (7)          -> list_services_response (6)
  file_containing_symbol (4) -> file_descriptor_response (4)
  file_by_filename (3)       -> file_descriptor_response (4)
Anything else gets error_response (7) UNIMPLEMENTED.
"""

from __future__ import annotations

import struct

import grpc

from . import order_pb2 as pb
from .service import SERVICE_NAME

REFLECTION_SERVICE = "grpc.reflection.v1alpha.ServerReflection"


# --- minimal protobuf wire helpers ---------------------------------------


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def _field(num: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _parse_fields(buf: bytes) -> list[tuple[int, int, bytes | int]]:
    """-> [(field_number, wire_type, value)] — enough for the request."""
    out = []
    off = 0
    while off < len(buf):
        tag, off = _read_varint(buf, off)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            val, off = _read_varint(buf, off)
        elif wt == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off : off + ln]
            off += ln
        elif wt == 5:
            val = struct.unpack_from("<I", buf, off)[0]
            off += 4
        elif wt == 1:
            val = struct.unpack_from("<Q", buf, off)[0]
            off += 8
        else:
            break
        out.append((num, wt, val))
    return out


# --- the servicer ---------------------------------------------------------


def _file_descriptor_response(original: bytes) -> bytes:
    fdp = pb.DESCRIPTOR.serialized_pb  # the order.proto FileDescriptorProto
    inner = _field(1, fdp)  # repeated bytes file_descriptor_proto = 1
    return _field(2, original) + _field(4, inner)


def _list_services_response(original: bytes) -> bytes:
    # Only services whose descriptors we can actually serve: advertising
    # the reflection service itself would make describe-every-listed-
    # service tools (evans auto-discovery) hit NOT_FOUND on it.
    services = _field(1, _field(1, SERVICE_NAME.encode()))
    return _field(2, original) + _field(6, services)


def _error_response(original: bytes, code: int, msg: str) -> bytes:
    err = (
        _varint((1 << 3) | 0) + _varint(code)  # error_code = 1
        + _field(2, msg.encode())  # error_message = 2
    )
    return _field(2, original) + _field(7, err)


def _handle(request: bytes) -> bytes:
    for num, _wt, val in _parse_fields(request):
        if num == 7:  # list_services
            return _list_services_response(request)
        if num in (3, 4):  # file_by_filename / file_containing_symbol
            want = val.decode() if isinstance(val, bytes) else ""
            known_symbols = (
                SERVICE_NAME,
                f"{SERVICE_NAME}.DoOrder",
                f"{SERVICE_NAME}.DeleteOrder",
                f"{SERVICE_NAME}.SubscribeMatches",
                "gome_tpu.api.OrderRequest",
                "gome_tpu.api.OrderResponse",
                "gome_tpu.api.SubscribeRequest",
                "gome_tpu.api.MatchEvent",
                "gome_tpu.api.OrderSnapshot",
            )
            if num == 3:
                ok = want == pb.DESCRIPTOR.name
            else:
                ok = want in known_symbols or want.startswith("gome_tpu.api")
            if ok:
                return _file_descriptor_response(request)
            return _error_response(request, 5, f"not found: {want}")  # NOT_FOUND
    return _error_response(request, 12, "unsupported reflection request")


def add_reflection_servicer(server: grpc.Server) -> None:
    """Register ServerReflection (main.go:33's reflection.Register parity)."""

    def server_reflection_info(request_iterator, context):
        for request in request_iterator:
            yield _handle(request)

    handler = grpc.stream_stream_rpc_method_handler(
        server_reflection_info,
        request_deserializer=None,  # raw bytes in
        response_serializer=None,  # raw bytes out
    )
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                REFLECTION_SERVICE, {"ServerReflectionInfo": handler}
            ),
        )
    )

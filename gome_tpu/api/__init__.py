"""Wire contract package: order.proto (parity with the reference's
api/order.proto:1-29 + extensions), generated message classes, and the
hand-wired gRPC service plumbing (this environment has protoc but no
grpc_python_plugin, so service registration/stubs live in service.py)."""

from . import order_pb2
from .service import OrderStub, add_order_servicer

OrderRequest = order_pb2.OrderRequest
OrderResponse = order_pb2.OrderResponse
MatchEvent = order_pb2.MatchEvent
OrderSnapshotMsg = order_pb2.OrderSnapshot
SubscribeRequest = order_pb2.SubscribeRequest

__all__ = [
    "order_pb2",
    "OrderRequest",
    "OrderResponse",
    "MatchEvent",
    "OrderSnapshotMsg",
    "SubscribeRequest",
    "OrderStub",
    "add_order_servicer",
]

"""gRPC service wiring for the Order service.

Equivalent of the protoc-grpc-generated order_pb2_grpc module (the image has
protoc for messages but no grpc Python plugin, so the handler table and stub
are written out by hand — same wire behavior: method paths
``/gome_tpu.api.Order/DoOrder`` etc.). Mirrors the reference's service
surface (api/order.proto:26-29) plus the SubscribeMatches streaming
extension.
"""

from __future__ import annotations

import grpc

from . import order_pb2 as pb

SERVICE_NAME = "gome_tpu.api.Order"


def add_order_servicer(server: grpc.Server, servicer) -> None:
    """Register a servicer exposing DoOrder / DeleteOrder / SubscribeMatches
    (api.RegisterOrderServer's role, gomengine/main.go:31)."""
    handlers = {
        "DoOrder": grpc.unary_unary_rpc_method_handler(
            servicer.DoOrder,
            request_deserializer=pb.OrderRequest.FromString,
            response_serializer=pb.OrderResponse.SerializeToString,
        ),
        "DeleteOrder": grpc.unary_unary_rpc_method_handler(
            servicer.DeleteOrder,
            request_deserializer=pb.OrderRequest.FromString,
            response_serializer=pb.OrderResponse.SerializeToString,
        ),
        "SubscribeMatches": grpc.unary_stream_rpc_method_handler(
            servicer.SubscribeMatches,
            request_deserializer=pb.SubscribeRequest.FromString,
            response_serializer=pb.MatchEvent.SerializeToString,
        ),
        "DoOrderBatch": grpc.unary_unary_rpc_method_handler(
            servicer.DoOrderBatch,
            request_deserializer=pb.OrderBatchRequest.FromString,
            response_serializer=pb.OrderBatchResponse.SerializeToString,
        ),
        "DoOrderStream": grpc.stream_unary_rpc_method_handler(
            servicer.DoOrderStream,
            request_deserializer=pb.OrderRequest.FromString,
            response_serializer=pb.OrderBatchResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class OrderStub:
    """Client stub (api.NewOrderClient's role, doorder.go:32)."""

    def __init__(self, channel: grpc.Channel):
        self.DoOrder = channel.unary_unary(
            f"/{SERVICE_NAME}/DoOrder",
            request_serializer=pb.OrderRequest.SerializeToString,
            response_deserializer=pb.OrderResponse.FromString,
        )
        self.DeleteOrder = channel.unary_unary(
            f"/{SERVICE_NAME}/DeleteOrder",
            request_serializer=pb.OrderRequest.SerializeToString,
            response_deserializer=pb.OrderResponse.FromString,
        )
        self.SubscribeMatches = channel.unary_stream(
            f"/{SERVICE_NAME}/SubscribeMatches",
            request_serializer=pb.SubscribeRequest.SerializeToString,
            response_deserializer=pb.MatchEvent.FromString,
        )
        self.DoOrderBatch = channel.unary_unary(
            f"/{SERVICE_NAME}/DoOrderBatch",
            request_serializer=pb.OrderBatchRequest.SerializeToString,
            response_deserializer=pb.OrderBatchResponse.FromString,
        )
        self.DoOrderStream = channel.stream_unary(
            f"/{SERVICE_NAME}/DoOrderStream",
            request_serializer=pb.OrderRequest.SerializeToString,
            response_deserializer=pb.OrderBatchResponse.FromString,
        )

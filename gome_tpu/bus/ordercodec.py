"""Batch order decoding: native C++ fast path with json.loads fallback.

The consumer decodes every inbound doOrder message; `decode_orders_batch`
parses a whole micro-batch in one native call (native/ordercodec.cc),
returning the same Order objects `codec.decode_order` would. Messages the
native parser declines (escaped strings, unknown keys, no toolchain) fall
back to the json path — the fast path can only be faster, never different.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..types import Action, Order, OrderType, Side
from .codec import decode_order

# Index tables beat Enum.__call__ (~10x) on the per-message hot path.
_SIDES = (Side.BUY, Side.SALE)
_ACTIONS = (Action.NOP, Action.ADD, Action.DEL)
_KINDS = (OrderType.LIMIT, OrderType.MARKET)

_fn = None
_fn_err = False


def _load():
    global _fn, _fn_err
    if _fn is not None or _fn_err:
        return _fn
    try:
        from .native import _load as _load_lib

        lib = _load_lib()
        if lib is None:
            _fn_err = True
            return None
        fn = lib.gome_parse_orders
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                       ctypes.c_int64] + [
            ctypes.POINTER(ctypes.c_int64)
        ] * 11
        _fn = fn
    except Exception:
        _fn_err = True
        return None
    return _fn


def decode_orders_batch(bodies: list[bytes]) -> list[Order]:
    """Decode a batch of doOrder message bodies. Semantics identical to
    [decode_order(b) for b in bodies]."""
    n = len(bodies)
    if n == 0:
        return []
    fn = _load()
    if fn is None:
        return [decode_order(b) for b in bodies]

    buf = b"".join(bodies)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum([len(b) for b in bodies], out=offs[1:])
    cols = [np.empty(n, np.int64) for _ in range(11)]
    ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    parsed = int(
        fn(buf, ptr(offs), n, *(ptr(c) for c in cols))
    )
    (action, transaction, price, volume, kind,
     u_off, u_len, o_off, o_len, s_off, s_len) = cols

    orders: list[Order] = []
    sv = buf.decode()  # one decode; offsets are byte==char offsets (ASCII
    # fast path — any non-ASCII byte makes len(sv) != len(buf) and we fall
    # back below rather than slice at wrong positions)
    if len(sv) != len(buf):
        return [decode_order(b) for b in bodies]
    # Out-of-range enum codes decline to the json path (which raises the
    # same ValueError decode_order would).
    ok = (
        (transaction[:parsed] >= 0) & (transaction[:parsed] <= 1)
        & (action[:parsed] >= 0) & (action[:parsed] <= 2)
        & (kind[:parsed] >= 0) & (kind[:parsed] <= 1)
    )
    if not ok.all():
        parsed = int(np.argmin(ok))

    uo, ul = u_off.tolist(), u_len.tolist()
    oo, ol = o_off.tolist(), o_len.tolist()
    so, sl = s_off.tolist(), s_len.tolist()
    tr, pr, vo = transaction.tolist(), price.tolist(), volume.tolist()
    ac, kn = action.tolist(), kind.tolist()
    append = orders.append
    for i in range(parsed):
        append(
            Order(
                uuid=sv[uo[i] : uo[i] + ul[i]],
                oid=sv[oo[i] : oo[i] + ol[i]],
                symbol=sv[so[i] : so[i] + sl[i]],
                side=_SIDES[tr[i]],
                price=pr[i],
                volume=vo[i],
                action=_ACTIONS[ac[i]],
                order_type=_KINDS[kn[i]],
            )
        )
    for i in range(parsed, n):  # native declined: exact json fallback
        orders.append(decode_order(bodies[i]))
    return orders

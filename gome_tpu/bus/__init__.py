"""Message bus — the reference's RabbitMQ layer (gomengine/engine/rabbitmq.go)
re-expressed as a pluggable queue abstraction.

Topology parity: two named queues, inbound ``doOrder`` (orders + cancels)
and outbound ``matchOrder`` (fill/cancel events) — rabbitmq.go:60-84 and the
two consume loops rabbitmq.go:86-177. Backends:

  memory — in-process deques; the single-binary deployment (and tests).
  file   — durable append-only log segments with consumer offsets; unlike
           the reference's non-durable auto-ack queues (rabbitmq.go:64,102 —
           in-flight messages die with the process, SURVEY §2.3.6), a file
           queue doubles as the replay log for crash recovery (§5.4).
  amqp   — a dependency-free AMQP 0-9-1 protocol client (bus/amqp.py)
           speaking to RabbitMQ or the in-process fake broker
           (bus/fakebroker.py); when no broker is listening, make_bus
           falls back loudly to `memory` so a reference config.yaml
           still boots.

Deliberately NOT reproduced: the reference opens a brand-new AMQP connection
per published message (NewSimpleRabbitMQ inline at engine.go:37,112,157,174,
193; dial at rabbitmq.go:35-38) — the documented anti-pattern. Publishers
here hold their queue handle.
"""

from .base import Message, Queue, QueueBus
from .codec import (
    decode_match_result,
    decode_order,
    encode_match_result,
    encode_order,
)
from .filelog import FileQueue
from .memory import MemoryQueue
from .ordercodec import decode_orders_batch

__all__ = [
    "decode_message_orders",
    "decode_orders_batch",
    "Message",
    "Queue",
    "QueueBus",
    "MemoryQueue",
    "FileQueue",
    "make_bus",
    "encode_order",
    "decode_order",
    "encode_match_result",
    "decode_match_result",
]


def decode_message_orders(body: bytes) -> list:
    """Orders carried by one bus message, whichever wire kind it is: a
    binary ORDER frame (colwire) holds a batch, a reference-shape JSON
    document holds one. The single dispatch point shared by the consumer's
    quarantine replay and the persistence layer's recovery scan — live
    decoding and recovery must never diverge."""
    from .colwire import decode_order_frame, is_frame

    if is_frame(body):
        from ..engine.frames import orders_from_frame

        return orders_from_frame(decode_order_frame(body))
    return decode_orders_batch([body])


def make_bus(config) -> QueueBus:
    """Build the two-queue bus from a BusConfig (gome_tpu.config)."""
    if config.backend == "memory":
        factory = lambda name: MemoryQueue(name)
    elif config.backend == "file":
        import os

        factory = lambda name: FileQueue(name, os.path.join(config.dir, name))
    elif config.backend == "cfile":
        import os

        from .native import NativeFileQueue, native_available

        if native_available():
            factory = lambda name: NativeFileQueue(
                name, os.path.join(config.dir, name)
            )
        else:
            import warnings

            warnings.warn(
                "native queue library unavailable; falling back to the "
                "Python file backend (same on-disk format)",
                RuntimeWarning,
                stacklevel=2,
            )
            factory = lambda name: FileQueue(
                name, os.path.join(config.dir, name)
            )
    elif config.backend == "amqp":
        # Supervised client: reconnect with backoff + circuit breaker +
        # topology re-declare on every ConnectionError (utils.resilience).
        # The raw AmqpQueue fails loudly and stays down; the supervised
        # wrapper is what makes a broker bounce a non-event.
        from .amqp import SupervisedAmqpQueue

        def factory(name, _cfg=config):
            return SupervisedAmqpQueue(
                name,
                host=_cfg.host,
                port=_cfg.port,
                username=_cfg.username or "guest",
                password=_cfg.password or "guest",
            )

        # A reference config.yaml selects this backend (its rabbitmq:
        # section); the service must still BOOT when no broker is
        # listening — fall back loudly to the in-process backend instead
        # of crashing at startup (VERDICT r1 weak #4).
        order_q = None
        try:
            order_q = factory(config.order_queue)
            return QueueBus(
                order_queue=order_q, match_queue=factory(config.match_queue)
            )
        except OSError as e:
            if order_q is not None:  # match-queue connect failed: clean up
                order_q.close()
            import warnings

            warnings.warn(
                f"amqp broker unreachable at {config.host}:{config.port} "
                f"({e}); falling back to the in-process memory bus — "
                "matching runs, but cross-process AMQP interop is off "
                "until a broker is available",
                RuntimeWarning,
                stacklevel=2,
            )
            factory = lambda name: MemoryQueue(name)
    else:  # pragma: no cover - BusConfig validates
        raise ValueError(config.backend)
    return QueueBus(
        order_queue=factory(config.order_queue),
        match_queue=factory(config.match_queue),
    )

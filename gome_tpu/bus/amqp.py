"""AMQP 0-9-1 transport — the reference's actual inter-process fabric
(gomengine/engine/rabbitmq.go) as a first-class bus backend.

This is a dependency-free protocol implementation (no pika/amqpstorm in
this image): a socket client speaking the 0-9-1 frame protocol subset the
reference uses — Connection Start/Tune/Open, Channel.Open, Queue.Declare
(idempotent, rabbitmq.go:62-69), Basic.Publish with content frames,
Basic.Consume/Deliver, Basic.Ack — against any broker (RabbitMQ included)
or the in-process fake (gome_tpu.bus.fakebroker) used by the tests.

Deliberately NOT reproduced: the reference opens a brand-new connection
per published message (NewSimpleRabbitMQ inline at engine.go:37,112,157,
174,193) — each AmqpQueue holds ONE connection for its lifetime.

Queue-contract adaptation: AMQP has server-side destructive consume with
acks, not offset-addressed logs. AmqpQueue maps the framework's
offset/commit contract onto it:

  * deliveries arrive on a background reader into a local arrival buffer;
    offset = arrival index (FIFO per queue, matching the broker order);
  * `commit(n)` acks through the delivery tag of arrival n-1
    (multiple-flag), so broker-side at-least-once matches the contract —
    uncommitted messages redeliver after a crash/reconnect;
  * the consume loop starts LAZILY on the first read-side call: an
    instance used only for publishing (a gateway process) never competes
    with the real consumer for deliveries;
  * read-side calls on an instance that also published wait (bounded) for
    the loopback deliveries to catch up with the local publish count, so
    publish-then-read is deterministic in-process.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from .base import Message, Queue, _Waitable

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"


# --- wire primitives -----------------------------------------------------


def shortstr(s) -> bytes:
    b = s.encode() if isinstance(s, str) else s
    if len(b) > 255:
        raise ValueError("shortstr too long")
    return bytes([len(b)]) + b


def longstr(b) -> bytes:
    b = b.encode() if isinstance(b, str) else b
    return struct.pack(">I", len(b)) + b


def read_shortstr(buf: memoryview, off: int):
    n = buf[off]
    return bytes(buf[off + 1 : off + 1 + n]).decode(), off + 1 + n


def read_longstr(buf: memoryview, off: int):
    (n,) = struct.unpack_from(">I", buf, off)
    return bytes(buf[off + 4 : off + 4 + n]), off + 4 + n


def skip_table(buf: memoryview, off: int) -> int:
    (n,) = struct.unpack_from(">I", buf, off)
    return off + 4 + n


EMPTY_TABLE = struct.pack(">I", 0)


def encode_table(d: dict) -> bytes:
    """AMQP field table: string keys, long-string ('S') values. This is
    the subset message headers need (trace propagation publishes
    {"x-trace": "<id>@<t>"}); everything is stringified."""
    body = b"".join(
        shortstr(k) + b"S" + longstr(str(v)) for k, v in d.items()
    )
    return struct.pack(">I", len(body)) + body


def read_table(buf: memoryview, off: int) -> tuple[dict, int]:
    """Parse an AMQP field table -> (dict, next offset). Recognizes the
    value types brokers commonly put in headers ('S' long string, 't'
    bool, 'I' int32, 'l' int64); an unknown type code stops the parse
    (the table length still advances the offset correctly, so framing
    never desyncs — we just drop the unparseable tail)."""
    (n,) = struct.unpack_from(">I", buf, off)
    off += 4
    end = off + n
    out: dict = {}
    while off < end:
        key, off = read_shortstr(buf, off)
        t = buf[off]
        off += 1
        if t == 0x53:  # 'S' long string
            v, off = read_longstr(buf, off)
            out[key] = v.decode()
        elif t == 0x74:  # 't' bool
            out[key] = bool(buf[off])
            off += 1
        elif t == 0x49:  # 'I' int32
            (out[key],) = struct.unpack_from(">i", buf, off)
            off += 4
        elif t == 0x6C:  # 'l' int64
            (out[key],) = struct.unpack_from(">q", buf, off)
            off += 8
        else:
            break
    return out, end


#: basic-properties flag bit for the headers table (AMQP 0-9-1 §4.2.6.1:
#: content-type bit 15, content-encoding 14, headers 13).
FLAG_HEADERS = 1 << 13


def frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return (
        struct.pack(">BHI", ftype, channel, len(payload))
        + payload
        + bytes([FRAME_END])
    )


def method(class_id: int, method_id: int, args: bytes = b"") -> bytes:
    return struct.pack(">HH", class_id, method_id) + args


def read_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("AMQP peer closed the connection")
        out += chunk
    return out


#: Hard upper bound on any incoming frame payload, regardless of the
#: negotiated frame-max: a corrupt/hostile size field must fail the
#: connection loudly, not allocate gigabytes.
MAX_FRAME_SIZE = 16 << 20


def read_frame(sock: socket.socket):
    """-> (type, channel, payload)."""
    hdr = read_exact(sock, 7)
    ftype, channel, size = struct.unpack(">BHI", hdr)
    if size > MAX_FRAME_SIZE:
        raise ConnectionError(f"AMQP frame size {size} exceeds sanity bound")
    payload = read_exact(sock, size) if size else b""
    end = read_exact(sock, 1)
    if end[0] != FRAME_END:
        raise ConnectionError(f"bad AMQP frame end {end!r}")
    return ftype, channel, payload


def content_frames(
    channel: int, body: bytes, frame_max: int, headers: dict | None = None
) -> list[bytes]:
    """Content header + body frames for one message (class 60 basic).
    Zero-length bodies are header-only. `headers` becomes the
    basic-properties headers table (trace propagation rides it)."""
    if headers:
        props = struct.pack(">HHQH", 60, 0, len(body), FLAG_HEADERS)
        header = props + encode_table(headers)
    else:
        header = struct.pack(">HHQH", 60, 0, len(body), 0)  # no properties
    out = [frame(FRAME_HEADER, channel, header)]
    limit = max(frame_max - 8, 1024)
    for i in range(0, len(body), limit):
        out.append(frame(FRAME_BODY, channel, body[i : i + limit]))
    return out


# --- client --------------------------------------------------------------


class AmqpQueue(Queue, _Waitable):
    """One AMQP 0-9-1 queue behind the framework's offset/commit contract
    (module docstring). One TCP connection + one channel per instance."""

    SYNC_WAIT_S = 5.0  # loopback publish -> delivery catch-up bound

    def __init__(
        self,
        name: str,
        host: str = "127.0.0.1",
        port: int = 5672,
        username: str = "guest",
        password: str = "guest",
        vhost: str = "/",
        connect_timeout_s: float = 3.0,
        confirm: bool = False,
    ):
        """confirm=True puts the channel in publisher-confirm mode
        (Confirm.Select): publish() blocks until the broker's Basic.Ack
        for that message, so a publish that returns HAS been enqueued —
        the property reconnect-with-retry needs to be redeliver-safe
        (bus.amqp.SupervisedAmqpQueue always enables it). Cost: one
        round trip per publish; the throughput paths use the memory/
        file/native buses, so the trade is latency-for-certainty on
        exactly the transport where certainty matters."""
        self.name = name
        self._init_wait()
        self._lock = threading.RLock()  # socket writes + state
        self._rpc_lock = threading.Lock()  # one outstanding sync RPC
        self._rpc_event = threading.Event()
        # (token, (cls, mth, payload)) — an event-mediated handoff slot,
        # NOT lock-guarded: _rpc nulls it (under _rpc_lock) before each
        # send, the reader stores into it and sets _rpc_event, and the
        # waiter reads it only after the event fires (happens-before via
        # Event). Mutation sites carry explicit GL70x suppressions.
        self._rpc_reply: tuple | None = None
        self._rpc_expect: tuple | None = None  # guarded by self._rpc_lock — ((cls, mth), token)
        self._rpc_seq = 0  # guarded by self._rpc_lock (token source, _rpc)
        self._buffer: list[bytes] = []  # guarded by self._lock (arrivals)
        self._tags: list[int] = []  # guarded by self._lock (tag/arrival)
        self._redelivered: list[bool] = []  # guarded by self._lock
        self._hdrs: list[dict | None] = []  # guarded by self._lock
        self._committed = 0  # guarded by self._lock
        self._acked_through = 0  # guarded by self._lock (broker-acked)
        self._published = 0  # guarded by self._lock (loopback sync)
        self._consuming = False  # single-writer: the polling thread (_ensure_consuming)
        # One-way latch: ANY thread (rpc waiter, sender, reader, closer)
        # may flip it False->True, and it never goes back. Readers
        # tolerate staleness — paths where it matters re-check under the
        # relevant lock. Mutation sites carry explicit GL70x suppressions.
        self._closed = False
        self._frame_max = 131072  # single-writer: __init__'s handshake (pre-thread)
        self._pending_deliver: tuple | None = None  # single-writer: the reader thread
        self._confirm = False  # set after Confirm.Select below
        self._pub_seq = 0  # guarded by self._lock (1-based confirm tags)
        self._confirmed = 0  # guarded by self._ack_cond (ack frontier)
        self._ack_cond = threading.Condition()

        self._heartbeat = 0  # single-writer: __init__'s handshake (pre-thread)
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s
        )
        try:
            self._sock.settimeout(None)
            self._handshake(username, password, vhost)
            if self._heartbeat:
                # Inbound-silence bound: a peer quiet for 2 intervals is
                # dead (the spec's expiry rule); recv then times out and
                # the read loop fails the connection loudly.
                self._sock.settimeout(2.0 * self._heartbeat)
                threading.Thread(
                    target=self._heartbeat_loop,
                    name=f"amqp-hb-{name}",
                    daemon=True,
                ).start()
            self._reader = threading.Thread(
                target=self._read_loop, name=f"amqp-{name}", daemon=True
            )
            self._reader.start()
            # channel + idempotent declare (rabbitmq.go:62-69 semantics)
            self._rpc((20, 11), method(20, 10, shortstr("")))
            self._rpc(
                (50, 11),
                method(
                    50,
                    10,
                    struct.pack(">H", 0)
                    + shortstr(self.name)
                    + bytes([0])  # passive/durable/exclusive/auto-del/no-wait
                    + EMPTY_TABLE,
                ),
            )
            if confirm:
                # Confirm.Select (nowait=0): broker Basic.Acks publishes.
                self._rpc((85, 11), method(85, 10, bytes([0])))
                self._confirm = True
        except Exception:
            # No half-open leaks: a failed handshake/declare closes the
            # socket (which also ends the reader thread) before raising.
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass
            raise

    # -- protocol plumbing -------------------------------------------------
    def _handshake(self, username, password, vhost) -> None:
        self._sock.sendall(PROTOCOL_HEADER)
        ftype, _, payload = read_frame(self._sock)
        buf = memoryview(payload)
        class_id, method_id = struct.unpack_from(">HH", buf, 0)
        if (ftype, class_id, method_id) != (FRAME_METHOD, 10, 10):
            raise ConnectionError("expected Connection.Start")
        start_ok = method(
            10,
            11,
            EMPTY_TABLE  # client-properties
            + shortstr("PLAIN")
            + longstr(b"\x00" + username.encode() + b"\x00" + password.encode())
            + shortstr("en_US"),
        )
        self._sock.sendall(frame(FRAME_METHOD, 0, start_ok))
        ftype, _, payload = read_frame(self._sock)
        class_id, method_id = struct.unpack_from(">HH", payload, 0)
        if (class_id, method_id) != (10, 30):
            raise ConnectionError("expected Connection.Tune")
        channel_max, frame_max, hb = struct.unpack_from(">HIH", payload, 4)
        self._frame_max = min(frame_max or 131072, 131072)
        # Heartbeat negotiation: accept the server's proposal (0 disables).
        # A server that proposes heartbeats WILL drop silent connections
        # (~2 intervals), so an idle publisher must send them — and we in
        # turn treat >2 intervals of inbound silence as a dead peer (the
        # read timeout below), instead of blocking forever on a TCP
        # connection whose other end is gone.
        self._heartbeat = hb
        tune_ok = method(
            10, 31, struct.pack(">HIH", channel_max, self._frame_max, hb)
        )
        self._sock.sendall(frame(FRAME_METHOD, 0, tune_ok))
        open_ = method(10, 40, shortstr(vhost) + shortstr("") + bytes([0]))
        self._sock.sendall(frame(FRAME_METHOD, 0, open_))
        ftype, _, payload = read_frame(self._sock)
        class_id, method_id = struct.unpack_from(">HH", payload, 0)
        if (class_id, method_id) != (10, 41):
            raise ConnectionError("expected Connection.OpenOk")

    def _rpc(self, expect: tuple[int, int], method_payload: bytes):
        """Send a method on channel 1 and block for the expected reply
        (dispatched by the reader thread)."""
        with self._rpc_lock:
            if self._closed:
                raise ConnectionError(
                    f"AMQP connection is closed (rpc {expect})"
                )
            # Correlation token: the reader echoes the token it read from
            # _rpc_expect back alongside the reply it stores, and the
            # waiter validates it. This catches a descheduled reader
            # delivering a previous RPC's reply into a fresh slot. It is
            # defense-in-depth, not a full fix for late replies: the real
            # guarantee is below — an RPC TIMEOUT FAILS THE CONNECTION,
            # because once an expected reply is in flight but untracked,
            # no tag can resynchronize the channel's request/reply stream
            # (a same-method retry could still adopt the late reply).
            self._rpc_seq += 1
            token = self._rpc_seq
            self._rpc_expect = (expect, token)
            self._rpc_reply = None  # fresh slot: reader stores, we read  # gomelint: disable=GL702 — event-handoff slot (see __init__)
            self._rpc_event.clear()
            try:
                with self._lock:
                    self._send(frame(FRAME_METHOD, 1, method_payload))
                if not self._rpc_event.wait(self.SYNC_WAIT_S):
                    # The reply is now an untracked in-flight frame; any
                    # further sync RPC on this channel could adopt it.
                    # Fail the connection: callers reconnect fresh.
                    self._closed = True  # gomelint: disable=GL702 — one-way latch (see __init__)
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    raise ConnectionError(
                        f"AMQP rpc timeout waiting for {expect}; "
                        "connection failed (reply stream unsyncable)"
                    )
                stored = self._rpc_reply
                if stored is None:  # reader died while we waited
                    raise ConnectionError(
                        f"AMQP connection failed while waiting for {expect}"
                    )
                got_token, reply = stored
                if got_token != token or (reply[0], reply[1]) != expect:
                    # Same unsyncable state as the timeout above: OUR
                    # reply is still in flight and untracked, so a retry
                    # on this connection could adopt it. Fail the
                    # connection before raising.
                    self._closed = True  # gomelint: disable=GL702 — one-way latch (see __init__)
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    raise ConnectionError(
                        f"AMQP stale rpc reply {reply[:2]} (token "
                        f"{got_token}), wanted {expect} (token {token})"
                    )
                return reply
            finally:
                # Cleared on EVERY exit (success, timeout, send failure):
                # a timed-out RPC that left expect set would otherwise let
                # its late reply be stored into the NEXT rpc's fresh slot.
                self._rpc_expect = None


    def _send(self, data: bytes) -> None:
        """All post-handshake writes go through here. The socket-level
        timeout is the heartbeat-expiry RECV bound (2*hb), which would
        also cut off sendall() mid-frame on a slow-but-alive link (large
        publishes up to frame_max can legitimately take longer than one
        window). So writes loop send() with a progress check: a window
        that moves ANY bytes resets the clock, and only two consecutive
        zero-progress windows (~4*hb with no bytes accepted — the peer's
        receive window has been closed for two full expiry periods) fail
        the connection. A failed/desynced write leaves an unknown amount
        of a frame on the wire — framing is unrecoverable, so the
        connection is marked closed and the caller gets the documented
        ConnectionError, never a raw socket.timeout + desynced retry.

        Progress alone is not liveness: a peer trickling one byte per
        window would reset the stall counter forever while this thread
        holds the write lock (wedging heartbeats and every RPC behind
        it). So the whole frame also gets an aggregate deadline — two
        full windows of grace plus a 64 KB/s floor rate — after which a
        technically-moving-but-dead-slow link is failed like a stalled
        one."""
        try:
            timeout = self._sock.gettimeout()
            deadline = (
                time.monotonic() + 2.0 * timeout + len(data) / 65536.0
                if timeout
                else None
            )
            with memoryview(data) as mv:
                off = 0
                stalled_windows = 0
                while off < len(mv):
                    if self._closed:
                        # The reader already declared the connection dead
                        # (heartbeat expiry / peer close); don't keep
                        # pushing bytes at a corpse while holding _lock.
                        raise ConnectionError("connection closed mid-send")
                    if deadline is not None and time.monotonic() > deadline:
                        raise socket.timeout(
                            f"send of {len(data)}B below floor rate"
                        )
                    try:
                        sent = self._sock.send(mv[off:])
                    except socket.timeout:
                        stalled_windows += 1
                        if stalled_windows >= 2:
                            raise
                        continue
                    if sent:
                        stalled_windows = 0
                    else:
                        # A zero-byte send (peer-shutdown edge on some
                        # platforms) is a stalled window too: without this
                        # the loop would busy-spin holding _lock until the
                        # aggregate deadline.
                        stalled_windows += 1
                        if stalled_windows >= 2:
                            raise socket.timeout(
                                "send made no progress (zero-byte sends)"
                            )
                    off += sent
        except (socket.timeout, OSError) as e:
            self._closed = True  # gomelint: disable=GL701 — one-way latch (see __init__)
            try:
                self._sock.close()
            except OSError:
                pass
            raise ConnectionError(f"AMQP send failed: {e}") from e

    def _heartbeat_loop(self) -> None:
        """Outbound heartbeats at half the negotiated interval (idle
        publishers would otherwise be dropped by a heartbeat-enforcing
        broker). Any frame counts as liveness traffic per spec, but
        unconditional heartbeats are simpler and always sufficient."""
        hb = frame(FRAME_HEARTBEAT, 0, b"")
        while not self._closed:
            time.sleep(self._heartbeat / 2.0)
            if self._closed:
                return
            try:
                with self._lock:
                    if self._closed:
                        return
                    self._send(hb)
            except OSError:
                return

    def _read_loop(self) -> None:
        try:
            while not self._closed:
                try:
                    ftype, channel, payload = read_frame(self._sock)
                except socket.timeout:
                    raise ConnectionError(
                        f"AMQP heartbeat expired: no traffic from peer in "
                        f"{2 * self._heartbeat}s"
                    ) from None
                if ftype == FRAME_HEARTBEAT:
                    continue
                if ftype == FRAME_METHOD:
                    class_id, method_id = struct.unpack_from(">HH", payload, 0)
                    if (class_id, method_id) == (60, 60):  # Basic.Deliver
                        buf = memoryview(payload)
                        off = 4
                        _tag, off = read_shortstr(buf, off)
                        dtag, redel = struct.unpack_from(">QB", buf, off)
                        self._pending_deliver = (
                            (dtag, bool(redel)), bytearray(), [0], [None]
                        )
                        continue
                    if (class_id, method_id) == (60, 80) and self._confirm:
                        # Publisher confirm: Basic.Ack from the broker.
                        # Tags are sequential per channel and acked in
                        # order (multiple or not), so the high-water mark
                        # is the confirmation frontier.
                        tag, _mult = struct.unpack_from(">QB", payload, 4)
                        with self._ack_cond:
                            if tag > self._confirmed:
                                self._confirmed = tag
                            self._ack_cond.notify_all()
                        continue
                    # Benign off-lock read: one reference load under the
                    # GIL; a stale value only means a reply is dropped or
                    # token-rejected, which the waiter's timeout/token
                    # validation is designed to absorb.
                    expect = self._rpc_expect  # gomelint: disable=GL402 — see above
                    if expect is not None and expect[0] == (
                        class_id,
                        method_id,
                    ):
                        # Event-handoff slot (see __init__): the store
                        # happens-before the waiter's read via _rpc_event.
                        self._rpc_reply = (  # gomelint: disable=GL701 — see above
                            expect[1],
                            (class_id, method_id, payload),
                        )
                        self._rpc_event.set()
                        continue
                    if (class_id, method_id) == (10, 50):  # Connection.Close
                        with self._lock:
                            self._sock.sendall(
                                frame(FRAME_METHOD, 0, method(10, 51))
                            )
                        raise ConnectionError("broker closed the connection")
                    if (class_id, method_id) == (20, 40):  # Channel.Close
                        # Server killed our (only) channel — acknowledge,
                        # then fail the queue loudly: every later op
                        # raises instead of publishing into a dead
                        # channel. (Previously this was silently ignored.)
                        code, = struct.unpack_from(">H", payload, 4)
                        with self._lock:
                            self._sock.sendall(
                                frame(FRAME_METHOD, channel, method(20, 41))
                            )
                        raise ConnectionError(
                            f"broker closed the channel (code {code})"
                        )
                    continue  # unsolicited method we don't care about
                if ftype == FRAME_HEADER and self._pending_deliver:
                    (size,) = struct.unpack_from(">Q", payload, 4)
                    (flags,) = struct.unpack_from(">H", payload, 12)
                    if flags & FLAG_HEADERS:
                        hdrs, _ = read_table(memoryview(payload), 14)
                        self._pending_deliver[3][0] = hdrs or None
                    self._pending_deliver[2][0] = size
                    if size == 0:
                        self._complete_delivery()
                    continue
                if ftype == FRAME_BODY and self._pending_deliver:
                    self._pending_deliver[1].extend(payload)
                    if (
                        len(self._pending_deliver[1])
                        >= self._pending_deliver[2][0]
                    ):
                        self._complete_delivery()
        except (ConnectionError, OSError):
            if not self._closed:
                self._closed = True  # gomelint: disable=GL701 — one-way latch (see __init__)
            # Fail any in-flight RPC NOW (it would otherwise block its
            # full timeout against a connection that is already dead) —
            # but never clobber a reply already stored: the reader can
            # die right after delivering a success, and the waiter must
            # still see it. _rpc nulls the slot before each send, so a
            # None here means no reply genuinely arrived.
            self._rpc_event.set()
            self._notify_publish()  # wake any poll_batch waiter
            # Fail publishers waiting on confirms. getattr: protocol-level
            # tests build partially-initialized instances via __new__.
            ack_cond = getattr(self, "_ack_cond", None)
            if ack_cond is not None:
                with ack_cond:
                    ack_cond.notify_all()

    def _complete_delivery(self) -> None:
        (dtag, redelivered), body, _, hdr = self._pending_deliver
        self._pending_deliver = None
        with self._lock:
            self._buffer.append(bytes(body))
            self._tags.append(dtag)
            self._redelivered.append(redelivered)
            self._hdrs.append(hdr[0])
        self._notify_publish()

    def _ensure_consuming(self) -> None:
        if self._consuming:
            return
        self._rpc(
            (60, 21),
            method(
                60,
                20,
                struct.pack(">H", 0)
                + shortstr(self.name)
                + shortstr(f"c-{self.name}")
                + bytes([0])  # no-local/no-ack/exclusive/no-wait
                + EMPTY_TABLE,
            ),
        )
        # Only after ConsumeOk: a failed/timed-out RPC must leave the flag
        # unset so the next poll retries instead of silently never
        # consuming again.
        self._consuming = True

    def _sync(self) -> None:
        """Read-side loopback barrier: wait (bounded) until every message
        WE published has arrived back via consume."""
        self._ensure_consuming()
        deadline = time.monotonic() + self.SYNC_WAIT_S
        while True:
            with self._lock:
                caught_up = len(self._buffer) >= self._published
            if caught_up or self._closed or time.monotonic() >= deadline:
                break
            self._wait_for_publish(0.002)

    # -- Queue contract ----------------------------------------------------
    supports_headers = True

    def publish(self, body: bytes, headers: dict | None = None) -> int:
        with self._lock:
            if self._closed:
                raise ConnectionError("AMQP connection is closed")
            pub = method(
                60,
                40,
                struct.pack(">H", 0)
                + shortstr("")  # default exchange
                + shortstr(self.name)  # routing key = queue
                + bytes([0]),
            )
            parts = [frame(FRAME_METHOD, 1, pub)] + content_frames(
                1, body, self._frame_max, headers=headers
            )
            self._send(b"".join(parts))
            if not self._confirm:
                off = self._published
                self._published += 1
                return off
            self._pub_seq += 1
            seq = self._pub_seq
        # Confirm mode: block (outside the write lock) until the broker's
        # Basic.Ack covers this publish. No ack within the window, or a
        # dead connection, is a FAILED publish — the message may or may
        # not be enqueued, and only the caller's reconnect+retry (against
        # a broker that drops pre-enqueue) or redelivery dedup can resolve
        # that; we fail loudly instead of guessing.
        deadline = time.monotonic() + self.SYNC_WAIT_S
        with self._ack_cond:
            while self._confirmed < seq and not self._closed:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._ack_cond.wait(left)
            if self._confirmed < seq:
                raise ConnectionError(
                    f"publish {seq} unconfirmed (confirmed through "
                    f"{self._confirmed}; closed={self._closed})"
                )
        with self._lock:
            off = self._published
            self._published += 1
            return off

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        self._sync()
        with self._lock:
            return [
                Message(
                    offset=i, body=self._buffer[i], headers=self._hdrs[i]
                )
                for i in range(
                    offset, min(offset + max_n, len(self._buffer))
                )
            ]

    def end_offset(self) -> int:
        self._sync()
        with self._lock:
            return max(len(self._buffer), self._published)

    def depth(self) -> int:
        # Deliberately NO _sync(): this is the scrape-time lag gauge
        # (bus.base.export_queue_metrics) and a /metrics scrape must
        # never do a broker round trip. Reads the local arrival/publish
        # view — momentarily stale until the next consume-path sync,
        # never blocking.
        with self._lock:
            return max(len(self._buffer), self._published) - self._committed

    def committed(self) -> int:
        with self._lock:
            return self._committed

    def commit(self, offset: int) -> None:
        self._ensure_consuming()
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"commit {offset} behind committed {self._committed}"
                )
            end = max(len(self._buffer), self._published)
            if offset > end:
                raise ValueError(f"commit {offset} past end {end}")
            self._committed = offset
            if offset > self._acked_through and offset <= len(self._tags):
                ack = method(
                    60, 80, struct.pack(">QB", self._tags[offset - 1], 1)
                )
                self._send(frame(FRAME_METHOD, 1, ack))
                self._acked_through = offset

    def rollback(self, offset: int) -> None:
        with self._lock:
            if offset > self._committed:
                raise ValueError("rollback must move backwards")
            # Local replay: arrivals stay buffered, so rewinding the
            # pointer replays them (broker acks already sent stand — the
            # buffer IS the replay log for this process's lifetime).
            self._committed = offset

    def truncate_to(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError("cannot truncate below committed")
            # Individually ack ONLY the dropped tail so the broker forgets
            # it (recovery regenerates it by deterministic replay). A
            # multiple-ack through the last tag would also ack the
            # uncommitted, undropped middle — which must stay redeliverable.
            for tag in self._tags[offset:]:
                ack = method(60, 80, struct.pack(">QB", tag, 0))
                self._send(frame(FRAME_METHOD, 1, ack))
            del self._buffer[offset:]
            del self._tags[offset:]
            del self._redelivered[offset:]
            del self._hdrs[offset:]
            self._published = min(self._published, offset)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True  # gomelint: disable=GL702 — one-way latch (see __init__)
            try:
                close = method(
                    10,
                    50,
                    struct.pack(">H", 200)  # reply-code
                    + shortstr("bye")
                    + struct.pack(">HH", 0, 0),  # offending class/method
                )
                self._sock.sendall(frame(FRAME_METHOD, 0, close))
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass


# --- supervised client ---------------------------------------------------


class SupervisedAmqpQueue(Queue):
    """An AmqpQueue under supervision (utils.resilience.Supervised): every
    ConnectionError tears the TCP connection down and the next operation
    reconnects under backoff + circuit breaker, re-declares the topology
    (AmqpQueue.__init__ declares idempotently), resumes the consume, and
    retries. This is the caller the raw client's fail-loudly contract
    ("callers reconnect fresh", _rpc) was always waiting for.

    Offset/commit contract across reconnects — the wrapper owns the
    arrival log, the inner client is a disposable transport:

      * wrapper offset = index into the wrapper-lifetime arrival log
        `_log`, which is NEVER truncated by a reconnect;
      * after a reconnect the broker redelivers everything it still holds
        unacked — including messages whose ack was in flight when the
        connection died. Every redelivered message was delivered to THIS
        wrapper before (single-logical-consumer topology, the repo's
        queue contract), so it is already in the log: arrivals with the
        Basic.Deliver REDELIVERED bit are skipped, fresh ones appended.
        Offsets therefore stay stable and nothing is ever read twice or
        lost, whatever the broker's ack frontier was at the crash;
      * commit() is LOCAL and never raises on transport faults: the
        committed offset is this process's read cursor, while the broker
        ack that makes it durable is sent best-effort and DEFERRED when
        the connection is down (flushed by the next successful drain). A
        process crash still replays from the broker's acked point
        (at-least-once, same as the raw client).

    Publishes run in publisher-confirm mode: publish() returning means
    the broker ENQUEUED the message, so a reconnect retry after a failed
    publish is redeliver-safe (a broker that died before the enqueue
    never confirmed it). The residual window — broker enqueues, then dies
    before the confirm reaches us — duplicates on retry, exactly as with
    any AMQP publisher; the drills script their kills on the
    drop-before-enqueue fault modes this repo's fake broker provides."""

    SYNC_WAIT_S = AmqpQueue.SYNC_WAIT_S

    def __init__(
        self,
        name: str,
        host: str = "127.0.0.1",
        port: int = 5672,
        username: str = "guest",
        password: str = "guest",
        vhost: str = "/",
        connect_timeout_s: float = 3.0,
        policy=None,
        breaker=None,
    ):
        from ..utils.resilience import Supervised

        self.name = name
        self._state = threading.Lock()  # log/cursor fields below
        self._io = threading.RLock()  # serializes compound queue ops
        self._log: list[bytes] = []  # guarded by self._state
        self._log_hdrs: list[dict | None] = []  # guarded by self._state
        self._committed = 0  # guarded by self._state
        self._published = 0  # guarded by self._state
        self._consuming = False  # guarded by self._state
        # Per-inner-connection cursors (reset by _on_reconnect): _n0 is
        # the log length when the connection opened, _r counts arrivals
        # skipped as redelivered, _inner_seen counts inner arrivals the
        # wrapper has consumed. Inner arrival j corresponds to log
        # position (_n0 - _r) + j — the formula the deferred broker acks
        # use to translate the committed cursor into a delivery tag.
        self._n0 = 0  # guarded by self._state
        self._r = 0  # guarded by self._state
        self._inner_seen = 0  # guarded by self._state

        def factory():
            # confirm=True: publish() returning means ENQUEUED — the
            # property that makes reconnect-with-retry redeliver-safe
            # (an unconfirmed publish is retried; a broker that died
            # before the enqueue never acked it).
            return AmqpQueue(
                name, host, port, username, password, vhost,
                connect_timeout_s, confirm=True,
            )

        self._sup = Supervised(
            f"amqp:{name}",
            factory,
            policy=policy,
            breaker=breaker,
            on_reconnect=[self._on_reconnect],
        )
        # Dial eagerly, ONE attempt: a dead broker at construction is a
        # deployment problem make_bus handles (loud memory fallback), not
        # something to hide behind a 15s backoff schedule.
        try:
            self._sup.prime()
        except BaseException:
            self._sup.close()  # unregister from the supervisor table
            raise

    # -- reconnect re-setup ------------------------------------------------
    def _on_reconnect(self, q: AmqpQueue) -> None:
        """Fresh connection: topology is already re-declared (the client
        constructor declares idempotently). Reset the per-connection
        cursors — the log itself is untouched; redelivered arrivals dedup
        against it (class docstring) — and resume the consume so
        redelivery starts flowing without waiting for the next read."""
        with self._state:
            self._n0 = len(self._log)
            self._r = 0
            self._inner_seen = 0
            consuming = self._consuming
        if consuming:
            q._ensure_consuming()

    def supervisor(self):
        return self._sup

    # -- internals ---------------------------------------------------------
    def _drain(self, sync: bool) -> None:
        """Pull new arrivals from the inner client into the wrapper log and
        flush any deferred broker acks. With sync=True, wait (bounded) for
        the loopback catch-up: everything THIS wrapper published should be
        back in the log before a read-side call returns (the raw client's
        publish-then-read determinism, across reconnects). Transport
        faults leave the log as-is — callers' poll loops retry."""
        deadline = time.monotonic() + self.SYNC_WAIT_S

        def pull(q: AmqpQueue):
            with self._state:
                self._consuming = True
                start = self._inner_seen
            msgs = q.read_from(start, 1 << 30)
            with self._state:
                for m in msgs:
                    if m.offset < self._inner_seen:
                        continue
                    if q._redelivered[m.offset]:
                        # Replayed delivery: already in the log (class
                        # docstring); count it so the tag<->log-position
                        # mapping stays aligned, but do not append.
                        self._r += 1
                    else:
                        self._log.append(m.body)
                        self._log_hdrs.append(m.headers)
                    self._inner_seen = m.offset + 1
                # Deferred broker acks: ack through the committed cursor
                # as far as arrivals allow. Inner arrival j maps to log
                # position (_n0 - _r) + j; the estimate is conservative
                # while redeliveries are still streaming in (_r only
                # grows, so the target only grows — never over-acks).
                target = min(
                    self._committed - self._n0 + self._r, len(q._tags)
                )
            if target > q._committed:
                q.commit(target)

        while True:
            try:
                self._sup.call(pull, retry_op=False)
            except (ConnectionError, OSError):
                return  # degraded: serve what the log already has
            with self._state:
                caught_up = len(self._log) >= self._published
            if not sync or caught_up or time.monotonic() >= deadline:
                return
            time.sleep(0.002)

    # -- Queue contract ----------------------------------------------------
    supports_headers = True

    def publish(self, body: bytes, headers: dict | None = None) -> int:
        with self._io:
            self._sup.call(lambda q: q.publish(body, headers=headers))
            with self._state:
                off = self._published
                self._published += 1
            return off

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        with self._io:
            self._drain(sync=True)
            with self._state:
                return [
                    Message(
                        offset=i,
                        body=self._log[i],
                        headers=self._log_hdrs[i],
                    )
                    for i in range(
                        offset, min(offset + max_n, len(self._log))
                    )
                ]

    def end_offset(self) -> int:
        with self._io:
            self._drain(sync=True)
            with self._state:
                return max(len(self._log), self._published)

    def depth(self) -> int:
        # Scrape-time lag gauge: no _io lock, no drain — a wedged broker
        # (or a reconnect in progress under _io) must not block /metrics.
        # The local log/cursor view is momentarily stale, never torn.
        with self._state:
            return max(len(self._log), self._published) - self._committed

    def committed(self) -> int:
        with self._state:
            return self._committed

    def commit(self, offset: int) -> None:
        with self._io:
            with self._state:
                if offset < self._committed:
                    raise ValueError(
                        f"commit {offset} behind committed {self._committed}"
                    )
                end = max(len(self._log), self._published)
                if offset > end:
                    raise ValueError(f"commit {offset} past end {end}")
                self._committed = offset
                self._consuming = True
            # Broker ack rides the next successful drain if this fails —
            # commit-after-publish must never die on a transport fault.
            self._drain(sync=False)

    def rollback(self, offset: int) -> None:
        with self._state:
            if offset > self._committed:
                raise ValueError("rollback must move backwards")
            self._committed = offset

    def truncate_to(self, offset: int) -> None:
        with self._io:
            with self._state:
                if offset < self._committed:
                    raise ValueError("cannot truncate below committed")
                inner_off = offset - self._n0 + self._r

            def drop(q: AmqpQueue):
                if inner_off < len(q._tags):
                    q.truncate_to(max(inner_off, 0))

            try:
                self._sup.call(drop, retry_op=False)
            except (ConnectionError, OSError):
                pass  # tail redelivers; recovery truncates again
            with self._state:
                del self._log[offset:]
                del self._log_hdrs[offset:]
                self._published = min(self._published, offset)
                self._inner_seen = min(
                    self._inner_seen, max(inner_off, 0)
                )

    def close(self) -> None:
        self._sup.close()


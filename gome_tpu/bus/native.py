"""ctypes binding for the native (C++) file-log queue backend.

`NativeFileQueue` is drop-in interchangeable with the Python `FileQueue` —
same Queue interface AND the same on-disk format, so a directory written by
one can be reopened by the other (tested both directions). Selected via
bus.backend = "cfile"; falls back to the Python backend with a warning when
the native library cannot be built (no toolchain).

Native additions over the Python backend: `publish_batch` amortizes one
write+fsync over a whole micro-batch of events (the consumer publishes all
of a batch's MatchResults in one call), and the record scan/read paths run
without interpreter overhead.
"""

from __future__ import annotations

import ctypes
import os
import threading

from .base import Message, Queue, _Waitable

_lib = None
_lib_err: str | None = None


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    try:
        import importlib.util

        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        build_py = os.path.join(repo_root, "native", "build.py")
        spec = importlib.util.spec_from_file_location(
            "gome_tpu._native_build", build_py
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        path = mod.build()
        if path is None:
            _lib_err = "g++ unavailable or compile failed"
            return None
        lib = ctypes.CDLL(path)
        lib.gq_open.restype = ctypes.c_void_p
        lib.gq_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.gq_close.argtypes = [ctypes.c_void_p]
        lib.gq_publish_batch.restype = ctypes.c_int64
        lib.gq_publish_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32,
        ]
        lib.gq_end_offset.restype = ctypes.c_int64
        lib.gq_end_offset.argtypes = [ctypes.c_void_p]
        lib.gq_committed.restype = ctypes.c_int64
        lib.gq_committed.argtypes = [ctypes.c_void_p]
        lib.gq_read_from.restype = ctypes.c_int64
        lib.gq_read_from.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        for name in ("gq_commit", "gq_rollback", "gq_truncate_to"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib = lib
    except Exception as e:  # pragma: no cover - environment-specific
        _lib_err = str(e)
    return _lib


def native_available() -> bool:
    return _load() is not None


class NativeFileQueue(_Waitable, Queue):
    def __init__(self, name: str, path_base: str, fsync: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native queue unavailable: {_lib_err}")
        self.name = name
        self._lib = lib
        os.makedirs(os.path.dirname(path_base) or ".", exist_ok=True)
        self._h = lib.gq_open(path_base.encode(), 1 if fsync else 0)
        if not self._h:
            raise RuntimeError(f"gq_open failed for {path_base}")
        self._lock = threading.Lock()
        self._init_wait()

    def _handle(self):
        """The open native handle; raises (instead of passing NULL into C,
        which would segfault) if the queue was closed. Serialization of the
        actual operations happens in the C library (Queue::mu); the Python
        lock exists only to make close() atomic vs this check. Contract (as
        for the Python backend): stop consumers before close() — a call
        racing close() may still reach a freed handle."""
        with self._lock:
            h = self._h
        if not h:
            raise ValueError(f"queue {self.name!r} is closed")
        return h

    # -- Queue interface -----------------------------------------------------
    def publish(self, body: bytes) -> int:
        return self.publish_batch([body])

    def publish_batch(self, bodies: list[bytes]) -> int:
        """Append many records with ONE write+fsync; returns the offset of
        the first. (The native fast path the Python backend lacks.)"""
        blob = b"".join(bodies)
        n = len(bodies)
        lengths = (ctypes.c_uint32 * n)(*[len(b) for b in bodies])
        buf = (ctypes.c_ubyte * len(blob)).from_buffer_copy(blob)
        first = self._lib.gq_publish_batch(self._handle(), buf, lengths, n)
        if first < 0:
            raise OSError("native publish failed")
        self._notify_publish()
        return int(first)

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        if max_n <= 0:
            return []
        cap = 1 << 16
        while True:
            bodies = (ctypes.c_ubyte * cap)()
            lengths = (ctypes.c_uint32 * max_n)()
            n = self._lib.gq_read_from(
                self._handle(), offset, max_n, bodies, cap, lengths
            )
            if n == -2:
                raise OSError(
                    f"native read I/O error on queue {self.name!r} (log "
                    "file unreadable)"
                )
            if n >= 0:
                out = []
                pos = 0
                for i in range(n):
                    ln = lengths[i]
                    out.append(
                        Message(
                            offset=offset + i,
                            body=bytes(bodies[pos : pos + ln]),
                        )
                    )
                    pos += ln
                return out
            cap *= 4  # n == -1: caller buffer too small; grow and retry
            if cap > 1 << 30:
                raise OSError("native read: record set exceeds 1 GiB buffer")

    def end_offset(self) -> int:
        return int(self._lib.gq_end_offset(self._handle()))

    def committed(self) -> int:
        return int(self._lib.gq_committed(self._handle()))

    def commit(self, offset: int) -> None:
        rc = self._lib.gq_commit(self._handle(), offset)
        if rc == -1:
            raise ValueError(
                f"commit out of range: {offset} (committed={self.committed()},"
                f" end={self.end_offset()})"
            )
        if rc != 0:
            raise OSError("native commit failed")

    def rollback(self, offset: int) -> None:
        rc = self._lib.gq_rollback(self._handle(), offset)
        if rc == -1:
            raise ValueError(f"rollback going forwards: {offset}")
        if rc != 0:
            raise OSError("native rollback failed")

    def truncate_to(self, offset: int) -> None:
        rc = self._lib.gq_truncate_to(self._handle(), offset)
        if rc == -1:
            raise ValueError(f"cannot truncate below committed: {offset}")
        if rc != 0:
            raise OSError("native truncate failed")

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.gq_close(self._h)
                self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

"""Columnar binary wire frames — the high-throughput order/event transport.

The reference ships one JSON document per order (engine.go:36
`json.Marshal(node)`) and one per fill (engine.go:149-158). At the 1M+
orders/sec the TPU engine sustains, per-message JSON costs more than the
matching itself (~1-2 µs/order for encode+decode+object churn vs ~0.07 µs
of device time). These frames carry a whole micro-batch as numpy columns:

  * ORDER frame ("GCO1"): one bus message holding N orders — fixed-width
    numeric columns plus dictionary-encoded symbols/uuids and
    padded-fixed-width oids, all decodable with `np.frombuffer` (no
    per-order Python).
  * EVENT frame ("GCE1"): one bus message holding an EventBatch's columns
    plus the id-table slices it references — the matchOrder feed at
    device speed. `decode_event_frame(...).to_results()` recovers the
    exact MatchResult objects, and `EventBatch.to_json_lines()` the exact
    reference JSON, so parity surfaces are unchanged; the binary hop is an
    internal transport choice (config: service.match_wire).

Frames are self-describing (magic + version); the consumer sniffs the
first byte to distinguish them from reference-parity JSON messages ('{'),
so both producers can share one queue during migration.

Layout conventions: little-endian, u32 lengths, arrays written back to
back in column order. Strings: `dict` columns are a u32 count + packed
(u16 len + bytes) uniques + u32 idx[n]; `padded` columns are a u16 width +
n*width bytes (numpy 'S{width}' — embedded NULs cannot occur in ids that
round-trip the reference's JSON contract).
"""

from __future__ import annotations

import struct
from collections import OrderedDict

import numpy as np

from ..utils.cache import IdentityCache

ORDER_MAGIC = b"GCO2"
ORDER_MAGIC_V1 = b"GCO1"  # decode-compat: pre-cache dict-column layout
#: GCO2 + one trailing padded per-order trace-context column (utils.trace
#: "<id>@<t>" strings; '' = untraced). Emitted only when at least one
#: order carries a context, so tracing-off traffic stays byte-identical
#: GCO2 — zero wire overhead on the hot path.
ORDER_MAGIC_TRACED = b"GCO3"
#: The columnar-front-door layout (round 11): a HEADER (u32 total order
#: count + u32 block count) followed by back-to-back GCO2-style BODIES
#: ("blocks"), each with its own count/dictionaries. The gateway's
#: columnar admit encodes one block per gRPC batch on the handler thread;
#: the batcher's flush is then a pure byte-join — no decode/re-encode
#: round-trip, no per-order Python anywhere between proto and frame.
#: Single-block frames decode through the exact GCO2 body reader (same
#: dict-cache identity semantics); multi-block frames merge on the
#: consumer side, which has ~13x the gateway's CPU headroom (HOSTPROF).
ORDER_MAGIC_BLOCKS = b"GCO4"
EVENT_MAGIC = b"GCE1"
#: GCE1 + one u64 base sequence number after the count: event i in the
#: frame is matchfeed seq ``seq0 + i`` (exactly-once across restarts —
#: ISSUE 11). Emitted only when the publisher stamps seqs, so legacy
#: traffic stays byte-identical GCE1 (the GCO3 migration story again).
EVENT_MAGIC_SEQ = b"GCE2"

# Order columns: (name, dtype) fixed-width part.
_ORDER_NUM = (
    ("action", np.uint8),
    ("side", np.uint8),
    ("kind", np.uint8),
    ("price", np.int64),
    ("volume", np.int64),
)

_EVENT_NUM = (
    # mirrors gome_tpu.engine.events._COLUMNS minus arrival (frame-local
    # order IS arrival order)
    ("is_cancel", np.uint8),
    ("symbol_id", np.int64),
    ("taker_uid", np.int64),
    ("taker_oid", np.int64),
    ("taker_side", np.int8),
    ("taker_price", np.int64),
    ("taker_volume", np.int64),
    ("maker_uid", np.int64),
    ("maker_oid", np.int64),
    ("fill_price", np.int64),
    ("maker_volume", np.int64),
    ("match_volume", np.int64),
    ("is_market", np.uint8),
)


# Decoded dict-column uniques, content-addressed by their raw wire bytes.
# Real order flow re-sends the same symbol/uuid dictionary frame after
# frame (exchange symbol universes are stable); decoding 10K+ strings per
# frame costs ~0.1 us/order, so the decoder hashes the uniques region and
# reuses the previously decoded list. HITS RETURN THE *SAME LIST OBJECT*,
# which downstream hot paths use as their own IdentityCache key (the
# engine's symbol->lane map, the pre-pool's packed key bytes) — decoded
# dicts are shared and must be treated as immutable.
# The cache is module-global and SHARED across all engines/threads in the
# process: values are immutable decoded lists (see above), so cross-thread
# reuse is safe; mutation relies on the GIL's per-op atomicity plus
# KeyError-tolerant eviction below. Eviction is one-entry LRU (oldest
# insertion out, hits refreshed), so a workload with >32 live dictionaries
# degrades to re-decoding only its coldest dict per frame instead of the
# wholesale clear() this used to do (which evicted every hot entry too).
_dict_cache: "OrderedDict[bytes, list[str]]" = OrderedDict()
_DICT_CACHE_MAX = 32

# Writer-side mirror: list object -> encoded uniques region (the gateway
# re-encodes the same dictionary every frame).
_pack_cache = IdentityCache()


def _dict_uniques_bytes(values) -> bytes:
    parts = [struct.pack("<I", len(values))]
    for s in values:
        b = s.encode() if isinstance(s, str) else s
        parts.append(struct.pack("<H", len(b)))
        parts.append(b)
    return b"".join(parts)


def _pack_dict_column(values: list[str], idx: np.ndarray) -> bytes:
    uniques = _pack_cache.get(values)
    if uniques is None:
        uniques = _pack_cache.put(values, _dict_uniques_bytes(values))
    return (
        struct.pack("<I", len(uniques))
        + uniques
        + np.ascontiguousarray(idx, np.uint32).tobytes()
    )


def _parse_dict_uniques(region: bytes) -> list[str]:
    (count,) = struct.unpack_from("<I", region, 0)
    off = 4
    values = []
    for _ in range(count):
        (ln,) = struct.unpack_from("<H", region, off)
        off += 2
        values.append(region[off : off + ln].decode())
        off += ln
    return values


def _read_dict_column(buf: memoryview, off: int, n: int):
    (nbytes,) = struct.unpack_from("<I", buf, off)
    off += 4
    region = bytes(buf[off : off + nbytes])
    off += nbytes
    values = _dict_cache.get(region)
    if values is None:
        values = _parse_dict_uniques(region)
        while len(_dict_cache) >= _DICT_CACHE_MAX:
            try:
                _dict_cache.popitem(last=False)  # LRU: evict oldest only
            except KeyError:  # concurrent evictor got there first
                break
        _dict_cache[region] = values
    else:
        try:
            _dict_cache.move_to_end(region)
        except KeyError:  # concurrently evicted; value is still valid
            pass
    idx = np.frombuffer(buf, np.uint32, n, off)
    off += 4 * n
    return values, idx, off


def _read_dict_column_v1(buf: memoryview, off: int, n: int):
    """GCO1 layout: no region-length prefix — walk the per-string lengths."""
    (count,) = struct.unpack_from("<I", buf, off)
    off += 4
    values = []
    for _ in range(count):
        (ln,) = struct.unpack_from("<H", buf, off)
        off += 2
        values.append(bytes(buf[off : off + ln]).decode())
        off += ln
    idx = np.frombuffer(buf, np.uint32, n, off)
    off += 4 * n
    return values, idx, off


def _pack_padded_column(strs) -> bytes:
    """strs: list[str] (or np 'S' array). Pads to the batch max width.
    str inputs are encoded to UTF-8 bytes FIRST — np.array(dtype='S') on
    str objects is ASCII-only and would crash on in-contract non-ASCII
    ids."""
    if isinstance(strs, np.ndarray) and strs.dtype.kind == "S":
        arr = np.ascontiguousarray(strs)
    else:
        arr = np.array(
            [s if isinstance(s, bytes) else s.encode() for s in strs],
            dtype="S",
        )
        if arr.dtype.itemsize == 0:  # all-empty edge
            arr = arr.astype("S1")
    return struct.pack("<H", arr.dtype.itemsize) + arr.tobytes()


def _read_padded_column(buf: memoryview, off: int, n: int):
    (width,) = struct.unpack_from("<H", buf, off)
    off += 2
    arr = np.frombuffer(buf, f"S{width}", n, off)
    off += width * n
    return arr, off


def encode_order_block(
    n: int,
    action: np.ndarray,
    side: np.ndarray,
    kind: np.ndarray,
    price: np.ndarray,
    volume: np.ndarray,
    symbols: list[str],
    symbol_idx: np.ndarray,
    uuids: list[str],
    uuid_idx: np.ndarray,
    oids,
) -> bytes:  # gomelint: hotpath
    """One ORDER block BODY (no magic): u32 count + numeric columns +
    dict-encoded symbols/uuids + padded oids — exactly a GCO2 body, so a
    single block prefixed with ORDER_MAGIC is a valid GCO2 frame and
    GCO4 is a pure framing of these. This is what the columnar gateway
    encodes per gRPC batch (array inputs straight from the admit masks,
    never per-order Python)."""
    parts = [struct.pack("<I", n)]
    for (_name, dt), col in zip(
        _ORDER_NUM, (action, side, kind, price, volume)
    ):
        parts.append(np.ascontiguousarray(col, dt).tobytes())
    parts.append(_pack_dict_column(symbols, symbol_idx))
    parts.append(_pack_dict_column(uuids, uuid_idx))
    parts.append(_pack_padded_column(oids))
    return b"".join(parts)


def encode_order_frame(
    n: int,
    action: np.ndarray,
    side: np.ndarray,
    kind: np.ndarray,
    price: np.ndarray,
    volume: np.ndarray,
    symbols: list[str],
    symbol_idx: np.ndarray,
    uuids: list[str],
    uuid_idx: np.ndarray,
    oids,
    traces=None,
) -> bytes:
    """Build one ORDER frame. symbols/uuids are per-batch dictionaries with
    u32 index columns; oids are raw per-order strings (padded column).
    traces: optional per-order trace-context strings ('' = untraced) —
    selects the GCO3 layout (a trailing padded column)."""
    magic = ORDER_MAGIC if traces is None else ORDER_MAGIC_TRACED
    body = encode_order_block(
        n, action, side, kind, price, volume, symbols, symbol_idx,
        uuids, uuid_idx, oids,
    )
    if traces is None:
        return magic + body
    return b"".join((magic, body, _pack_padded_column(traces)))


def encode_order_frame_blocks(blocks: list[bytes]) -> bytes:  # gomelint: hotpath
    """Pre-encoded ORDER blocks -> one GCO4 frame: magic + u32 total
    order count + u32 block count + the blocks back to back. The total
    is read off each block's leading u32 — the flush path stays a byte
    join, never a decode."""
    if not blocks:
        raise ValueError("GCO4 frame needs at least one block")
    n_total = 0
    for b in blocks:
        (n,) = struct.unpack_from("<I", b, 0)
        n_total += n
    return b"".join(
        [ORDER_MAGIC_BLOCKS, struct.pack("<II", n_total, len(blocks))]
        + list(blocks)
    )


def encode_orders(orders) -> bytes:
    """Convenience: a list of Order objects -> one ORDER frame (what a
    batching gateway produces; shared by tests, the fuzzer, and examples)."""
    n = len(orders)
    syms: list[str] = []
    uuids: list[str] = []
    sym_ix: dict[str, int] = {}
    uuid_ix: dict[str, int] = {}
    sym_idx = np.empty(n, np.uint32)
    uuid_idx = np.empty(n, np.uint32)
    action = np.empty(n, np.uint8)
    side = np.empty(n, np.uint8)
    kind = np.empty(n, np.uint8)
    price = np.empty(n, np.int64)
    volume = np.empty(n, np.int64)
    oids = []
    for i, o in enumerate(orders):
        action[i] = int(o.action)
        side[i] = int(o.side)
        kind[i] = int(o.order_type)
        price[i] = o.price
        volume[i] = o.volume
        if o.symbol not in sym_ix:
            sym_ix[o.symbol] = len(syms)
            syms.append(o.symbol)
        sym_idx[i] = sym_ix[o.symbol]
        if o.uuid not in uuid_ix:
            uuid_ix[o.uuid] = len(uuids)
            uuids.append(o.uuid)
        uuid_idx[i] = uuid_ix[o.uuid]
        oids.append(o.oid)
    traces = None
    if any(o.trace is not None for o in orders):
        traces = [o.trace or "" for o in orders]
    return encode_order_frame(
        n, action, side, kind, price, volume, syms, sym_idx, uuids,
        uuid_idx, oids, traces=traces,
    )


def _read_order_body(buf: memoryview, off: int, read_dict):
    """One ORDER body (u32 count + columns) -> (cols dict, new offset) —
    shared by the GCO1/GCO2/GCO3 frame decoders and the per-block GCO4
    loop, so every layout funnels through identical column parsing (and
    the same dict-column identity cache)."""
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    out: dict = {"n": n}
    for name, dt in _ORDER_NUM:
        out[name] = np.frombuffer(buf, dt, n, off)
        off += np.dtype(dt).itemsize * n
    out["symbols"], out["symbol_idx"], off = read_dict(buf, off, n)
    out["uuids"], out["uuid_idx"], off = read_dict(buf, off, n)
    out["oids"], off = _read_padded_column(buf, off, n)
    return out, off


# Merged multi-block dictionaries, keyed on the identity of the per-block
# uniques lists (which the _dict_cache keeps stable for a stable symbol
# universe), so a steady flow of same-shaped GCO4 frames reuses one merged
# list object — downstream identity caches (the engine's symbol->lane map,
# the native pre-pool's packed tables) keep hitting. Values pin the part
# lists so an id() can never be recycled while its key is live; the
# whole-tuple identity is re-verified on hit anyway (IdentityCache's
# discipline). Same GIL-atomicity + LRU reasoning as _dict_cache above.
_merge_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
_MERGE_CACHE_MAX = 32


def _merge_dicts(parts: list) -> tuple:
    """Per-block uniques lists -> (merged uniques list, per-block u32
    remap arrays): remap[i] is the merged id of part value i, so a
    block's index column remaps in one vectorized gather."""
    key = tuple(map(id, parts))
    hit = _merge_cache.get(key)
    if hit is not None and all(
        a is b for a, b in zip(hit[0], parts)
    ):
        try:
            _merge_cache.move_to_end(key)
        except KeyError:  # concurrently evicted; value is still valid
            pass
        return hit[1], hit[2]
    ix: dict = {}
    merged: list = []
    remaps = []
    for vals in parts:
        remap = np.empty(len(vals), np.uint32)
        for j, s in enumerate(vals):
            k = ix.get(s)
            if k is None:
                k = ix[s] = len(merged)
                merged.append(s)
            remap[j] = k
        remaps.append(remap)
    while len(_merge_cache) >= _MERGE_CACHE_MAX:
        try:
            _merge_cache.popitem(last=False)
        except KeyError:  # concurrent evictor got there first
            break
    _merge_cache[key] = (list(parts), merged, remaps)
    return merged, remaps


def _merge_order_blocks(blocks: list) -> dict:
    """Decoded GCO4 blocks -> one standard cols dict: numeric columns
    concatenate, dictionary columns merge through _merge_dicts (stable
    merged-list identity), oids concatenate with 'S' width promotion."""
    out: dict = {"n": int(sum(b["n"] for b in blocks))}
    for name, _dt in _ORDER_NUM:
        out[name] = np.concatenate([b[name] for b in blocks])
    for values_key, idx_key in (
        ("symbols", "symbol_idx"), ("uuids", "uuid_idx")
    ):
        merged, remaps = _merge_dicts([b[values_key] for b in blocks])
        out[values_key] = merged
        out[idx_key] = np.concatenate(
            [remap[b[idx_key]] for remap, b in zip(remaps, blocks)]
        )
    out["oids"] = np.concatenate([b["oids"] for b in blocks])
    return out


def decode_order_frame(payload: bytes) -> dict:
    """ORDER frame -> dict of numpy columns + string dictionaries:
    {action,side,kind,price,volume: np arrays; symbols: list[str],
    symbol_idx: u32 array; uuids, uuid_idx; oids: np 'S' array}. All
    layouts (GCO1-GCO4) normalize to this one contract, so the consumer
    and engine frame path never see the wire version."""
    buf = memoryview(payload)
    magic = bytes(buf[:4])
    if magic == ORDER_MAGIC_BLOCKS:
        n_total, n_blocks = struct.unpack_from("<II", buf, 4)
        off = 12
        blocks = []
        for _ in range(n_blocks):
            block, off = _read_order_body(buf, off, _read_dict_column)
            blocks.append(block)
        if n_blocks == 1:
            out = blocks[0]  # the GCO2-identical fast path
        else:
            out = _merge_order_blocks(blocks)
        if out["n"] != n_total:
            raise ValueError(
                f"GCO4 header count {n_total} != block sum {out['n']}"
            )
        return out
    if magic not in (ORDER_MAGIC, ORDER_MAGIC_V1, ORDER_MAGIC_TRACED):
        raise ValueError("not an ORDER frame")
    read_dict = (
        _read_dict_column_v1 if magic == ORDER_MAGIC_V1 else _read_dict_column
    )
    out, off = _read_order_body(buf, 4, read_dict)
    if magic == ORDER_MAGIC_TRACED:
        # Per-order trace contexts ride the frame; engine code never reads
        # this key (the consumer peels it off before processing).
        out["trace"], off = _read_padded_column(buf, off, out["n"])
    return out


def is_frame(body: bytes) -> bool:
    return body[:1] == b"G"


def _pack_id_table(table, used: np.ndarray) -> bytes:
    """Frame-local id table: u32 count + padded 'S' column of the USED
    strings. A native-interner table (gather_padded) packs without
    materializing ANY Python strings; Python-list tables gather via
    operator.itemgetter at C speed."""
    count = len(used)
    gather = getattr(table, "gather_padded", None)
    if gather is not None and count:
        arr = gather(np.ascontiguousarray(used, np.int64))
        return (
            struct.pack("<I", count)
            + struct.pack("<H", arr.dtype.itemsize)
            + arr.tobytes()
        )
    import operator

    if count == 0:
        gathered = []
    elif count == 1:
        gathered = [table[int(used[0])]]
    else:
        gathered = list(operator.itemgetter(*used.tolist())(table))
    return struct.pack("<I", count) + _pack_padded_column(gathered)


def _read_id_table(buf: memoryview, off: int):
    (count,) = struct.unpack_from("<I", buf, off)
    off += 4
    arr, off = _read_padded_column(buf, off, count)
    return [s.decode() for s in arr.tolist()], off


def encode_event_frame(batch, seq0: int | None = None) -> bytes:
    """EventBatch -> one EVENT frame. Only the id-table entries the batch
    references are shipped (remapped to frame-local ids), so frame size
    tracks the batch, not the process-lifetime interners. All column and
    table packing is vectorized — no per-event or per-string Python.

    With ``seq0`` (defaults to the batch's own stamp) the frame is GCE2:
    a u64 base seq follows the count and event i is seq ``seq0 + i``.
    Without one it stays byte-identical GCE1."""
    c = batch.columns
    n = len(batch)
    if seq0 is None:
        seq0 = getattr(batch, "seq0", None)
    if seq0 is None:
        parts = [EVENT_MAGIC, struct.pack("<I", n)]
    else:
        parts = [EVENT_MAGIC_SEQ, struct.pack("<IQ", n, seq0)]
    local_cols: dict[str, np.ndarray] = {}
    tables = []
    for table, cols in (
        (batch.symbols, ("symbol_id",)),
        (batch.uid_table, ("taker_uid", "maker_uid")),
        (batch.oid_table, ("taker_oid", "maker_oid")),
    ):
        if n:
            cat = np.concatenate([c[k] for k in cols])
            top = int(cat.max()) if len(cat) else 0
            lo = int(cat.min()) if len(cat) else 0
            span = top - lo
            if 0 <= lo and span < max(16 * len(cat), 1 << 16):
                # Dense ids (interner-assigned): a flag-scatter + nonzero
                # over the batch's [lo, top] id RANGE replaces the
                # O(n log n) sort inside np.unique — ~2x less host CPU at
                # frame shape. Unlike the remap below (lazy np.empty, only
                # touched pages materialize), nonzero READS the whole flag
                # array, so it is sized to the batch's span (a frame's oid
                # ids are recent neighbors even when the interner holds
                # hundreds of millions); spans sparser than 16x the batch
                # degrade to np.unique.
                seen = np.zeros(span + 1, np.bool_)
                seen[cat - lo] = True
                used = np.nonzero(seen)[0] + lo
            else:
                used = np.unique(cat)
        else:
            used = np.zeros(0, np.int64)
        tables.append(_pack_id_table(table, used))
        if n and len(used):
            top = int(used[-1])
            if top < (1 << 28):
                # Dense O(1) remap instead of per-column searchsorted:
                # scatter frame-local ids into a position-indexed map.
                # np.empty is a lazy mmap and only the touched pages
                # materialize, but the map still scales with the LARGEST
                # id (the oid interner grows one id per order for the
                # process lifetime) — so cap it at 2^28 ids (1 GB u32,
                # ~270M orders) and degrade to searchsorted beyond, which
                # keeps scratch O(batch).
                remap = np.empty(top + 1, np.uint32)
                remap[used] = np.arange(len(used), dtype=np.uint32)
                for k in cols:
                    local_cols[k] = remap[c[k]]
            else:
                for k in cols:
                    local_cols[k] = np.searchsorted(used, c[k])
        else:
            for k in cols:
                local_cols[k] = np.zeros(0, np.int64)
    for name, dt in _EVENT_NUM:
        col = local_cols.get(name, c.get(name))
        parts.append(np.ascontiguousarray(col, dt).tobytes())
    parts.extend(tables)
    return b"".join(parts)


def decode_event_frame(payload: bytes):
    """EVENT frame -> EventBatch (frame-local tables)."""
    from ..engine.events import EventBatch

    buf = memoryview(payload)
    magic = bytes(buf[:4])
    seq0: int | None = None
    if magic == EVENT_MAGIC:
        (n,) = struct.unpack_from("<I", buf, 4)
        off = 8
    elif magic == EVENT_MAGIC_SEQ:
        n, seq0 = struct.unpack_from("<IQ", buf, 4)
        off = 16
    else:
        raise ValueError("not an EVENT frame")
    cols: dict = {}
    for name, dt in _EVENT_NUM:
        cols[name] = np.frombuffer(buf, dt, n, off).astype(
            np.bool_ if name in ("is_cancel", "is_market") else np.int64
        )
        off += np.dtype(dt).itemsize * n
    cols["taker_side"] = cols["taker_side"].astype(np.int8)
    symbols, off = _read_id_table(buf, off)
    uids, off = _read_id_table(buf, off)
    oids, off = _read_id_table(buf, off)
    cols["arrival"] = np.arange(n, dtype=np.int64)
    return EventBatch(
        columns=cols, symbols=symbols, oid_table=oids, uid_table=uids,
        seq0=seq0,
    )

"""In-process queue backend (single-binary deployments and tests)."""

from __future__ import annotations

import threading

from .base import Message, Queue, _Waitable


class MemoryQueue(_Waitable, Queue):
    supports_headers = True  # in-process equivalent of AMQP headers

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        # The log: _items[i] holds offset _base + i. compact() releases
        # the committed prefix (advances _base); offsets stay absolute.
        self._items: list[bytes] = []  # guarded by self._lock
        self._headers: list[dict | None] = []  # guarded by self._lock
        self._base = 0  # guarded by self._lock
        self._committed = 0  # guarded by self._lock
        self._init_wait()

    def publish(self, body: bytes, headers: dict | None = None) -> int:
        with self._lock:
            self._items.append(bytes(body))
            self._headers.append(headers)
            off = self._base + len(self._items) - 1
        self._notify_publish()
        return off

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        with self._lock:
            if offset < self._base:
                raise ValueError(
                    f"offset {offset} was compacted away (base "
                    f"{self._base}); compact() only frees the committed "
                    "prefix, so a committed reader can never see this"
                )
            end = min(len(self._items), offset - self._base + max_n)
            return [
                Message(
                    offset=self._base + i,
                    body=self._items[i],
                    headers=self._headers[i],
                )
                for i in range(offset - self._base, end)
            ]

    def end_offset(self) -> int:
        with self._lock:
            return self._base + len(self._items)

    def depth(self) -> int:
        # One lock acquisition (the base-class default takes it twice —
        # end then committed — and can interleave with a publish).
        with self._lock:
            return self._base + len(self._items) - self._committed

    def committed(self) -> int:
        with self._lock:
            return self._committed

    def commit(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"commit going backwards: {offset} < {self._committed}"
                )
            if offset > self._base + len(self._items):
                raise ValueError(
                    f"commit past end: {offset} > "
                    f"{self._base + len(self._items)}"
                )
            self._committed = offset

    def rollback(self, offset: int) -> None:
        with self._lock:
            if offset > self._committed:
                raise ValueError(
                    f"rollback going forwards: {offset} > {self._committed}"
                )
            if offset < self._base:
                raise ValueError(
                    f"rollback below compacted base: {offset} < "
                    f"{self._base} — compact() bounds the redelivery "
                    "window to messages since the last compaction"
                )
            self._committed = offset

    def compact(self) -> int:
        """Release the committed prefix (the memory-bus analog of a log
        segment delete): message bodies below the committed offset are
        freed and the base advances. Without this, an in-process queue
        retains every message for the life of the process — fine for a
        bounded bench, UNBOUNDED growth for a wall-clock soak (the
        steady-state proof would be measuring its own harness). Bounds
        the rollback/redelivery window to messages since the last
        compaction — callers compact only past state they will never
        replay. Returns the number of messages released."""
        with self._lock:
            n = self._committed - self._base
            if n <= 0:
                return 0
            del self._items[:n]
            del self._headers[:n]
            self._base = self._committed
            return n

    def truncate_to(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"cannot truncate below committed: {offset} < "
                    f"{self._committed}"
                )
            del self._items[max(offset - self._base, 0):]
            del self._headers[max(offset - self._base, 0):]

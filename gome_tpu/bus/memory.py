"""In-process queue backend (single-binary deployments and tests)."""

from __future__ import annotations

import threading

from .base import Message, Queue, _Waitable


class MemoryQueue(_Waitable, Queue):
    supports_headers = True  # in-process equivalent of AMQP headers

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._items: list[bytes] = []  # guarded by self._lock
        self._headers: list[dict | None] = []  # guarded by self._lock
        self._committed = 0  # guarded by self._lock
        self._init_wait()

    def publish(self, body: bytes, headers: dict | None = None) -> int:
        with self._lock:
            self._items.append(bytes(body))
            self._headers.append(headers)
            off = len(self._items) - 1
        self._notify_publish()
        return off

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        with self._lock:
            end = min(len(self._items), offset + max_n)
            return [
                Message(
                    offset=i, body=self._items[i], headers=self._headers[i]
                )
                for i in range(offset, end)
            ]

    def end_offset(self) -> int:
        with self._lock:
            return len(self._items)

    def committed(self) -> int:
        with self._lock:
            return self._committed

    def commit(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"commit going backwards: {offset} < {self._committed}"
                )
            if offset > len(self._items):
                raise ValueError(
                    f"commit past end: {offset} > {len(self._items)}"
                )
            self._committed = offset

    def rollback(self, offset: int) -> None:
        with self._lock:
            if offset > self._committed:
                raise ValueError(
                    f"rollback going forwards: {offset} > {self._committed}"
                )
            self._committed = offset

    def truncate_to(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"cannot truncate below committed: {offset} < "
                    f"{self._committed}"
                )
            del self._items[offset:]
            del self._headers[offset:]

"""Bus wire codec — JSON bodies shaped like the reference's.

The reference publishes JSON-marshalled Go structs: OrderNode to "doOrder"
(engine.go:36) and MatchResult{Node, MatchNode, MatchVolume} to "matchOrder"
(engine.go:153-158). Go's encoder uses the exact exported field names
(no json tags anywhere in gomengine), so the parity field set is
  order:  Action, Uuid, Oid, Symbol, Transaction, Price, Volume
          (ordernode.go:10-16; the Redis key-plumbing fields NodeName..
          OrderDepthHashField are internal — meaningless off-device — and a
          decoder must ignore them)
  result: Node, MatchNode, MatchVolume (engine.go:24-28)

Price/Volume on the wire are the *scaled* values (the reference marshals
post-scaling nodes — float64 on 10^accuracy-scaled integers, SURVEY §2.2);
we encode our exact int ticks, which serialize identically for every value
in the float64-exact range. Extension field: Kind (market orders) — absent
⇒ LIMIT, so reference-shaped messages decode unchanged.
"""

from __future__ import annotations

import json

from ..types import Action, MatchResult, Order, OrderSnapshot, OrderType, Side


def encode_order(order: Order) -> bytes:
    body = {
        "Action": int(order.action),
        "Uuid": order.uuid,
        "Oid": order.oid,
        "Symbol": order.symbol,
        "Transaction": int(order.side),
        "Price": order.price,
        "Volume": order.volume,
    }
    if order.order_type is not OrderType.LIMIT:
        body["Kind"] = int(order.order_type)
    if order.trace is not None:
        # Order-lifecycle trace context (utils.trace). Extension field
        # like Kind: absent on reference-shaped messages, ignored by a
        # reference decoder.
        body["Trace"] = order.trace
    return json.dumps(body, separators=(",", ":")).encode()


def decode_order(body: bytes) -> Order:
    d = json.loads(body)
    return Order(
        uuid=d["Uuid"],
        oid=d["Oid"],
        symbol=d["Symbol"],
        side=Side(d["Transaction"]),
        price=int(d["Price"]),
        volume=int(d["Volume"]),
        action=Action(d.get("Action", int(Action.ADD))),
        order_type=OrderType(d.get("Kind", 0)),
        trace=d.get("Trace"),
    )


def _encode_snapshot(s: OrderSnapshot) -> dict:
    return {
        "Uuid": s.uuid,
        "Oid": s.oid,
        "Symbol": s.symbol,
        "Transaction": int(s.side),
        "Price": s.price,
        "Volume": s.volume,
    }


def _decode_snapshot(d: dict) -> OrderSnapshot:
    return OrderSnapshot(
        uuid=d["Uuid"],
        oid=d["Oid"],
        symbol=d["Symbol"],
        side=Side(d["Transaction"]),
        price=int(d["Price"]),
        volume=int(d["Volume"]),
    )


def encode_match_result(mr: MatchResult) -> bytes:
    body = {
        "Node": _encode_snapshot(mr.node),
        "MatchNode": _encode_snapshot(mr.match_node),
        "MatchVolume": mr.match_volume,
    }
    if mr.seq is not None:
        # Matchfeed sequence number (ISSUE 11 exactly-once). Extension
        # field like Kind/Trace: absent on reference-shaped messages,
        # ignored by a reference decoder.
        body["Seq"] = mr.seq
    return json.dumps(body, separators=(",", ":")).encode()


def decode_match_result(body: bytes) -> MatchResult:
    d = json.loads(body)
    seq = d.get("Seq")
    return MatchResult(
        node=_decode_snapshot(d["Node"]),
        match_node=_decode_snapshot(d["MatchNode"]),
        match_volume=int(d["MatchVolume"]),
        seq=None if seq is None else int(seq),
    )

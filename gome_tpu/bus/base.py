"""Queue interface shared by all bus backends.

Semantics (deliberately stronger than the reference's): at-least-once
delivery with explicit commit of consumer progress, vs the reference's
auto-ack at-most-once (rabbitmq.go:102,148). `poll_batch` is the
micro-batching primitive the TPU engine needs (SURVEY §7: N orders or T µs,
whichever first) that the reference's one-message-at-a-time loop
(rabbitmq.go:116-125) lacks.
"""

from __future__ import annotations

import abc
import dataclasses
import threading
import time


@dataclasses.dataclass(frozen=True)
class Message:
    offset: int  # monotonically increasing position in the queue
    body: bytes


class Queue(abc.ABC):
    """A single named FIFO queue of byte messages."""

    name: str

    @abc.abstractmethod
    def publish(self, body: bytes) -> int:
        """Append one message; returns its offset."""

    @abc.abstractmethod
    def read_from(self, offset: int, max_n: int) -> list[Message]:
        """Read up to max_n messages at >= offset (non-destructive)."""

    @abc.abstractmethod
    def end_offset(self) -> int:
        """Offset one past the last published message."""

    @abc.abstractmethod
    def committed(self) -> int:
        """The durable consumer offset (next message to process)."""

    @abc.abstractmethod
    def commit(self, offset: int) -> None:
        """Durably record that messages below `offset` are fully processed."""

    def poll_batch(
        self, max_n: int, max_wait_s: float, poll_interval_s: float = 0.001
    ) -> list[Message]:
        """Micro-batch read from the committed offset: returns as soon as
        max_n messages are available, else whatever arrived after max_wait_s
        (possibly empty). Does NOT commit — the caller commits after the
        batch is fully processed (crash ⇒ replay, at-least-once)."""
        deadline = time.monotonic() + max_wait_s
        start = self.committed()
        while True:
            msgs = self.read_from(start, max_n)
            if len(msgs) >= max_n or time.monotonic() >= deadline:
                return msgs
            self._wait_for_publish(poll_interval_s)

    def _wait_for_publish(self, timeout_s: float) -> None:
        time.sleep(timeout_s)


@dataclasses.dataclass
class QueueBus:
    """The reference's two-queue topology (rabbitmq.go: "doOrder" inbound,
    "matchOrder" outbound)."""

    order_queue: Queue
    match_queue: Queue


class _Waitable:
    """Mixin: condition-variable publish notification so poll_batch wakes
    immediately instead of sleeping the full poll interval."""

    def _init_wait(self):
        self._cond = threading.Condition()

    def _notify_publish(self):
        with self._cond:
            self._cond.notify_all()

    def _wait_for_publish(self, timeout_s: float) -> None:
        with self._cond:
            self._cond.wait(timeout_s)

"""Durable append-only file queue.

Format: length-prefixed records in one log file per queue
(``<dir>/<name>.log``: 4-byte big-endian length + payload per record) plus a
sidecar ``<name>.offset`` holding the committed consumer offset as ASCII.
Publishes fsync per append batch; commits rewrite the sidecar atomically
(tmp + rename). A torn final record (crash mid-append) is detected on open
and truncated away. Readers TAIL the log across processes: read_from/
end_offset re-scan for records another process appended since the last
look (single writer per queue; an incomplete tail record is the live
writer mid-append and is skipped, not truncated) — the split
gateway/consumer fleet topology runs on exactly this.

This is the durability the reference lacks on its bus (non-durable queues +
auto-ack, rabbitmq.go:64,102 — SURVEY §2.3.6): with a FileQueue, the order
log doubles as the replay source for crash recovery (gome_tpu.persist), the
role the raw Redis book plays in the reference (§5.4).
"""

from __future__ import annotations

import os
import re
import struct
import threading

from ..utils.faults import FAULTS
from .base import Message, Queue, _Waitable

_LEN = struct.Struct(">I")

# Committed-offset sidecar parse: accept any leading decimal run. A torn
# write of "1234" can leave "12" — and any prefix of a decimal string is
# numerically <= the full value, so the digit prefix IS the last valid
# committed prefix (commits only move forward; re-delivery is safe,
# losing acknowledged work is not).
_OFF_RE = re.compile(rb"\s*(\d+)")


class FileQueue(_Waitable, Queue):
    def __init__(self, name: str, path_base: str, fsync: bool = True):
        self.name = name
        self._log_path = path_base + ".log"
        self._off_path = path_base + ".offset"
        self._fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(self._log_path) or ".", exist_ok=True)
        # In-memory index: byte position of each record (offset -> filepos).
        self._positions: list[int] = []
        # Byte position one past the last fully-indexed record: the
        # cross-process tail point (_refresh_locked resumes scanning
        # here when ANOTHER process appended since we last looked).
        self._scan_end = 0  # guarded by self._lock
        with self._lock:
            self._scan_existing_locked()
        self._f = open(self._log_path, "ab")
        self._committed = self._read_committed()
        self._init_wait()

    # -- recovery-time scan --------------------------------------------------
    def _scan_existing_locked(self) -> None:
        if not os.path.exists(self._log_path):
            return
        valid_end = 0
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, pos)
            if pos + _LEN.size + n > len(data):
                break  # torn tail record
            self._positions.append(pos)
            pos += _LEN.size + n
            valid_end = pos
        self._scan_end = valid_end
        if valid_end < len(data):
            with open(self._log_path, "ab") as f:
                f.truncate(valid_end)

    def _refresh_locked(self) -> None:
        """Index records appended by ANOTHER process since our last look
        (caller holds self._lock). The fleet topology runs one writer and
        one reader process per queue over the same log file: the reader's
        in-memory index must tail the writer's appends. Only complete
        records are indexed — an incomplete tail is a record the live
        writer is mid-append on, so (unlike the open-time scan) it is
        left alone, never truncated. One stat per call when nothing
        changed."""
        try:
            size = os.path.getsize(self._log_path)
        except OSError:
            return
        if size <= self._scan_end:
            return
        with open(self._log_path, "rb") as f:
            f.seek(self._scan_end)
            data = f.read(size - self._scan_end)
        pos = 0
        while pos + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, pos)
            if pos + _LEN.size + n > len(data):
                break  # writer mid-append; next refresh picks it up
            self._positions.append(self._scan_end + pos)
            pos += _LEN.size + n
        self._scan_end += pos

    def _read_committed(self) -> int:
        """Parse the sidecar, surviving torn/empty/garbage contents.

        Fallback order: digit prefix of whatever is there (see _OFF_RE),
        else 0 (full replay from the start). Either way the result is
        clamped to [0, len(positions)] — a sidecar ahead of a truncated
        log must not make read_from index past the end.
        """
        try:
            with open(self._off_path, "rb") as f:
                m = _OFF_RE.match(f.read(64))
        except OSError:
            return 0
        committed = int(m.group(1)) if m else 0
        return min(committed, len(self._positions))

    # -- Queue interface -----------------------------------------------------
    def publish(self, body: bytes) -> int:
        with self._lock:
            record = _LEN.pack(len(body)) + body
            cut = FAULTS.fire("filelog.append")
            if cut:
                # Torn append: persist a strict prefix of the record and
                # die. _scan_existing_locked truncates it on the next open.
                self._f.write(record[: cut % len(record)])
                self._f.flush()
                os.fsync(self._f.fileno())
                FAULTS.hard_exit()
            pos = self._f.tell()
            self._f.write(record)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self._positions.append(pos)
            self._scan_end = pos + len(record)
            off = len(self._positions) - 1
        self._notify_publish()
        return off

    def read_from(self, offset: int, max_n: int) -> list[Message]:
        with self._lock:
            self._refresh_locked()
            end = min(len(self._positions), offset + max_n)
            if offset >= end:
                return []
            start_pos = self._positions[offset]
        out: list[Message] = []
        with open(self._log_path, "rb") as f:
            f.seek(start_pos)
            for i in range(offset, end):
                (n,) = _LEN.unpack(f.read(_LEN.size))
                out.append(Message(offset=i, body=f.read(n)))
        return out

    def end_offset(self) -> int:
        with self._lock:
            self._refresh_locked()
            return len(self._positions)

    def committed(self) -> int:
        with self._lock:
            return self._committed

    def commit(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"commit going backwards: {offset} < {self._committed}"
                )
            if offset > len(self._positions):
                raise ValueError(
                    f"commit past end: {offset} > {len(self._positions)}"
                )
            self._write_offset(offset)
            self._committed = offset

    def rollback(self, offset: int) -> None:
        with self._lock:
            if offset > self._committed:
                raise ValueError(
                    f"rollback going forwards: {offset} > {self._committed}"
                )
            self._write_offset(offset)
            self._committed = offset

    def truncate_to(self, offset: int) -> None:
        with self._lock:
            if offset < self._committed:
                raise ValueError(
                    f"cannot truncate below committed: {offset} < "
                    f"{self._committed}"
                )
            if offset >= len(self._positions):
                return
            pos = self._positions[offset]
            self._f.truncate(pos)
            self._f.seek(pos)
            del self._positions[offset:]
            self._scan_end = pos

    def _write_offset(self, offset: int) -> None:
        cut = FAULTS.fire("filelog.offset")
        if cut:
            # Torn sidecar: a truncated decimal written straight to the
            # final path (simulating a filesystem that tore the replace),
            # then die. _read_committed's digit-prefix parse recovers.
            text = str(offset)
            with open(self._off_path, "w") as f:
                f.write(text[: cut % (len(text) + 1)])
            FAULTS.hard_exit()
        tmp = self._off_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(offset))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._off_path)

    def close(self) -> None:
        with self._lock:
            self._f.close()

"""In-process AMQP 0-9-1 broker speaking the frame-protocol subset the
client (gome_tpu.bus.amqp) and the reference (rabbitmq.go) use.

No RabbitMQ exists in this environment, so the AMQP transport is tested
against this: a real TCP server doing the real handshake, queue
declaration, publish/content framing, consumer delivery, multiple-flag
acks, and unacked-requeue on connection loss (the at-least-once semantics
RabbitMQ provides). Tests and local single-host deployments can run the
full reference topology — gateway and consumer processes joined by AMQP —
without an external broker.
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque

from .amqp import (
    EMPTY_TABLE,
    FLAG_HEADERS,
    FRAME_BODY,
    FRAME_END,
    FRAME_HEADER,
    FRAME_METHOD,
    PROTOCOL_HEADER,
    content_frames,
    frame,
    longstr,
    method,
    read_exact,
    read_frame,
    read_longstr,
    read_shortstr,
    read_table,
    shortstr,
    skip_table,
)


class _BrokerQueue:
    def __init__(self, name: str):
        self.name = name
        # (body, redelivered, headers): the redelivered flag rides
        # Basic.Deliver so a reconnecting consumer can tell replayed
        # deliveries from fresh ones (RabbitMQ semantics; bus.amqp.
        # SupervisedAmqpQueue keys its exact-resume dedup on it); headers
        # are the publisher's basic-properties table, preserved verbatim
        # across delivery AND redelivery (trace propagation relies on it).
        self.pending: deque[tuple[bytes, bool, dict | None]] = deque()
        self.consumers: list["_Connection"] = []  # round-robin order
        self.drain_lock = threading.Lock()  # one drainer at a time (FIFO)
        self._rr = 0

    def next_consumer(self):
        live = [c for c in self.consumers if not c.closed]
        self.consumers = live
        if not live:
            return None
        c = live[self._rr % len(live)]
        self._rr += 1
        return c


class _Connection:
    def __init__(self, broker: "FakeBroker", sock: socket.socket):
        self.broker = broker
        self.sock = sock
        self.closed = False  # single-writer: this connection's reader thread
        self.wlock = threading.Lock()
        self.dlock = threading.Lock()  # delivery-tag + unacked consistency
        # tag -> (queue, body, headers)  # guarded by self.dlock
        self.unacked: dict[int, tuple[str, bytes, dict | None]] = {}
        self.consuming: list[str] = []  # single-writer: the reader thread
        self._next_tag = 1  # guarded by self.dlock
        # (queue, bytearray, [size], [headers])
        self._pending_pub: tuple | None = None  # single-writer: the reader thread
        self._publishes = 0  # single-writer: the reader thread (fault accounting)
        self._confirm = False  # single-writer: the reader thread (Confirm.Select)
        self._pub_tag = 0  # single-writer: the reader thread (ack tag sequence)

    def send(self, data: bytes) -> None:
        with self.wlock:
            self.sock.sendall(data)

    def deliver(
        self,
        queue: str,
        body: bytes,
        redelivered: bool = False,
        headers: dict | None = None,
    ) -> None:
        # Broker threads for DIFFERENT producer connections can deliver to
        # the same consumer concurrently: tag allocation + unacked insert +
        # the send must be one atomic unit or tags duplicate and unacked
        # entries vanish (breaking the redelivery guarantee this broker
        # exists to test).
        with self.dlock:
            tag = self._next_tag
            self._next_tag += 1
            self.unacked[tag] = (queue, body, headers)
            deliver = method(
                60,
                60,
                shortstr(f"c-{queue}")
                + struct.pack(">QB", tag, 1 if redelivered else 0)
                + shortstr("")
                + shortstr(queue),
            )
            parts = [frame(FRAME_METHOD, 1, deliver)] + content_frames(
                1, body, self.broker.frame_max, headers=headers
            )
            self.send(b"".join(parts))

    # -- frame handlers ---------------------------------------------------
    def run(self) -> None:
        try:
            hdr = read_exact(self.sock, 8)
            if hdr != PROTOCOL_HEADER:
                self.sock.close()
                return
            start = method(
                10,
                10,
                bytes([0, 9])
                + EMPTY_TABLE
                + longstr(b"PLAIN")
                + longstr(b"en_US"),
            )
            self.send(frame(FRAME_METHOD, 0, start))
            if self.broker.heartbeat and not self.broker.mute_heartbeats:
                threading.Thread(
                    target=self._heartbeat_loop, daemon=True
                ).start()
            if self.broker.heartbeat:
                # Enforce like RabbitMQ: a peer silent for ~2 intervals is
                # dead. (Heartbeat frames from the client count.)
                self.sock.settimeout(2.0 * self.broker.heartbeat + 0.5)
            while not self.closed:
                ftype, channel, payload = read_frame(self.sock)
                if ftype == FRAME_METHOD:
                    self._method(channel, memoryview(payload))
                elif ftype == FRAME_HEADER and self._pending_pub:
                    (size,) = struct.unpack_from(">Q", payload, 4)
                    (flags,) = struct.unpack_from(">H", payload, 12)
                    if flags & FLAG_HEADERS:
                        hdrs, _ = read_table(memoryview(payload), 14)
                        self._pending_pub[3][0] = hdrs or None
                    self._pending_pub[2][0] = size
                    if size == 0:
                        self._finish_publish()
                elif ftype == FRAME_BODY and self._pending_pub:
                    self._pending_pub[1].extend(payload)
                    if len(self._pending_pub[1]) >= self._pending_pub[2][0]:
                        self._finish_publish()
        except (ConnectionError, OSError, socket.timeout):
            pass
        finally:
            self.closed = True
            try:
                self.sock.close()
            except OSError:
                pass
            self.broker._requeue_unacked(self)

    def _method(self, channel: int, buf: memoryview) -> None:
        class_id, method_id = struct.unpack_from(">HH", buf, 0)
        off = 4
        if (class_id, method_id) == (10, 11):  # StartOk
            off = skip_table(buf, off)
            _mech, off = read_shortstr(buf, off)
            _resp, off = read_longstr(buf, off)
            tune = method(
                10,
                30,
                struct.pack(
                    ">HIH", 2047, self.broker.frame_max,
                    self.broker.heartbeat,
                ),
            )
            self.send(frame(FRAME_METHOD, 0, tune))
        elif (class_id, method_id) == (10, 31):  # TuneOk
            pass
        elif (class_id, method_id) == (10, 40):  # Open
            self.send(frame(FRAME_METHOD, 0, method(10, 41, shortstr(""))))
        elif (class_id, method_id) == (10, 50):  # Close
            self.send(frame(FRAME_METHOD, 0, method(10, 51)))
            self.closed = True
        elif (class_id, method_id) == (20, 10):  # Channel.Open
            self.send(
                frame(FRAME_METHOD, channel, method(20, 11, longstr(b"")))
            )
        elif (class_id, method_id) == (50, 10):  # Queue.Declare
            off += 2  # reserved
            qname, off = read_shortstr(buf, off)
            q = self.broker._queue(qname)
            ok = method(
                50,
                11,
                shortstr(qname) + struct.pack(">II", len(q.pending), 0),
            )
            self.send(frame(FRAME_METHOD, channel, ok))
        elif (class_id, method_id) == (60, 40):  # Basic.Publish
            off += 2  # reserved
            _ex, off = read_shortstr(buf, off)
            rkey, off = read_shortstr(buf, off)
            self._publishes += 1
            if self._publishes == self.broker.close_abruptly_on_publish:
                # Fault mode: the broker process dies mid-stream — no
                # Close method, just a dead socket (kill -9 equivalent).
                # shutdown first so the peer SEES the death immediately
                # (close alone leaves its blocked reader hanging).
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.sock.close()
                self.closed = True
                return
            if self._publishes == self.broker.channel_close_on_publish:
                # Fault mode: server-initiated Channel.Close (e.g. 404
                # NOT_FOUND / resource error) instead of accepting.
                self.send(
                    frame(
                        FRAME_METHOD,
                        channel,
                        method(
                            20,
                            40,
                            struct.pack(">H", 404)
                            + shortstr("NOT_FOUND - fault injection")
                            + struct.pack(">HH", 60, 40),
                        ),
                    )
                )
                return
            self._pending_pub = (rkey, bytearray(), [0], [None])
        elif (class_id, method_id) == (60, 20):  # Basic.Consume
            off += 2
            qname, off = read_shortstr(buf, off)
            ctag, off = read_shortstr(buf, off)
            self.consuming.append(qname)
            self.send(
                frame(FRAME_METHOD, channel, method(60, 21, shortstr(ctag)))
            )
            self.broker._attach_consumer(qname, self)
        elif (class_id, method_id) == (60, 80):  # Basic.Ack
            tag, multiple = struct.unpack_from(">QB", buf, off)
            with self.dlock:
                if multiple:
                    for t in [t for t in self.unacked if t <= tag]:
                        self.unacked.pop(t, None)
                else:
                    self.unacked.pop(tag, None)
        elif (class_id, method_id) == (85, 10):  # Confirm.Select
            self._confirm = True
            self.send(frame(FRAME_METHOD, channel, method(85, 11)))
        # anything else: ignore (permissive test broker)

    def _finish_publish(self) -> None:
        qname, body, _, hdr = self._pending_pub
        self._pending_pub = None
        self.broker._publish(qname, bytes(body), headers=hdr[0])
        if self._confirm:
            # Publisher confirm: Basic.Ack AFTER the enqueue — a killed
            # connection whose publish was dropped never acks, which is
            # what lets a supervised publisher retry exactly.
            self._pub_tag += 1
            self.send(
                frame(
                    FRAME_METHOD, 1,
                    method(60, 80, struct.pack(">QB", self._pub_tag, 0)),
                )
            )

    def _heartbeat_loop(self) -> None:
        hb = frame(8, 0, b"")  # FRAME_HEARTBEAT
        while not self.closed:
            import time

            time.sleep(self.broker.heartbeat / 2.0)
            if self.closed:
                return
            try:
                self.send(hb)
            except OSError:
                return


class FakeBroker:
    """Threaded localhost AMQP broker. start() binds an ephemeral port
    (.port); stop() closes everything.

    Fault modes (protocol-strictness testing — behaviors a well-behaved
    fake never produces but a real broker/network does):
      heartbeat       — propose N-second heartbeats in Tune and ENFORCE
                        them (silent peers are dropped after ~2N);
      mute_heartbeats — with heartbeat set, the broker never sends its
                        own (clients must detect the silence and fail);
      frame_max       — propose a small frame size (content must split);
      channel_close_on_publish — the Nth Basic.Publish draws a
                        server-initiated Channel.Close(404);
      close_abruptly_on_publish — the Nth Basic.Publish kills the socket
                        with no Close handshake (broker crash)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat: int = 0,
        mute_heartbeats: bool = False,
        frame_max: int = 131072,
        channel_close_on_publish: int | None = None,
        close_abruptly_on_publish: int | None = None,
    ):
        self.host = host
        self.port = port  # single-writer: start() caller (rebound to the bound port)
        self.heartbeat = heartbeat
        self.mute_heartbeats = mute_heartbeats
        self.frame_max = frame_max
        self.channel_close_on_publish = channel_close_on_publish
        self.close_abruptly_on_publish = close_abruptly_on_publish
        self._server: socket.socket | None = None  # single-writer: start()/stop() caller
        self._lock = threading.Lock()
        self._queues: dict[str, _BrokerQueue] = {}
        self._conns: list[_Connection] = []
        self._stop = False  # single-writer: stop() caller

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FakeBroker":
        self._server = socket.create_server((self.host, self.port))
        self.port = self._server.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name="fake-amqp", daemon=True
        ).start()
        return self

    def stop(self) -> None:
        self._stop = True
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
            # Wake the accept thread (a blocked accept() keeps the LISTEN
            # socket's file description open — the port would linger).
            try:
                socket.create_connection(
                    (self.host, self.port), timeout=0.2
                ).close()
            except OSError:
                pass
        for c in list(self._conns):
            c.closed = True
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            conn = _Connection(self, sock)
            self._conns.append(conn)
            threading.Thread(
                target=conn.run, name="fake-amqp-conn", daemon=True
            ).start()

    # -- queue ops --------------------------------------------------------
    def _queue(self, name: str) -> _BrokerQueue:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = _BrokerQueue(name)
            return self._queues[name]

    def _publish(
        self, name: str, body: bytes, headers: dict | None = None
    ) -> None:
        q = self._queue(name)
        with self._lock:
            q.pending.append((body, False, headers))
        self._drain(q)

    def _attach_consumer(self, name: str, conn: _Connection) -> None:
        q = self._queue(name)
        with self._lock:
            q.consumers.append(conn)
        self._drain(q)

    def _drain(self, q: _BrokerQueue) -> None:
        """Deliver pending messages FIFO. Every publish and consumer attach
        funnels through here; the PER-QUEUE drain lock serializes drainers
        (so a new publish can never overtake an older backlog message)
        while the blocking socket send happens outside the broker-global
        lock — one slow consumer must not stall every queue or deadlock
        against a publisher blocked on its own send."""
        with q.drain_lock:
            while True:
                with self._lock:
                    if not q.pending:
                        return
                    consumer = q.next_consumer()
                    if consumer is None:
                        return
                    body, redelivered, headers = q.pending.popleft()
                try:
                    consumer.deliver(q.name, body, redelivered, headers)
                except OSError:
                    with self._lock:
                        q.pending.appendleft((body, redelivered, headers))
                    return

    def _requeue_unacked(self, conn: _Connection) -> None:
        """Connection died: everything it held unacked goes back to its
        queue at the HEAD (FIFO by delivery tag, AHEAD of messages
        published during the outage) — RabbitMQ's at-least-once
        redelivery, which replays requeued messages before younger ones.
        Head placement is what lets a reconnecting consumer rebuild the
        exact arrival order it saw before the drop (bus.amqp.
        SupervisedAmqpQueue relies on it)."""
        with conn.dlock:
            items = sorted(conn.unacked.items())
            conn.unacked.clear()
        by_queue: dict[str, list[tuple]] = {}
        for _tag, (qname, body, headers) in items:
            by_queue.setdefault(qname, []).append((body, headers))
        for qname, entries in by_queue.items():
            q = self._queue(qname)
            with self._lock:
                q.pending.extendleft(
                    (body, True, headers)
                    for body, headers in reversed(entries)
                )
            self._drain(q)

    def kill_connections(self, consuming: str | None = None) -> int:
        """Fault injection: abruptly close live connections (no Close
        handshake — kill -9 / network-partition equivalent). With
        `consuming` set, only connections consuming that queue die (the
        broker-side way to kill a specific consumer mid-stream). Unacked
        deliveries requeue via each connection's normal death path.
        Returns the number of connections killed.

        shutdown() before close(): close() alone does NOT wake a thread
        blocked in recv() on the same socket (neither our conn thread nor
        the peer would notice for seconds), while shutdown sends the FIN
        and interrupts both sides immediately — the kill must be
        OBSERVABLE at the instant it happens for fault schedules to be
        deterministic."""
        killed = 0
        for c in list(self._conns):
            if c.closed:
                continue
            if consuming is not None and consuming not in c.consuming:
                continue
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass
            killed += 1
        return killed

    def queue_depth(self, name: str) -> int:
        """Test introspection: messages waiting with no consumer."""
        with self._lock:
            q = self._queues.get(name)
            return len(q.pending) if q else 0

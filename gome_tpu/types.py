"""Domain types shared by the oracle, the JAX engine, and the bridge.

Mirrors the reference wire contract (api/order.proto:4-29) and the internal
order node / match-result shapes (gomengine/engine/ordernode.go:9-36,
gomengine/engine/engine.go:24-28) — re-expressed as integer tick/lot
quantities so the TPU hot path is exact integer arithmetic rather than the
reference's float64-on-scaled-values model (SURVEY §2.2).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace


# Device execution strategies for one [S, T] op grid (single source of
# truth for config validation and BatchEngine selection).
KERNELS = ("scan", "pallas")


class Side(enum.IntEnum):
    """api/order.proto:4-7 — TransactionType {BUY=0, SALE=1}."""

    BUY = 0
    SALE = 1

    @property
    def opposite(self) -> "Side":
        return Side.SALE if self is Side.BUY else Side.BUY


class Action(enum.IntEnum):
    """gomengine/main.go:14-18 — iota consts: ADD=1, DEL=2. NOP=0 is ours
    (padding slot in fixed-shape device op grids)."""

    NOP = 0
    ADD = 1
    DEL = 2


class OrderType(enum.IntEnum):
    """Extension beyond the reference: the proto has no order-type field, so
    every reference order is implicitly a limit order (api/order.proto:10-17;
    SURVEY §1 L5). MARKET is required by BASELINE.json config 5."""

    LIMIT = 0
    MARKET = 1


@dataclass(frozen=True)
class Order:
    """An order in engine-internal form: prices/volumes are *scaled integers*
    (ticks/lots — the value after the reference's 10^accuracy scaling,
    ordernode.go:76-87, held exactly as int instead of float64).
    """

    uuid: str
    oid: str
    symbol: str
    side: Side
    price: int  # scaled ticks; ignored for MARKET
    volume: int  # scaled lots
    action: Action = Action.ADD
    order_type: OrderType = OrderType.LIMIT
    # Order-lifecycle trace context (utils.trace encode_context wire form,
    # "<id>@<t>"). None when tracing is off; excluded from equality so a
    # traced order still compares equal to its untraced twin (replay,
    # oracle parity).
    trace: str | None = field(default=None, compare=False, repr=False)

    def with_volume(self, volume: int) -> "Order":
        return replace(self, volume=volume)


@dataclass(frozen=True)
class OrderSnapshot:
    """The observable fields of an OrderNode as they appear in a MatchResult
    event (engine.go:24-28 serializes whole OrderNodes; the parity surface is
    the subset below — uuid/oid/symbol/side/price/volume; SURVEY §3.4)."""

    uuid: str
    oid: str
    symbol: str
    side: Side
    price: int
    volume: int  # remaining volume at event time (see MatchResult docstring)


@dataclass(frozen=True)
class MatchResult:
    """One fill or cancel event — the parity surface vs the reference.

    Field semantics (engine.go:138-198, engine.go:109-113; SURVEY §3.4):
      * node        — the taker, with volume = remaining AFTER this fill.
      * match_node  — the maker. For a FULL maker fill its volume is the
                      maker's PRE-fill volume (== match_volume); for a
                      PARTIAL maker fill it is the maker's remaining volume
                      after the fill (engine.go:154,171 vs engine.go:178-190).
      * match_volume — traded quantity; 0 ⇒ this is a cancel notice, and
                      node == match_node == the cancelled order with its
                      remaining resting volume (engine.go:109-113).
    Fill price is implicit: match_node.price (the maker's level).
    """

    node: OrderSnapshot
    match_node: OrderSnapshot
    match_volume: int
    # Matchfeed sequence number (monotonic per book epoch; ISSUE 11
    # exactly-once). None when the producer predates seq stamping —
    # excluded from equality so a stamped event still compares equal to
    # its unstamped twin (replay, oracle parity), like Order.trace.
    seq: int | None = field(default=None, compare=False, repr=False)

    @property
    def is_cancel(self) -> bool:
        return self.match_volume == 0


@dataclass
class StepStats:
    """Oracle-side diagnostics (new instrumentation; the reference has none —
    SURVEY §5.5). The device engine's counters live in
    gome_tpu.engine.batch.EngineStats."""

    dropped_no_prepool: int = 0
    cancels_missed: int = 0
    fills: int = 0


def snapshot_of(order: Order, volume: int | None = None) -> OrderSnapshot:
    return OrderSnapshot(
        uuid=order.uuid,
        oid=order.oid,
        symbol=order.symbol,
        side=order.side,
        price=order.price,
        volume=order.volume if volume is None else volume,
    )

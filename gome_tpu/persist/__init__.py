"""Durability — snapshot/restore + replay recovery (SURVEY §5.4).

The reference gets durability for free: Redis IS the book, so every mutation
is instantly persistent and restart = reconnect (redis.go:17-28; the queues
are deliberately lossy, rabbitmq.go:64,102). The TPU build inverts the
tiers: HBM arrays are primary, so durability must be explicit —

  snapshot — periodic atomic dump of all mutable engine state (books,
             interners, pre-pool) plus the bus cursors that make it a
             *consistent cut*: the order-queue committed offset (everything
             below it is IN the books) and the match-queue end offset
             (everything below it was emitted FOR those orders).
  replay   — on restore, rewind the order-queue consumer to the snapshot's
             offset and truncate the match queue to its end offset; the
             normal consumer loop then re-processes the tail
             deterministically, regenerating the exact same events
             (exactly-once on the match queue, vs the reference's
             at-most-once).

Requires the `file` bus backend for crash durability (the memory bus dies
with the process — then snapshots still restore books, and the replay tail
is empty, which is precisely the reference's crash model: in-flight
messages lost, book state kept, SURVEY §2.3.6).

Redis interop is bidirectional: redis_schema *exports* the book in the
reference's exact key schema (commands are generated without a client;
applying them is gated on redis-py being installed), and redis_restore
*imports* that schema back — a live gome deployment's Redis book migrates
into the TPU engine, which continues matching the same symbols. DictRedis
(redis_restore) is an offline in-memory store serving both directions in
tests and as a snapshot target without a server.
"""

from .redis_restore import DictRedis, discover_symbols, restore_from_redis
from .snapshot import Persister, SnapshotStore
from .redis_schema import book_redis_commands

__all__ = [
    "DictRedis",
    "Persister",
    "SnapshotStore",
    "book_redis_commands",
    "discover_symbols",
    "restore_from_redis",
]

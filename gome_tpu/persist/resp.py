"""Dependency-free RESP2 socket client — the wire protocol the reference
speaks to Redis (go-redis v8 client built at gomengine/redis/redis.go:17-28;
every book operation in the reference is a RESP command against the schema
in SURVEY §2.1).

redis-py is not in this image, so this is a from-scratch protocol
implementation, mirroring what bus/amqp.py did for AMQP 0-9-1: the framework
can reach a REAL Redis server (live gome migration, external pre-pool
marker store) with zero dependencies. The fake server half lives in
persist/respserver.py.

Protocol (RESP2): a command is an array of bulk strings
(`*N\r\n` then `$len\r\n<bytes>\r\n` per arg); replies are simple strings
(`+OK`), errors (`-ERR ...`), integers (`:n`), bulk strings (`$n`, `$-1`
null) or arrays (`*n`, `*-1` null). Pipelining is plain batching: write N
commands, read N replies — `pipeline()` exposes that, and it is what makes
a remote pre-pool viable on the hot path (one round trip per FRAME of
HDELs, not one per order).
"""

from __future__ import annotations

import socket
import threading


class RespError(Exception):
    """Server-side error reply (`-ERR ...`)."""


def encode_command(*args) -> bytes:
    """Encode one command as a RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode()
        elif isinstance(a, (int, float)):
            b = repr(a).encode() if isinstance(a, float) else b"%d" % a
        else:
            raise TypeError(f"cannot encode {type(a).__name__} as RESP arg")
        out.append(b"$%d\r\n" % len(b))
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class _Reader:
    """Buffered RESP reply parser over a socket (or any recv(n) source)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()
        self._pos = 0

    def _fill(self) -> None:
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ConnectionError("RESP connection closed by peer")
        # Compact consumed prefix occasionally so the buffer stays bounded.
        if self._pos > 1 << 20:
            del self._buf[: self._pos]
            self._pos = 0
        self._buf.extend(chunk)

    def _readline(self) -> bytes:
        while True:
            nl = self._buf.find(b"\r\n", self._pos)
            if nl >= 0:
                line = bytes(self._buf[self._pos : nl])
                self._pos = nl + 2
                return line
            self._fill()

    def _readn(self, n: int) -> bytes:
        while len(self._buf) - self._pos < n + 2:
            self._fill()
        data = bytes(self._buf[self._pos : self._pos + n])
        self._pos += n + 2  # skip trailing \r\n
        return data

    def read_reply(self):
        line = self._readline()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            return self._readn(n)
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self.read_reply() for _ in range(n)]
        raise RespError(f"malformed RESP reply: {line!r}")


class RespClient:
    """One RESP2 connection. Thread-safe (a lock serializes round trips);
    execute_command matches redis-py's surface so redis_schema's
    export_to_redis works unchanged, and the three read primitives
    (`keys`, `zrange`, `hgetall`) satisfy redis_restore's store contract."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 6379,
        timeout_s: float = 10.0, db: int = 0, password: str | None = None,
    ):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _Reader(self._sock)
        self._lock = threading.Lock()
        # The reference ignores the configured password and uses DB 0
        # (redis.go:20-24); we honor both if given.
        if password:
            self.execute_command("AUTH", password)
        if db:
            self.execute_command("SELECT", db)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def execute_command(self, *args):
        with self._lock:
            self._sock.sendall(encode_command(*args))
            return self._reader.read_reply()

    def pipeline(self, commands: list[tuple]) -> list:
        """Send every command in one write, read all replies — ONE network
        round trip for the whole batch. Errors are returned in-place (as
        RespError instances) rather than raised, so one bad command does
        not orphan the replies behind it."""
        if not commands:
            return []
        payload = b"".join(encode_command(*c) for c in commands)
        out = []
        with self._lock:
            self._sock.sendall(payload)
            for _ in commands:
                try:
                    out.append(self._reader.read_reply())
                except RespError as e:
                    out.append(e)
        return out

    # -- redis_restore's read primitives ----------------------------------
    def keys(self, pattern: str = "*") -> list[str]:
        return [k.decode() for k in self.execute_command("KEYS", pattern)]

    def zrange(self, key: str, start: int = 0, end: int = -1) -> list[str]:
        return [
            m.decode()
            for m in self.execute_command("ZRANGE", key, start, end)
        ]

    def hgetall(self, key: str) -> dict[str, str]:
        flat = self.execute_command("HGETALL", key)
        it = iter(flat)
        return {k.decode(): v.decode() for k, v in zip(it, it)}

    # -- conveniences used by the pre-pool and tests -----------------------
    def ping(self) -> bool:
        return self.execute_command("PING") == "PONG"

    def flushdb(self) -> None:
        self.execute_command("FLUSHDB")

    def hset(self, key: str, field: str, value: str) -> int:
        return self.execute_command("HSET", key, field, value)

    def hdel(self, key: str, *fields: str) -> int:
        return self.execute_command("HDEL", key, *fields)

    def hexists(self, key: str, field: str) -> bool:
        return self.execute_command("HEXISTS", key, field) == 1


class SupervisedRespClient:
    """A RespClient under supervision (utils.resilience.Supervised): a
    dead store connection reconnects under backoff + circuit breaker, the
    session is re-established (AUTH/SELECT replay happens in the
    RespClient constructor), and the failed command retries on the fresh
    connection. Same surface as RespClient, so RespPrePool, redis_schema
    and redis_restore take it unchanged.

    Retry semantics: HSET/HGETALL/KEYS/ZRANGE/… retries are idempotent.
    HDEL (the pre-pool's consume path) has the classic ambiguity window —
    a server that applied the delete but died before replying makes the
    retried command report 0 — which maps onto the engine's at-least-once
    replay exactly like a lost-reply Redis deployment would; exact-once
    marker consumption across store crashes needs transactional markers,
    which neither the reference nor this port has."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 6379,
        timeout_s: float = 10.0, db: int = 0, password: str | None = None,
        name: str | None = None, policy=None, breaker=None,
    ):
        from ..utils.resilience import Supervised

        def factory():
            return RespClient(host, port, timeout_s, db, password)

        self._sup = Supervised(
            name or f"resp:{host}:{port}", factory,
            policy=policy, breaker=breaker,
        )
        # One eager dial, no backoff: boot fallback (service/app.py keeps
        # the in-process pool when the store is down) must be fast.
        try:
            self._sup.prime()
        except BaseException:
            self._sup.close()
            raise

    def supervisor(self):
        return self._sup

    def execute_command(self, *args):
        return self._sup.call(lambda c: c.execute_command(*args))

    def pipeline(self, commands: list[tuple]) -> list:
        return self._sup.call(lambda c: c.pipeline(commands))

    # RespClient's full read/convenience surface, supervised.
    def keys(self, pattern: str = "*") -> list[str]:
        return self._sup.call(lambda c: c.keys(pattern))

    def zrange(self, key: str, start: int = 0, end: int = -1) -> list[str]:
        return self._sup.call(lambda c: c.zrange(key, start, end))

    def hgetall(self, key: str) -> dict[str, str]:
        return self._sup.call(lambda c: c.hgetall(key))

    def ping(self) -> bool:
        return self._sup.call(lambda c: c.ping())

    def flushdb(self) -> None:
        return self._sup.call(lambda c: c.flushdb())

    def hset(self, key: str, field: str, value: str) -> int:
        return self._sup.call(lambda c: c.hset(key, field, value))

    def hdel(self, key: str, *fields: str) -> int:
        return self._sup.call(lambda c: c.hdel(key, *fields))

    def hexists(self, key: str, field: str) -> bool:
        return self._sup.call(lambda c: c.hexists(key, field))

    def close(self) -> None:
        self._sup.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

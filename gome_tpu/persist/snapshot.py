"""Snapshot store + the Persister that wires it into the service loop."""

from __future__ import annotations

import json
import os
import shutil
import tempfile

import numpy as np

from ..utils.logging import get_logger

log = get_logger("persist")

_MANIFEST = "manifest.json"
_BOOKS = "books.npz"


class SnapshotStore:
    """Atomic, versioned snapshot directory.

    Layout: <dir>/snap-<n>/ containing manifest.json (everything JSON-able:
    cursors, interners, pre-pool, geometry) + books.npz (the array state).
    Written to a temp dir then os.rename'd — a crash mid-write leaves no
    torn snapshot, and restore picks the newest directory with a valid
    manifest ("DONE" marker is the manifest itself, written last).
    """

    def __init__(self, directory: str, keep: int = 4):
        self.dir = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _ids(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("snap-"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, manifest: dict, books: dict[str, np.ndarray]) -> str:
        ids = self._ids()
        snap_id = (ids[-1] + 1) if ids else 0
        final = os.path.join(self.dir, f"snap-{snap_id}")
        tmp = tempfile.mkdtemp(prefix=".tmp-snap-", dir=self.dir)
        try:
            books_path = os.path.join(tmp, _BOOKS)
            np.savez(books_path, **books)
            with open(books_path, "rb+") as f:
                os.fsync(f.fileno())
            # manifest last: its presence marks the snapshot complete
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, final)
            # fsync the parent dir so the rename itself survives power loss
            dirfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        ids = self._ids()
        for old in ids[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.dir, f"snap-{old}"), ignore_errors=True
            )

    def load_latest(self) -> tuple[dict, dict[str, np.ndarray]] | None:
        """Newest snapshot with a valid manifest, or None."""
        for snap_id in reversed(self._ids()):
            path = os.path.join(self.dir, f"snap-{snap_id}")
            try:
                with open(os.path.join(path, _MANIFEST)) as f:
                    manifest = json.load(f)
                with np.load(os.path.join(path, _BOOKS)) as z:
                    books = {k: z[k] for k in z.files}
                return manifest, books
            except Exception as e:  # torn npz raises BadZipFile etc.; any
                # unreadable snapshot must fall back to the previous one
                log.warning("skipping unreadable snapshot %s: %s", path, e)
        return None


class Persister:
    """Service-loop integration: cadence counting, consistent-cut capture,
    restore + replay rewind. Attach via EngineService(persist=...)."""

    def __init__(self, config):
        """config: gome_tpu.config.PersistConfig."""
        self.store = SnapshotStore(config.dir, keep=config.keep)
        self.every_n = config.every_n_batches
        self._batches = 0
        self.engine = None  # MatchEngine
        self.bus = None
        self.snapshots_taken = 0
        self.restored = False

    def attach(self, engine, bus) -> None:
        self.engine = engine
        self.bus = bus

    # -- called by OrderConsumer after each committed batch ------------------
    def on_batch(self, n_orders: int, n_events: int) -> None:
        self._batches += 1
        if self._batches >= self.every_n:
            self._batches = 0
            self.snapshot()

    def snapshot(self) -> str:
        """Capture a consistent cut. Must run from the consumer thread (or
        with the consumer idle): the cut is 'books == orders below the
        committed offset', which only holds between batches."""
        state = self.engine.batch.export_state()
        # The gateway thread mutates pre_pool concurrently; retry the copy on
        # the (tiny) window where iteration observes a mutation. Extra marks
        # captured here belong to orders published after the cut and are
        # reconciled from the order log on restore.
        for _ in range(100):
            try:
                pre_pool = sorted(self.engine.pre_pool)
                break
            except RuntimeError:
                continue
        else:
            raise RuntimeError(
                "could not copy pre_pool after 100 attempts (pathological "
                "concurrent marking); snapshot aborted"
            )
        manifest = {
            "version": 1,
            "order_committed": self.bus.order_queue.committed(),
            "match_end": self.bus.match_queue.end_offset(),
            "pre_pool": pre_pool,
            **{k: v for k, v in state.items() if k != "books"},
        }
        path = self.store.save(manifest, state["books"])
        self.snapshots_taken += 1
        log.info(
            "snapshot %s (orders<%d, matches<%d)",
            os.path.basename(path),
            manifest["order_committed"],
            manifest["match_end"],
        )
        return path

    def restore_latest(self) -> bool:
        """Restore books + pre-pool and rewind the bus to the snapshot cut.
        After this, the NORMAL consumer loop replays the order-log tail
        deterministically, regenerating the truncated match-queue tail
        exactly (see package docstring). Returns True if a snapshot was
        applied."""
        loaded = self.store.load_latest()
        oq = self.bus.order_queue
        mq = self.bus.match_queue
        # The pre-crash consumer position: tail messages below it were
        # consumed by the crashed process (their effects may have been
        # observable), messages at/above it never were.
        consumed_to = oq.committed()
        if loaded is not None:
            manifest, books = loaded
            self.engine.batch.import_state({**manifest, "books": books})
            # In place, not reassignment: the pool object may be a shared
            # remote marker store (prepool.RespPrePool) the gateway also
            # holds.
            self.engine.pre_pool.clear()
            self.engine.pre_pool.update(tuple(k) for k in manifest["pre_pool"])
            oq.rollback(manifest["order_committed"])
            # The feed may have committed past the cut before the crash;
            # replay regenerates byte-identical events, so rewind its cursor
            # and drop the stale tail.
            mq.rollback(min(mq.committed(), manifest["match_end"]))
            mq.truncate_to(manifest["match_end"])
            self.restored = True
        elif oq.committed() > 0:
            # Durable order log but no snapshot yet (crash before the first
            # cadence tick): the engine is fresh/empty, so the only
            # consistent cut is offset 0 — rewind and replay the ENTIRE log;
            # the truncated match queue is regenerated deterministically.
            oq.rollback(0)
            mq.rollback(0)
            mq.truncate_to(0)
        replayed = self._reconstruct_marks(
            cut=oq.committed(), consumed_to=consumed_to
        )
        if loaded is not None or replayed:
            log.info(
                "recovery: snapshot=%s, %d queued ops to replay",
                "yes" if loaded is not None else "no",
                replayed,
            )
        return loaded is not None

    def _reconstruct_marks(self, cut: int, consumed_to: int) -> int:
        """Rebuild pre-pool marks for ADDs queued at/after `cut` (they were
        marked in the crashed process's memory: the gateway marks BEFORE
        publishing, main.go:44-45 ordering — so every queued ADD carried a
        mark).

        One refinement separates two cases by `consumed_to` (the pre-crash
        consumer position):

        * ADD consumed pre-crash (offset < consumed_to): its admission
          decision may already be observable (fills delivered to live
          subscribers), so replay must re-admit — always re-mark. The
          realizable serialization: the mark was placed at publish time,
          after every DEL consumed before it.
        * ADD never consumed (offset >= consumed_to): no decision was made,
          so any realizable interleaving is valid; we choose NOT to re-mark
          when the key's latest committed message below the cut is a DEL —
          that DEL's cancel semantics were observable (event below
          match_end), and resurrecting a cancelled order would surprise
          (SURVEY §2.3.3's race, resolved deterministically at recovery).

        Residual ambiguity (documented, not resolvable from the log alone):
        a DEL *inside* the consumed tail followed by a same-key ADD replays
        as drop, while the crashed process may have raced to admit. Both
        outcomes are realizable serializations of the reference's racy
        pre-pool; eliminating the race entirely would need a durable mark
        log (fsync per gateway mark — rejected as the wrong latency trade).
        """
        from ..bus import decode_message_orders
        from ..types import Action

        def orders_in(m):
            # A frame's whole batch shares the message offset (it consumes
            # atomically), so the offset-based logic below is unchanged.
            return decode_message_orders(m.body)

        oq = self.bus.order_queue
        tail = oq.read_from(cut, oq.end_offset() - cut)
        suppressible = set()  # keys of never-consumed ADDs
        tail_adds: list[tuple[int, tuple]] = []
        for m in tail:
            for order in orders_in(m):
                if order.action is Action.ADD:
                    key = (order.symbol, order.uuid, order.oid)
                    tail_adds.append((m.offset, key))
                    if m.offset >= consumed_to:
                        suppressible.add(key)
        if not tail_adds:
            return len(tail)
        # Last committed action per suppressible key (recovery-only scan).
        last_committed: dict[tuple, Action] = {}
        pos = 0
        while pos < cut and suppressible:
            for m in oq.read_from(pos, min(4096, cut - pos)):
                for order in orders_in(m):
                    key = (order.symbol, order.uuid, order.oid)
                    if key in suppressible:
                        last_committed[key] = order.action
                pos = m.offset + 1
        remark = [
            key
            for offset, key in tail_adds
            if not (
                offset >= consumed_to
                and last_committed.get(key) is Action.DEL
            )
        ]
        # One batched update: with a remote marker store this is a single
        # pipelined round trip instead of one HSET per queued ADD (a tail
        # of 256K-order frames would otherwise take minutes to re-mark).
        self.engine.pre_pool.update(remark)
        return len(tail)

"""Snapshot store + the Persister that wires it into the service loop."""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

import numpy as np

from ..utils.faults import FAULTS
from ..utils.logging import get_logger

log = get_logger("persist")

_MANIFEST = "manifest.json"
_BOOKS = "books.npz"


class SnapshotStore:
    """Atomic, versioned snapshot directory.

    Layout: <dir>/snap-<n>/ containing manifest.json (everything JSON-able:
    cursors, interners, pre-pool, geometry) + books.npz (the array state).
    Written to a temp dir then os.rename'd — a crash mid-write leaves no
    torn snapshot, and restore picks the newest directory with a valid
    manifest ("DONE" marker is the manifest itself, written last).
    """

    def __init__(self, directory: str, keep: int = 4):
        self.dir = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _ids(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("snap-"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, manifest: dict, books: dict[str, np.ndarray]) -> str:
        ids = self._ids()
        snap_id = (ids[-1] + 1) if ids else 0
        final = os.path.join(self.dir, f"snap-{snap_id}")
        tmp = tempfile.mkdtemp(prefix=".tmp-snap-", dir=self.dir)
        try:
            books_path = os.path.join(tmp, _BOOKS)
            np.savez(books_path, **books)
            with open(books_path, "rb+") as f:
                os.fsync(f.fileno())
            # manifest last: its presence marks the snapshot complete
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            cut = FAULTS.fire("snapshot.rename")
            if cut:
                # Torn publish: truncate the manifest inside tmp, complete
                # the rename anyway, and die — load_latest must skip the
                # unreadable snapshot and fall back to the previous one.
                mpath = os.path.join(tmp, _MANIFEST)
                with open(mpath, "rb+") as f:
                    f.truncate(cut % os.path.getsize(mpath))
                os.rename(tmp, final)
                FAULTS.hard_exit()
            os.rename(tmp, final)
            # fsync the parent dir so the rename itself survives power loss
            dirfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        ids = self._ids()
        for old in ids[: -self.keep]:
            shutil.rmtree(
                os.path.join(self.dir, f"snap-{old}"), ignore_errors=True
            )

    def load_latest(self) -> tuple[dict, dict[str, np.ndarray]] | None:
        """Newest snapshot with a valid manifest, or None."""
        for snap_id in reversed(self._ids()):
            path = os.path.join(self.dir, f"snap-{snap_id}")
            try:
                with open(os.path.join(path, _MANIFEST)) as f:
                    manifest = json.load(f)
                with np.load(os.path.join(path, _BOOKS)) as z:
                    books = {k: z[k] for k in z.files}
                return manifest, books
            except Exception as e:  # torn npz raises BadZipFile etc.; any
                # unreadable snapshot must fall back to the previous one
                log.warning("skipping unreadable snapshot %s: %s", path, e)
        return None


class Persister:
    """Service-loop integration: cadence counting, consistent-cut capture,
    restore + replay rewind. Attach via EngineService(persist=...)."""

    def __init__(self, config):
        """config: gome_tpu.config.PersistConfig."""
        self.store = SnapshotStore(config.dir, keep=config.keep)
        self.every_n = config.every_n_batches
        self._batches = 0  # single-writer: the consuming thread (on_batch)
        self.engine = None  # MatchEngine  # single-writer: attach() caller
        self.bus = None  # single-writer: attach() caller
        self.consumer = None  # single-writer: attach() caller (matchfeed seq recovery)
        self.snapshots_taken = 0  # single-writer: the consuming thread
        self.restored = False  # single-writer: restore_latest() caller
        # Durability telemetry (/durability payload, gome_* gauges, the
        # timeline probe). Written from the consuming thread / the
        # restore_latest() caller only; the ops HTTP thread reads it
        # off-lock (floats and small ints are single-bytecode loads —
        # stale at worst, never torn).
        self.last_snapshot_unix = 0.0  # single-writer: the consuming thread
        self.last_snapshot_bytes = 0  # single-writer: the consuming thread
        self.last_restore = "never"  # single-writer: restore_latest() caller
        self.last_recovery_seconds = 0.0  # single-writer: restore_latest() caller
        self.wal_replay_frames = 0  # single-writer: restore_latest() caller

    def attach(self, engine, bus, consumer=None) -> None:
        self.engine = engine
        self.bus = bus
        if consumer is not None:
            self.consumer = consumer

    # -- called by OrderConsumer after each committed batch ------------------
    def on_batch(self, n_orders: int, n_events: int) -> None:
        self._batches += 1
        if self._batches >= self.every_n:
            self._batches = 0
            self.snapshot()

    def snapshot(self) -> str:
        """Capture a consistent cut. Must run from the consumer thread (or
        with the consumer idle): the cut is 'books == orders below the
        committed offset', which only holds between batches."""
        state = self.engine.batch.export_state()
        # The gateway thread mutates pre_pool concurrently; retry the copy on
        # the (tiny) window where iteration observes a mutation. Extra marks
        # captured here belong to orders published after the cut and are
        # reconciled from the order log on restore.
        for _ in range(100):
            try:
                pre_pool = sorted(self.engine.pre_pool)
                break
            except RuntimeError:
                continue
        else:
            raise RuntimeError(
                "could not copy pre_pool after 100 attempts (pathological "
                "concurrent marking); snapshot aborted"
            )
        manifest = {
            "version": 1,
            "order_committed": self.bus.order_queue.committed(),
            "match_end": self.bus.match_queue.end_offset(),
            # Matchfeed seq at the cut: every event below match_end carries
            # a seq below this (exactly-once suppression after restore).
            "match_seq": (
                self.consumer.match_seq if self.consumer is not None else 0
            ),
            "pre_pool": pre_pool,
            **{k: v for k, v in state.items() if k != "books"},
        }
        path = self.store.save(manifest, state["books"])
        self.snapshots_taken += 1
        self.last_snapshot_unix = time.time()
        try:
            self.last_snapshot_bytes = sum(
                os.path.getsize(os.path.join(path, n)) for n in os.listdir(path)
            )
        except OSError:
            pass
        log.info(
            "snapshot %s (orders<%d, matches<%d)",
            os.path.basename(path),
            manifest["order_committed"],
            manifest["match_end"],
        )
        return path

    def restore_latest(self) -> bool:
        """Restore books + pre-pool and rewind the bus to the snapshot cut.
        After this, the NORMAL consumer loop replays the order-log tail
        deterministically, regenerating the truncated match-queue tail
        exactly (see package docstring). Returns True if a snapshot was
        applied."""
        t0 = time.monotonic()
        loaded = self.store.load_latest()
        oq = self.bus.order_queue
        mq = self.bus.match_queue
        # The pre-crash consumer position: tail messages below it were
        # consumed by the crashed process (their effects may have been
        # observable), messages at/above it never were.
        consumed_to = oq.committed()
        if loaded is not None:
            manifest, books = loaded
            self.engine.batch.import_state({**manifest, "books": books})
            # In place, not reassignment: the pool object may be a shared
            # remote marker store (prepool.RespPrePool) the gateway also
            # holds.
            self.engine.pre_pool.clear()
            self.engine.pre_pool.update(tuple(k) for k in manifest["pre_pool"])
            # The snapshot is the authority on the cut. Normally the cut is
            # at/below the committed offset (rollback); after a TORN
            # .offset sidecar the recovered committed offset can sit BELOW
            # the cut (FileQueue falls back to a conservative digit
            # prefix) — the snapshot proves orders below the cut are fully
            # applied, so seek forward instead of replaying them onto
            # restored books (found by scripts/chaos.py's torn-sidecar
            # schedule).
            cut = manifest["order_committed"]
            if cut <= oq.committed():
                oq.rollback(cut)
            else:
                oq.commit(cut)
            # The feed may have committed past the cut before the crash;
            # replay regenerates byte-identical events, so rewind its cursor
            # and drop the stale tail.
            mq.rollback(min(mq.committed(), manifest["match_end"]))
            mq.truncate_to(manifest["match_end"])
            if self.consumer is not None:
                # Replay regenerates the truncated match tail with the
                # SAME seqs it had pre-crash (exactly-once across restarts).
                self.consumer.reset_seq(int(manifest.get("match_seq", 0)))
            self.restored = True
        elif oq.committed() > 0 or mq.end_offset() > 0:
            # Durable order log but no snapshot yet (crash before the first
            # cadence tick): the engine is fresh/empty, so the only
            # consistent cut is offset 0 — rewind and replay the ENTIRE log;
            # the truncated match queue is regenerated deterministically.
            # The mq conditions cover a crash BEFORE the first order-queue
            # commit but AFTER a match publish (the at-least-once window at
            # offset 0): without truncation the replay would re-publish
            # those events as queue-level duplicates (found by
            # scripts/chaos.py's first-frame kill).
            oq.rollback(0)
            mq.rollback(0)
            mq.truncate_to(0)
            if self.consumer is not None:
                self.consumer.reset_seq(0)
        replayed = self._reconstruct_marks(
            cut=oq.committed(), consumed_to=consumed_to
        )
        self.wal_replay_frames = replayed
        self.last_recovery_seconds = time.monotonic() - t0
        self.last_restore = (
            "restored"
            if loaded is not None
            else ("replayed" if replayed else "none")
        )
        if loaded is not None or replayed:
            log.info(
                "recovery: snapshot=%s, %d queued ops to replay",
                "yes" if loaded is not None else "no",
                replayed,
            )
        return loaded is not None

    def _reconstruct_marks(self, cut: int, consumed_to: int) -> int:
        """Rebuild pre-pool marks for ADDs queued at/after `cut` (they were
        marked in the crashed process's memory: the gateway marks BEFORE
        publishing, main.go:44-45 ordering — so every queued ADD carried a
        mark).

        One refinement separates two cases by `consumed_to` (the pre-crash
        consumer position):

        * ADD consumed pre-crash (offset < consumed_to): its admission
          decision may already be observable (fills delivered to live
          subscribers), so replay must re-admit — always re-mark. The
          realizable serialization: the mark was placed at publish time,
          after every DEL consumed before it.
        * ADD never consumed (offset >= consumed_to): no decision was made,
          so any realizable interleaving is valid; we choose NOT to re-mark
          when the key's latest committed message below the cut is a DEL —
          that DEL's cancel semantics were observable (event below
          match_end), and resurrecting a cancelled order would surprise
          (SURVEY §2.3.3's race, resolved deterministically at recovery).

        Residual ambiguity (documented, not resolvable from the log alone):
        a DEL *inside* the consumed tail followed by a same-key ADD replays
        as drop, while the crashed process may have raced to admit. Both
        outcomes are realizable serializations of the reference's racy
        pre-pool; eliminating the race entirely would need a durable mark
        log (fsync per gateway mark — rejected as the wrong latency trade).
        """
        from ..bus import decode_message_orders
        from ..types import Action

        def orders_in(m):
            # A frame's whole batch shares the message offset (it consumes
            # atomically), so the offset-based logic below is unchanged.
            return decode_message_orders(m.body)

        oq = self.bus.order_queue
        tail = oq.read_from(cut, oq.end_offset() - cut)
        suppressible = set()  # keys of never-consumed ADDs
        tail_adds: list[tuple[int, tuple]] = []
        for m in tail:
            for order in orders_in(m):
                if order.action is Action.ADD:
                    key = (order.symbol, order.uuid, order.oid)
                    tail_adds.append((m.offset, key))
                    if m.offset >= consumed_to:
                        suppressible.add(key)
        if not tail_adds:
            return len(tail)
        # Last committed action per suppressible key (recovery-only scan).
        last_committed: dict[tuple, Action] = {}
        pos = 0
        while pos < cut and suppressible:
            for m in oq.read_from(pos, min(4096, cut - pos)):
                for order in orders_in(m):
                    key = (order.symbol, order.uuid, order.oid)
                    if key in suppressible:
                        last_committed[key] = order.action
                pos = m.offset + 1
        remark = [
            key
            for offset, key in tail_adds
            if not (
                offset >= consumed_to
                and last_committed.get(key) is Action.DEL
            )
        ]
        # One batched update: with a remote marker store this is a single
        # pipelined round trip instead of one HSET per queued ADD (a tail
        # of 256K-order frames would otherwise take minutes to re-mark).
        self.engine.pre_pool.update(remark)
        return len(tail)

    # -- observability -------------------------------------------------------
    def snapshot_age_seconds(self) -> float:
        """Seconds since the last snapshot; -1 before the first one."""
        if not self.last_snapshot_unix:
            return -1.0
        return max(0.0, time.time() - self.last_snapshot_unix)

    def export_metrics(self, registry=None) -> None:
        """Register the durability gauges (callback gauges: values are read
        from this Persister at scrape time; re-registering rebinds)."""
        if registry is None:
            from ..utils.metrics import REGISTRY as registry  # noqa: N811
        registry.callback_gauge(
            "gome_snapshot_age_seconds",
            "Seconds since the last snapshot (-1 before the first)",
            self.snapshot_age_seconds,
        )
        registry.callback_gauge(
            "gome_snapshot_bytes",
            "On-disk size of the last snapshot",
            lambda: float(self.last_snapshot_bytes),
        )
        registry.callback_gauge(
            "gome_snapshots_taken_total",
            "Snapshots taken by this process",
            lambda: float(self.snapshots_taken),
        )
        registry.callback_gauge(
            "gome_recovery_seconds",
            "Duration of the last restore_latest (restore + mark rebuild)",
            lambda: self.last_recovery_seconds,
        )
        registry.callback_gauge(
            "gome_wal_replay_frames",
            "Order-log messages rewound for replay by the last restore",
            lambda: float(self.wal_replay_frames),
        )

    def probe(self) -> dict:
        """TimelineSampler probe: snapshot cadence + recovery state."""
        return {
            "snapshots_taken": self.snapshots_taken,
            "snapshot_age_s": round(self.snapshot_age_seconds(), 3),
            "snapshot_bytes": self.last_snapshot_bytes,
            "last_restore": self.last_restore,
            "recovery_s": round(self.last_recovery_seconds, 6),
            "wal_replay_frames": self.wal_replay_frames,
        }

"""Export the engine's book state to the reference's exact Redis schema.

This makes the TPU engine's state inspectable by any tooling written against
the reference's keys (SURVEY §2.1): for a symbol S, scaled price P, user U,
order O —

  S:BUY / S:SALE   zset   one member per occupied level, score = member =
                          scaled price (nodepool.go:71-73)
  S:depth          hash   field "S:depth:P" -> aggregate resting volume
                          (nodepool.go:61-63, ordernode.go:104-108)
  S:link:P         hash   "f"/"l" head/tail node names + one field
                          "S:node:O" per resting order holding the
                          JSON-encoded node with FIFO prev/next pointers
                          (nodelink.go; ordernode.go:110-117)
  S:comparison     hash   field "S:U:O" -> "1" per pre-pool mark
                          (nodepool.go:14-16, ordernode.go:89-92)

Command generation needs no Redis client (returns (cmd, *args) tuples,
testable offline); `export_to_redis` applies them and is gated on redis-py,
which this environment does not ship.
"""

from __future__ import annotations

import json

import numpy as np

from ..types import Action

_SIDE_KEY = {0: "BUY", 1: "SALE"}  # ordernode.go:94-102 zset key suffixes


def _fmt_price(ticks: int) -> str:
    """The reference renders scaled prices through shopspring decimal's
    String() on a float-held integer (ordernode.go:106,115) — for in-range
    integers that is the plain integer string."""
    return str(int(ticks))


def _node_json(
    symbol: str, uuid: str, oid: str, side: int, price: int, volume: int,
    prev_oid: str | None, next_oid: str | None, accuracy: int,
) -> str:
    """The resting-node JSON the reference stores in S:link:P (the
    serialized OrderNode, ordernode.go:9-36: domain fields + linked-list
    pointers + derived key names)."""
    node_name = f"{symbol}:node:{oid}"
    price_s = _fmt_price(price)
    return json.dumps(
        {
            "Action": int(Action.ADD),
            "Uuid": uuid,
            "Oid": oid,
            "Symbol": symbol,
            "Transaction": side,
            "Price": price,
            "Volume": volume,
            "Accuracy": accuracy,
            "NodeName": node_name,
            "IsFirst": prev_oid is None,
            "IsLast": next_oid is None,
            "PrevNode": f"{symbol}:node:{prev_oid}" if prev_oid else "",
            "NextNode": f"{symbol}:node:{next_oid}" if next_oid else "",
            "NodeLink": f"{symbol}:link:{price_s}",
            "OrderHashKey": f"{symbol}:comparison",
            "OrderHashField": f"{symbol}:{uuid}:{oid}",
            "OrderListZsetKey": f"{symbol}:{_SIDE_KEY[side]}",
            "OrderListZsetRKey": f"{symbol}:{_SIDE_KEY[1 - side]}",
            "OrderDepthHashKey": f"{symbol}:depth",
            "OrderDepthHashField": f"{symbol}:depth:{price_s}",
        },
        separators=(",", ":"),
    )


def book_redis_commands(
    engine, accuracy: int = 8, include_pre_pool: bool = True
) -> list[tuple]:
    """Generate the full command list re-creating the engine's current book
    state under the reference schema. `engine` is a MatchEngine (or anything
    with .batch and .pre_pool)."""
    batch = engine.batch
    books = batch.lane_books()
    cmds: list[tuple] = []
    n_lanes = int(books.count.shape[0])
    for lane in range(n_lanes):
        sym_id = lane + 1
        if sym_id >= len(batch.symbols):
            continue
        symbol = batch.symbols.lookup(sym_id)
        for side in (0, 1):
            count = int(books.count[lane, side])
            if count == 0:
                continue
            zset_key = f"{symbol}:{_SIDE_KEY[side]}"
            prices = np.asarray(books.price[lane, side][:count])
            lots = np.asarray(books.lots[lane, side][:count])
            oids = np.asarray(books.oid[lane, side][:count])
            uids = np.asarray(books.uid[lane, side][:count])
            # slots are priority-sorted; group contiguous equal prices into
            # levels (book.py invariant) — FIFO order within level is slot
            # order, which becomes the linked-list order.
            level_start = 0
            for i in range(count + 1):
                if i < count and prices[i] == prices[level_start]:
                    continue
                level = slice(level_start, i)
                p = int(prices[level_start])
                p_s = _fmt_price(p)
                cmds.append(("ZADD", zset_key, float(p), p_s))
                cmds.append(
                    (
                        "HSET",
                        f"{symbol}:depth",
                        f"{symbol}:depth:{p_s}",
                        str(int(lots[level].sum())),
                    )
                )
                link_key = f"{symbol}:link:{p_s}"
                level_oids = [
                    batch.oids.lookup(int(o)) for o in oids[level]
                ]
                level_uids = [
                    batch.uids.lookup(int(u)) for u in uids[level]
                ]
                cmds.append(
                    ("HSET", link_key, "f", f"{symbol}:node:{level_oids[0]}")
                )
                cmds.append(
                    ("HSET", link_key, "l", f"{symbol}:node:{level_oids[-1]}")
                )
                for j, oid in enumerate(level_oids):
                    cmds.append(
                        (
                            "HSET",
                            link_key,
                            f"{symbol}:node:{oid}",
                            _node_json(
                                symbol,
                                level_uids[j],
                                oid,
                                side,
                                p,
                                int(lots[level][j]),
                                level_oids[j - 1] if j > 0 else None,
                                level_oids[j + 1]
                                if j + 1 < len(level_oids)
                                else None,
                                accuracy,
                            ),
                        )
                    )
                level_start = i
    if include_pre_pool:
        for symbol, uuid, oid in sorted(engine.pre_pool):
            cmds.append(
                ("HSET", f"{symbol}:comparison", f"{symbol}:{uuid}:{oid}", "1")
            )
    return cmds


def export_to_redis(engine, accuracy: int = 8, client=None, flush: bool = False):
    """Apply book_redis_commands to a live Redis. Gated: redis-py is not in
    this image, so a client (or an object with execute_command) must be
    injectable for tests."""
    if client is None:
        try:
            import redis  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "redis-py is not installed; pass an explicit client with an "
                "execute_command(*args) method"
            ) from e
        client = redis.Redis()
    if flush:
        client.execute_command("FLUSHDB")
    cmds = book_redis_commands(engine, accuracy=accuracy)
    for cmd in cmds:
        client.execute_command(*cmd)
    return len(cmds)

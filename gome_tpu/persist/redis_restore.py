"""Restore engine state FROM the reference's Redis schema — the inverse of
redis_schema.py, and the live-migration path: a running gome deployment's
entire order book (SURVEY §2.1 — Redis IS its book) imports into the TPU
engine, which then continues matching the same symbols with exact
semantics.

Schema read (all keys per SURVEY §2.1 / nodepool.go / nodelink.go):

  S:BUY / S:SALE   zset   members = scaled price strings -> the levels
  S:link:P         hash   "f" head node name, "l" tail, one field per
                          resting order holding the JSON node with FIFO
                          NextNode pointers — walked head-to-tail, which
                          also sidesteps the reference's leaked-entry quirk
                          (DeleteLinkNode leaves unreachable JSON behind,
                          SURVEY §2.3.1: unreachable entries are simply
                          never visited)
  S:comparison     hash   pre-pool marks -> MatchEngine.pre_pool
  S:depth          hash   aggregate level volumes — used as a consistency
                          check (warn on mismatch, trust the FIFO lists)

The store argument needs three read primitives (`keys`, `zrange`,
`hgetall`) — satisfied by redis-py and by DictRedis, the in-memory store
that also accepts redis_schema's command stream (export -> import
round-trips are tested offline, no server needed).
"""

from __future__ import annotations

import fnmatch
import json
from decimal import Decimal

import numpy as np

from ..engine.book import BUY


class DictRedis:
    """Minimal in-memory Redis: enough write commands for
    redis_schema.book_redis_commands and the three read primitives the
    restore needs. Doubles as an offline snapshot target."""

    def __init__(self):
        self.zsets: dict[str, dict[str, float]] = {}
        self.hashes: dict[str, dict[str, str]] = {}

    # -- write side (redis_schema's command stream) ------------------------
    def execute_command(self, *args):
        cmd = args[0].upper()
        if cmd == "ZADD":
            _, key, score, member = args
            self.zsets.setdefault(key, {})[member] = float(score)
        elif cmd == "HSET":
            _, key, field, value = args
            self.hashes.setdefault(key, {})[field] = value
        elif cmd == "FLUSHDB":
            self.zsets.clear()
            self.hashes.clear()
        else:
            raise ValueError(f"DictRedis does not support {cmd}")

    # -- read side (the restore's primitives) ------------------------------
    def keys(self, pattern: str = "*") -> list[str]:
        all_keys = list(self.zsets) + list(self.hashes)
        return [k for k in all_keys if fnmatch.fnmatch(k, pattern)]

    def zrange(self, key: str, start: int = 0, end: int = -1) -> list[str]:
        members = sorted(
            self.zsets.get(key, {}).items(), key=lambda kv: kv[1]
        )
        out = [m for m, _ in members]
        end = len(out) if end == -1 else end + 1
        return out[start:end]

    def hgetall(self, key: str) -> dict[str, str]:
        return dict(self.hashes.get(key, {}))


def _as_str(v) -> str:
    return v.decode() if isinstance(v, bytes) else str(v)


def _ticks(v) -> int:
    """Reference numerics round-trip through floats/strings (SURVEY §2.2);
    Decimal parsing keeps in-range integers exact where float() wouldn't."""
    return int(Decimal(_as_str(v)))


def _walk_level(link: dict[str, str]) -> list[dict]:
    """S:link:P hash -> resting nodes head-to-tail (FIFO)."""
    link = {_as_str(k): _as_str(v) for k, v in link.items()}
    head = link.get("f", "")
    out = []
    seen = set()
    name = head
    while name and name in link and name not in seen:
        seen.add(name)
        node = json.loads(link[name])
        out.append(node)
        name = node.get("NextNode", "") or ""
    return out


def discover_symbols(store) -> list[str]:
    """Symbols present in the store (their BUY/SALE zsets or pre-pool)."""
    syms = set()
    for key in store.keys("*"):
        key = _as_str(key)
        for suffix in (":BUY", ":SALE", ":comparison"):
            if key.endswith(suffix):
                syms.add(key[: -len(suffix)])
    return sorted(syms)


def read_book(store, symbol: str):
    """-> (per-side lists of node dicts in priority order, pre-pool keys).
    Each node: {uuid, oid, price(int ticks), volume(int lots)}."""
    depth_hash = {
        _as_str(k): v for k, v in store.hgetall(f"{symbol}:depth").items()
    }
    sides = []
    for side, zkey_sfx in ((0, "BUY"), (1, "SALE")):
        members = store.zrange(f"{symbol}:{zkey_sfx}", 0, -1)
        prices = sorted(
            (_ticks(m) for m in members), reverse=(side == BUY)
        )
        slots = []
        for p in prices:
            link = store.hgetall(f"{symbol}:link:{p}")
            nodes = _walk_level(link)
            level_volume = 0
            for node in nodes:
                volume = _ticks(node["Volume"])
                level_volume += volume
                slots.append(
                    dict(
                        uuid=str(node["Uuid"]),
                        oid=str(node["Oid"]),
                        price=p,
                        volume=volume,
                    )
                )
            depth = depth_hash.get(f"{symbol}:depth:{p}")
            if depth is not None and _ticks(depth) != level_volume:
                import warnings

                warnings.warn(
                    f"{symbol} level {p}: depth hash says {_as_str(depth)} "
                    f"but FIFO list sums to {level_volume}; trusting the "
                    "list (the reference's own HIncrByFloat residue quirk, "
                    "SURVEY §2.3)",
                    RuntimeWarning,
                    stacklevel=2,
                )
        sides.append(slots)
    marks = []
    for field in store.hgetall(f"{symbol}:comparison"):
        parts = _as_str(field).split(":")
        if len(parts) >= 3:
            marks.append((parts[0], parts[1], ":".join(parts[2:])))
    return sides, marks


def restore_from_redis(engine, store, symbols: list[str] | None = None) -> int:
    """Populate a MatchEngine from a store holding the reference schema.
    Replaces the engine's books and pre-pool; returns the number of resting
    orders imported. The engine keeps its configured dtype/max_fills/max_t;
    cap and lane count grow to fit the imported book."""
    from ..engine.batch import _next_pow2

    if symbols is None:
        symbols = discover_symbols(store)
    books = {}
    all_marks = set()
    max_side = 0
    for symbol in symbols:
        sides, marks = read_book(store, symbol)
        books[symbol] = sides
        all_marks.update(marks)
        max_side = max(max_side, len(sides[0]), len(sides[1]))

    batch = engine.batch
    cap = max(batch.config.cap, _next_pow2(max(max_side, 1)))
    n_slots = max(batch.n_slots, _next_pow2(max(len(symbols), 1)))
    if batch.mesh is not None and n_slots % batch.mesh.size:
        m = batch.mesh.size
        n_slots = ((n_slots + m - 1) // m) * m

    dtype = np.dtype(batch.config.dtype)
    rebase = dtype.itemsize <= 4
    symbols_list = list(symbols)
    oid_strings: list[str] = []
    uid_strings: list[str] = []
    oid_ix: dict[str, int] = {}
    uid_ix: dict[str, int] = {}

    def intern(table, ix, s):
        i = ix.get(s)
        if i is None:
            i = len(table) + 1  # interner ids start at 1
            ix[s] = i
            table.append(s)
        return i

    shape = (n_slots, 2, cap)
    price = np.zeros(shape, np.int64)
    lots = np.zeros(shape, np.int64)
    seq = np.zeros(shape, np.int32)
    oid = np.zeros(shape, np.int64)
    uid = np.zeros(shape, np.int64)
    count = np.zeros((n_slots, 2), np.int32)
    next_seq = np.zeros(n_slots, np.int32)
    price_base = np.zeros(n_slots, np.int64)
    base_set = np.zeros(n_slots, bool)
    env_lo = np.zeros(n_slots, np.int64)
    env_hi = np.zeros(n_slots, np.int64)

    total = 0
    for lane, symbol in enumerate(symbols_list):
        sides = books[symbol]
        lane_prices = [s["price"] for side in sides for s in side]
        if rebase and lane_prices:
            lo, hi = min(lane_prices), max(lane_prices)
            base = (lo + hi) // 2
            if max(hi - base, base - lo) > (1 << 31) - 2:
                raise ValueError(
                    f"{symbol}: resting price range [{lo}, {hi}] cannot fit "
                    "an int32 window; restore into an int64 engine"
                )
            price_base[lane] = base
            base_set[lane] = True
            env_lo[lane], env_hi[lane] = lo, hi
        stamp = 0
        for side in (0, 1):
            for slot, node in enumerate(sides[side]):
                stamp += 1
                price[lane, side, slot] = node["price"] - price_base[lane]
                lots[lane, side, slot] = node["volume"]
                seq[lane, side, slot] = stamp
                oid[lane, side, slot] = intern(
                    oid_strings, oid_ix, node["oid"]
                )
                uid[lane, side, slot] = intern(
                    uid_strings, uid_ix, node["uuid"]
                )
                total += 1
            count[lane, side] = len(sides[side])
        next_seq[lane] = stamp + 1

    val_dtype = dtype.name
    state = {
        "books": {
            "price": price.astype(dtype),
            "lots": lots.astype(dtype),
            "seq": seq,
            "oid": oid.astype(dtype),
            "uid": uid.astype(dtype),
            "count": count,
            "next_seq": next_seq,
        },
        "symbols": symbols_list,
        "oids": oid_strings,
        "uids": uid_strings,
        "cap": cap,
        "max_fills": batch.config.max_fills,
        "dtype": val_dtype,
        "n_slots": n_slots,
        "max_t": batch.max_t,
        "price_base": price_base.tolist(),
        "base_set": base_set.astype(int).tolist(),
        "env_lo": env_lo.tolist(),
        "env_hi": env_hi.tolist(),
    }
    batch.import_state(state)
    # In place (the pool object may be shared with a gateway); plain set
    # assignment would also silently bypass a remote marker store.
    engine.pre_pool.clear()
    engine.pre_pool.update(all_marks)
    return total

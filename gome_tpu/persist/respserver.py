"""In-process fake Redis server speaking RESP2 — the test/bench double for
a real Redis, mirroring what bus/fakebroker.py is for RabbitMQ.

Implements the command subset the reference engine issues against its book
schema (SURVEY §2.1; gomengine/nodepool.go, nodelink.go, redis.go) plus
what redis_schema/redis_restore and the RESP pre-pool need: hash ops
(HSET/HDEL/HEXISTS/HGET/HGETALL/HINCRBYFLOAT), zset ops
(ZADD/ZREM/ZRANGE/ZREVRANGE/ZRANGEBYSCORE/ZREVRANGEBYSCORE), KEYS, DEL,
EXISTS, PING/ECHO/SELECT/AUTH/FLUSHDB. Pipelined commands are handled
naturally (the parser drains the connection buffer command by command).

Runnable standalone for multi-process topologies:

    python -m gome_tpu.persist.respserver --port 6379

(prints "READY <port>" on stdout once listening; port 0 picks a free one.)
"""

from __future__ import annotations

import fnmatch
import socket
import threading

from .resp import _Reader


class _Store:
    """The keyspace: hashes + zsets (the only types the schema uses),
    str -> str internally, one lock (Redis itself is single-threaded)."""

    def __init__(self):
        self.hashes: dict[str, dict[str, str]] = {}
        self.zsets: dict[str, dict[str, float]] = {}
        self.lock = threading.Lock()

    def keys(self):
        return list(self.hashes) + list(self.zsets)


def _s(v) -> str:
    return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)


def _score(v) -> float:
    s = _s(v)
    if s in ("-inf", "+inf", "inf"):
        return float(s)
    if s.startswith("("):  # exclusive bound: approximate (schema never uses)
        return float(s[1:])
    return float(s)


def _fmt_float(x: float) -> str:
    """Redis renders integral floats without the trailing .0"""
    i = int(x)
    return str(i) if x == i else repr(x)


class FakeRedisServer:
    """Threaded RESP2 server over an in-memory store. start() returns the
    bound port; stop() closes the listener and every live connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        # single-writer (lifecycle state below): the start()/stop()/
        # restart() caller — the test or chaos drill driving the bounce;
        # the accept/client threads only append via method calls, which
        # the restart drill joins behind stop().
        self.host = host
        self.port = port  # single-writer: start()/restart() caller
        self.store = _Store()  # single-writer: restart() caller (kept keyspace)
        self._listener: socket.socket | None = None  # single-writer: start()/stop() caller
        self._threads: list[threading.Thread] = []  # single-writer: start()/restart() caller
        self._conns: list[socket.socket] = []  # single-writer: restart() caller
        self._stop = threading.Event()  # single-writer: restart() caller (rebound)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(
            target=self._accept_loop, name="fakeredis-accept", daemon=True
        )
        t.start()
        self._threads.append(t)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            # Wake the accept thread: blocked accept() holds the listener's
            # open file description, so the LISTEN socket would linger
            # (blocking a same-port restart) until a connection arrives.
            try:
                socket.create_connection(
                    (self.host, self.port), timeout=0.2
                ).close()
            except OSError:
                pass
        for c in self._conns:
            # shutdown() first: close() alone neither wakes a thread
            # blocked in recv() on this socket nor tells the peer — the
            # restart drill needs clients to see the death immediately.
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def restart(self) -> int:
        """Fault injection: bounce the server — drop the listener and
        every live connection (clients see ECONNRESET mid-command, like a
        real Redis restart), then come back on the SAME port with the
        SAME keyspace (a restart with an RDB/AOF-backed store; marker
        state survives, sessions do not). Returns the port."""
        import time

        port, store = self.port, self.store
        self.stop()
        self._stop = threading.Event()
        self._threads = []
        self._conns = []
        # gomelint: disable=GL704 — false edge: the accept loop's
        # `t.start()` (a Thread) resolves by bare name to self.start() in
        # the conservative call graph; only the drill caller runs here.
        self.port = port  # gomelint: disable=GL704
        self.store = store
        # The dead connections' sockets can hold the port for a beat even
        # with SO_REUSEADDR; retry the bind briefly rather than flaking.
        for _ in range(100):
            try:
                return self.start()
            except OSError:
                time.sleep(0.02)
        return self.start()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        reader = _Reader(conn)
        out = bytearray()

        def read_command():
            """RESP-array command, or a real-Redis-parity INLINE command
            (a bare space-separated line — redis-cli/telnet send these;
            the RESP client never does, so this is exactly the kind of
            input an in-repo fake would otherwise never see)."""
            while len(reader._buf) - reader._pos < 1:
                reader._fill()
            if reader._buf[reader._pos : reader._pos + 1] == b"*":
                return reader.read_reply()
            return reader._readline().split()

        try:
            while not self._stop.is_set():
                args = read_command()
                if not isinstance(args, list):
                    break
                if not args:  # empty inline line: ignore, like Redis
                    continue
                out.clear()
                self._dispatch([_s(a) for a in args], out)
                # Drain any further fully-buffered (pipelined) commands
                # before writing, so a pipeline costs one send.
                while reader._buf.find(b"*", reader._pos) == reader._pos:
                    try:
                        nxt = reader.read_reply()
                    except Exception:
                        break
                    self._dispatch([_s(a) for a in nxt], out)
                conn.sendall(bytes(out))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- command dispatch --------------------------------------------------
    def _dispatch(self, args: list[str], out: bytearray) -> None:
        cmd = args[0].upper()
        h = getattr(self, "_cmd_" + cmd.lower(), None)
        if h is None:
            out += f"-ERR unknown command '{cmd}'\r\n".encode()
            return
        try:
            with self.store.lock:
                h(args[1:], out)
        except Exception as e:  # command-level error, connection survives
            out += f"-ERR {type(e).__name__}: {e}\r\n".encode()

    # reply helpers
    @staticmethod
    def _int(out, n: int):
        out += b":%d\r\n" % n

    @staticmethod
    def _ok(out, s: str = "OK"):
        out += b"+" + s.encode() + b"\r\n"

    @staticmethod
    def _bulk(out, v: str | None):
        if v is None:
            out += b"$-1\r\n"
        else:
            b = v.encode()
            out += b"$%d\r\n" % len(b) + b + b"\r\n"

    @classmethod
    def _array(cls, out, items: list[str]):
        out += b"*%d\r\n" % len(items)
        for it in items:
            cls._bulk(out, it)

    # -- connection commands ----------------------------------------------
    def _cmd_ping(self, a, out):
        self._ok(out, "PONG" if not a else a[0])

    def _cmd_echo(self, a, out):
        self._bulk(out, a[0])

    def _cmd_select(self, a, out):
        self._ok(out)  # single keyspace (reference uses DB 0, redis.go:23)

    def _cmd_auth(self, a, out):
        self._ok(out)  # reference ignores the password (redis.go:20-24)

    def _cmd_flushdb(self, a, out):
        self.store.hashes.clear()
        self.store.zsets.clear()
        self._ok(out)

    # -- generic keyspace --------------------------------------------------
    def _cmd_keys(self, a, out):
        pat = a[0] if a else "*"
        self._array(
            out, [k for k in self.store.keys() if fnmatch.fnmatch(k, pat)]
        )

    def _cmd_del(self, a, out):
        n = 0
        for k in a:
            n += int(
                self.store.hashes.pop(k, None) is not None
                or self.store.zsets.pop(k, None) is not None
            )
        self._int(out, n)

    def _cmd_exists(self, a, out):
        self._int(
            out,
            sum(k in self.store.hashes or k in self.store.zsets for k in a),
        )

    # -- hashes ------------------------------------------------------------
    def _cmd_hset(self, a, out):
        key, rest = a[0], a[1:]
        if len(rest) % 2:
            raise ValueError("wrong number of arguments for HSET")
        h = self.store.hashes.setdefault(key, {})
        added = 0
        for f, v in zip(rest[::2], rest[1::2]):
            added += f not in h
            h[f] = v
        self._int(out, added)

    def _cmd_hdel(self, a, out):
        h = self.store.hashes.get(a[0])
        n = 0
        if h:
            for f in a[1:]:
                n += h.pop(f, None) is not None
            if not h:
                self.store.hashes.pop(a[0], None)
        self._int(out, n)

    def _cmd_hexists(self, a, out):
        self._int(out, int(a[1] in self.store.hashes.get(a[0], {})))

    def _cmd_hget(self, a, out):
        self._bulk(out, self.store.hashes.get(a[0], {}).get(a[1]))

    def _cmd_hgetall(self, a, out):
        h = self.store.hashes.get(a[0], {})
        flat: list[str] = []
        for f, v in h.items():
            flat += [f, v]
        self._array(out, flat)

    def _cmd_hlen(self, a, out):
        self._int(out, len(self.store.hashes.get(a[0], {})))

    def _cmd_hincrbyfloat(self, a, out):
        h = self.store.hashes.setdefault(a[0], {})
        v = float(h.get(a[1], "0")) + float(a[2])
        h[a[1]] = _fmt_float(v)
        self._bulk(out, h[a[1]])

    # -- zsets -------------------------------------------------------------
    def _cmd_zadd(self, a, out):
        z = self.store.zsets.setdefault(a[0], {})
        added = 0
        pairs = a[1:]
        for s, m in zip(pairs[::2], pairs[1::2]):
            added += m not in z
            z[m] = float(s)
        self._int(out, added)

    def _cmd_zrem(self, a, out):
        z = self.store.zsets.get(a[0], {})
        n = 0
        for m in a[1:]:
            n += z.pop(m, None) is not None
        if not z:
            self.store.zsets.pop(a[0], None)
        self._int(out, n)

    def _sorted(self, key, reverse=False):
        z = self.store.zsets.get(key, {})
        return sorted(z.items(), key=lambda kv: (kv[1], kv[0]), reverse=reverse)

    def _range_reply(self, out, items, withscores):
        flat = []
        for m, s in items:
            flat.append(m)
            if withscores:
                flat.append(_fmt_float(s))
        self._array(out, flat)

    def _cmd_zrange(self, a, out, reverse=False):
        items = self._sorted(a[0], reverse)
        start, stop = int(a[1]), int(a[2])
        n = len(items)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        withscores = any(x.upper() == "WITHSCORES" for x in a[3:])
        self._range_reply(out, items[max(start, 0) : stop + 1], withscores)

    def _cmd_zrevrange(self, a, out):
        self._cmd_zrange(a, out, reverse=True)

    def _cmd_zrangebyscore(self, a, out, reverse=False):
        if reverse:  # ZREVRANGEBYSCORE key max min
            hi, lo = _score(a[1]), _score(a[2])
        else:  # ZRANGEBYSCORE key min max
            lo, hi = _score(a[1]), _score(a[2])
        items = [
            (m, s) for m, s in self._sorted(a[0], reverse) if lo <= s <= hi
        ]
        withscores = any(x.upper() == "WITHSCORES" for x in a[3:])
        self._range_reply(out, items, withscores)

    def _cmd_zrevrangebyscore(self, a, out):
        self._cmd_zrangebyscore(a, out, reverse=True)

    def _cmd_zcard(self, a, out):
        self._int(out, len(self.store.zsets.get(a[0], {})))

    def _cmd_zscore(self, a, out):
        s = self.store.zsets.get(a[0], {}).get(a[1])
        self._bulk(out, None if s is None else _fmt_float(s))


def main(argv=None):
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    srv = FakeRedisServer(args.host, args.port)
    port = srv.start()
    print(f"READY {port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
